"""Real-client passthrough for Kafka — the analogue of the reference's
non-sim build vendoring the genuine rdkafka API
(`/root/reference/madsim-rdkafka/src/lib.rs:5-12`, `src/std/`).

Two layers:

* `probe_real_kafka(host, port)` — detects a genuine Kafka broker by
  speaking one frame of the real wire protocol (ApiVersions v0: the
  broker echoes our correlation id). The sim pickle-protocol server
  fails the handshake, so real mode can route per endpoint. Needs no
  client library.
* `RealKafkaConn` — maps the sim request enum onto the genuine
  `kafka-python` library when it is installed (producers, fetch,
  metadata, watermarks, offsets-for-time, topic creation, offset
  commit/fetch, group describe). Group *coordination* ops
  (join/sync/heartbeat/leave) raise a typed error: against a genuine
  cluster the broker's own coordinator owns that protocol, and the
  genuine client should drive it — the same division the reference
  draws by shipping the unmodified rdkafka consumer in real mode.

If a genuine broker is detected but no client library is installed, the
error says exactly that instead of silently falling back.
"""

from __future__ import annotations

import asyncio
import struct
from typing import Dict, Optional, Tuple

from . import ErrorCode, KafkaError, Message

_PROBE_CORRELATION_ID = 0x6D61_6473  # "mads"


def api_versions_frame(client_id: str = "madsim-probe") -> bytes:
    """One genuine-wire ApiVersions v0 request frame
    (api_key=18, correlation id echoed by any real broker)."""
    cid = client_id.encode()
    body = struct.pack(">hhih", 18, 0, _PROBE_CORRELATION_ID, len(cid)) + cid
    return struct.pack(">i", len(body)) + body


async def probe_real_kafka(host: str, port: int, timeout: float = 2.0) -> bool:
    """True iff a genuine Kafka broker answers the ApiVersions frame."""
    try:
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, port), timeout
        )
    except Exception:
        return False
    try:
        writer.write(api_versions_frame())
        await writer.drain()
        head = await asyncio.wait_for(reader.readexactly(8), timeout)
        (_length, correlation_id) = struct.unpack(">ii", head)
        return correlation_id == _PROBE_CORRELATION_ID
    except Exception:
        return False
    finally:
        writer.close()


def _genuine_lib():
    try:
        import kafka  # kafka-python

        return kafka
    except ImportError:
        return None


class RealKafkaConn:
    """sim request tuples -> genuine kafka-python calls (data plane)."""

    _UNSUPPORTED = {"join_group", "sync_group", "heartbeat", "leave_group"}

    def __init__(self, bootstrap: str):
        import threading

        kafka = _genuine_lib()
        if kafka is None:
            raise KafkaError(
                f"genuine Kafka broker detected at {bootstrap} but no client "
                "library is installed — `pip install kafka-python` (or point "
                "bootstrap.servers at a `python -m madsim_tpu serve --service "
                "kafka` sim-protocol server)",
                ErrorCode.INVALID_ARG,
            )
        self._kafka = kafka
        self._bootstrap = bootstrap
        self._producer = None
        self._consumers: Dict[Optional[str], object] = {}
        self._admin = None
        # kafka-python clients are NOT thread-safe; asyncio.to_thread can
        # run concurrent calls on different worker threads, so the whole
        # data plane is serialized per connection
        self._lock = threading.Lock()

    # lazily built per role; all blocking calls hop to a worker thread
    def _get_producer(self):
        if self._producer is None:
            self._producer = self._kafka.KafkaProducer(bootstrap_servers=self._bootstrap)
        return self._producer

    def _get_consumer(self, group: Optional[str] = None):
        if group not in self._consumers:
            self._consumers[group] = self._kafka.KafkaConsumer(
                bootstrap_servers=self._bootstrap,
                group_id=group,
                enable_auto_commit=False,
            )
        return self._consumers[group]

    def _get_admin(self):
        if self._admin is None:
            self._admin = self._kafka.KafkaAdminClient(bootstrap_servers=self._bootstrap)
        return self._admin

    async def call(self, req: tuple):
        kind = req[0]
        if kind in self._UNSUPPORTED:
            raise KafkaError(
                f"{kind} is sim-only: against a genuine cluster the broker "
                "coordinator owns the group protocol — use the genuine "
                "client's group consumer in production",
                ErrorCode.INVALID_ARG,
            )
        return await asyncio.to_thread(self._call_locked, kind, req)

    def _call_locked(self, kind: str, req: tuple):
        with self._lock:
            return self._call_sync(kind, req)

    def _call_sync(self, kind: str, req: tuple):
        kafka = self._kafka
        TopicPartition = kafka.TopicPartition
        if kind == "create_topic":
            from kafka.admin import NewTopic as GenuineNewTopic

            self._get_admin().create_topics(
                [GenuineNewTopic(name=req[1], num_partitions=req[2], replication_factor=1)]
            )
            return None
        if kind == "produce":
            _k, topic, partition, key, payload, ts_ms, headers = req
            fut = self._get_producer().send(
                topic, value=payload, key=key, partition=partition,
                timestamp_ms=ts_ms, headers=list(headers or []),
            )
            md = fut.get(timeout=30)
            return (md.partition, md.offset)
        if kind == "fetch":
            _k, topic, partition, offset, max_records = req
            c = self._get_consumer()
            tp = TopicPartition(topic, partition)
            c.assign([tp])
            c.seek(tp, offset)
            out = []
            polled = c.poll(timeout_ms=500, max_records=max_records)
            for recs in polled.values():
                for r in recs:
                    out.append(Message(
                        r.topic, r.partition, r.offset, r.key, r.value,
                        r.timestamp, list(r.headers or []),
                    ))
            return out
        if kind == "metadata":
            c = self._get_consumer()
            return {t: len(c.partitions_for_topic(t) or ()) for t in c.topics()}
        if kind == "watermarks":
            c = self._get_consumer()
            tp = TopicPartition(req[1], req[2])
            lo = c.beginning_offsets([tp])[tp]
            hi = c.end_offsets([tp])[tp]
            return (lo, hi)
        if kind == "offsets_for_time":
            c = self._get_consumer()
            tp = TopicPartition(req[1], req[2])
            got = c.offsets_for_times({tp: req[3]})[tp]
            return got.offset if got is not None else None
        if kind == "commit_offsets":
            from kafka.structs import OffsetAndMetadata

            group, offsets = req[1], req[2]
            c = self._get_consumer(group)
            c.commit({
                TopicPartition(t, p): OffsetAndMetadata(o, None, -1)
                for (t, p), o in dict(offsets).items()
            })
            return None
        if kind == "committed":
            c = self._get_consumer(req[1])
            return c.committed(TopicPartition(req[2], req[3]))
        if kind == "describe_group":
            infos = self._get_admin().describe_consumer_groups([req[1]])
            g = infos[0]
            return {
                "group": req[1], "state": g.state, "generation": 0,
                "members": [m.member_id for m in g.members],
            }
        raise KafkaError(f"unknown request {kind}", ErrorCode.INVALID_ARG)

    def close(self) -> None:
        with self._lock:
            if self._producer is not None:
                self._producer.close()
                self._producer = None
            for c in self._consumers.values():
                c.close()
            self._consumers.clear()
            if self._admin is not None:
                self._admin.close()
                self._admin = None
