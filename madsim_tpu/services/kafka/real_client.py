"""Real-client passthrough for Kafka — the analogue of the reference's
non-sim build vendoring the genuine rdkafka API
(`/root/reference/madsim-rdkafka/src/lib.rs:5-12`, `src/std/`). Where
the reference ships the real client library, this build implements the
actual Kafka wire protocol natively (stdlib-only — see `wire.py`), so
the passthrough has no third-party dependency at all.

Two layers:

* `probe_real_kafka(host, port)` — detects a genuine Kafka broker by
  speaking one frame of the real wire protocol (ApiVersions v0: the
  broker echoes our correlation id). The sim pickle-protocol server
  fails the handshake, so real mode can route per endpoint.
* `RealKafkaConn` — maps the sim request tuples onto genuine Kafka
  frames: Produce v3 / Fetch v4 (RecordBatch v2, headers preserved),
  Metadata, ListOffsets, CreateTopics, OffsetCommit/Fetch,
  DescribeGroups, and the classic group protocol (JoinGroup/SyncGroup/
  Heartbeat/LeaveGroup) with leader-side assignment computed
  client-side when the broker elects us leader — a complete group
  consumer, like the vendored rdkafka one in the reference. Requests
  route to partition leaders / the group coordinator via cached
  Metadata + FindCoordinator, refreshed on routing errors.
"""

from __future__ import annotations

import asyncio
import struct
from typing import Dict, List, Optional, Tuple

from . import ErrorCode, KafkaError, Message
from ...net.rpc import hash_str
from .wire import (
    ApiKey,
    Err,
    Reader,
    UnsupportedCodec,
    Writer,
    decode_assignment,
    decode_record_blob,
    decode_subscription,
    encode_assignment,
    encode_record_batch,
    encode_subscription,
)

_PROBE_CORRELATION_ID = 0x6D61_6473  # "mads"

# kafka numeric codes -> the sim's rdkafka-style codes, so app logic
# that matches on KafkaError.code behaves identically on both backends
_CODE_BACK = {
    Err.OFFSET_OUT_OF_RANGE: ErrorCode.OFFSET_OUT_OF_RANGE,
    Err.UNKNOWN_TOPIC_OR_PARTITION: ErrorCode.UNKNOWN_TOPIC_OR_PART,
    Err.MESSAGE_TOO_LARGE: ErrorCode.MSG_SIZE_TOO_LARGE,
    Err.COORDINATOR_NOT_AVAILABLE: ErrorCode.UNKNOWN_GROUP,
    Err.NOT_COORDINATOR: ErrorCode.UNKNOWN_GROUP,
    Err.ILLEGAL_GENERATION: ErrorCode.ILLEGAL_GENERATION,
    Err.UNKNOWN_MEMBER_ID: ErrorCode.UNKNOWN_MEMBER_ID,
    Err.REBALANCE_IN_PROGRESS: ErrorCode.REBALANCE_IN_PROGRESS,
    Err.TOPIC_ALREADY_EXISTS: ErrorCode.TOPIC_ALREADY_EXISTS,
    Err.INVALID_PARTITIONS: ErrorCode.INVALID_ARG,
    Err.INVALID_REQUEST: ErrorCode.INVALID_ARG,
}


def _err(code: int, what: str) -> KafkaError:
    return KafkaError(
        f"{what} failed with kafka error {code}",
        _CODE_BACK.get(code, ErrorCode.FAIL),
    )


def api_versions_frame(client_id: str = "madsim-probe") -> bytes:
    """One genuine-wire ApiVersions v0 request frame
    (api_key=18, correlation id echoed by any real broker)."""
    cid = client_id.encode()
    body = struct.pack(">hhih", 18, 0, _PROBE_CORRELATION_ID, len(cid)) + cid
    return struct.pack(">i", len(body)) + body


async def probe_real_kafka(host: str, port: int, timeout: float = 2.0) -> bool:
    """True iff a genuine Kafka broker answers the ApiVersions frame."""
    try:
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, port), timeout
        )
    except Exception:
        return False
    try:
        writer.write(api_versions_frame())
        await writer.drain()
        head = await asyncio.wait_for(reader.readexactly(8), timeout)
        (_length, correlation_id) = struct.unpack(">ii", head)
        return correlation_id == _PROBE_CORRELATION_ID
    except Exception:
        return False
    finally:
        writer.close()


class _BrokerWire:
    """One socket to one broker; request/response framing with
    correlation-id checking, serialized per connection."""

    def __init__(self, host: str, port: int, client_id: str = "madsim"):
        self.host = host
        self.port = port
        self.client_id = client_id
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._corr = 0
        self._lock = asyncio.Lock()

    async def call(self, api_key: int, version: int, body: bytes,
                   timeout: float = 30.0) -> Reader:
        async with self._lock:
            if self._writer is None:
                self._reader, self._writer = await asyncio.wait_for(
                    asyncio.open_connection(self.host, self.port), timeout
                )
            self._corr += 1
            corr = self._corr
            head = (
                Writer().i16(api_key).i16(version).i32(corr)
                .string(self.client_id).build()
            )
            frame = head + body
            self._writer.write(struct.pack(">i", len(frame)) + frame)
            try:
                await self._writer.drain()
                raw = await asyncio.wait_for(
                    self._reader.readexactly(4), timeout
                )
                (n,) = struct.unpack(">i", raw)
                rsp = await asyncio.wait_for(
                    self._reader.readexactly(n), timeout
                )
            except BaseException:  # incl. CancelledError: response is
                self.close()       # in flight; the socket must not be
                raise              # reused or pairing desyncs
            r = Reader(rsp)
            got = r.i32()
            if got != corr:
                self.close()
                raise KafkaError(
                    f"correlation mismatch: sent {corr}, got {got}",
                    ErrorCode.FAIL,
                )
            return r

    def close(self) -> None:
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass
        self._reader = self._writer = None


def _range_assign(members: Dict[str, List[str]],
                  partitions: Dict[str, int]) -> Dict[str, List[Tuple[str, int]]]:
    """Kafka's RangeAssignor (same arithmetic as the sim Broker's)."""
    out: Dict[str, List[Tuple[str, int]]] = {m: [] for m in members}
    for topic in sorted({t for ts in members.values() for t in ts}):
        n = partitions.get(topic)
        if not n:
            continue
        subs = sorted(m for m, ts in members.items() if topic in ts)
        if not subs:
            continue
        base, extra = divmod(n, len(subs))
        start = 0
        for idx, m in enumerate(subs):
            take = base + (1 if idx < extra else 0)
            out[m].extend((topic, p) for p in range(start, start + take))
            start += take
    return out


def _roundrobin_assign(members: Dict[str, List[str]],
                       partitions: Dict[str, int]) -> Dict[str, List[Tuple[str, int]]]:
    """Kafka's RoundRobinAssignor: one circular pass over all
    topic-partitions (matches Broker._rebalance)."""
    out: Dict[str, List[Tuple[str, int]]] = {m: [] for m in members}
    ms = sorted(members)
    idx = 0
    for topic in sorted({t for ts in members.values() for t in ts}):
        n = partitions.get(topic)
        if not n or not any(topic in members[m] for m in ms):
            continue
        for p in range(n):
            while topic not in members[ms[idx % len(ms)]]:
                idx += 1
            out[ms[idx % len(ms)]].append((topic, p))
            idx += 1
    return out


class RealKafkaConn:
    """sim request tuples -> genuine Kafka wire frames (stdlib only)."""

    def __init__(self, bootstrap: str):
        host, _, port = bootstrap.rpartition(":")
        self._bootstrap = (host or "127.0.0.1", int(port))
        self._conns: Dict[Tuple[str, int], _BrokerWire] = {}
        # topic -> [leader (host, port) per partition]
        self._leaders: Dict[str, List[Tuple[str, int]]] = {}
        self._coord: Dict[str, Tuple[str, int]] = {}  # group -> coordinator
        self._rr: Dict[str, int] = {}  # client-side round-robin partitioner
        # the strategy each joined group negotiated (leader-side assign)
        self._group_strategy: Dict[str, str] = {}

    # -- connection/routing -------------------------------------------------

    def _conn(self, addr: Tuple[str, int]) -> _BrokerWire:
        if addr not in self._conns:
            self._conns[addr] = _BrokerWire(*addr)
        return self._conns[addr]

    async def _refresh_metadata(self, topics: Optional[List[str]] = None) -> Dict[str, int]:
        w = Writer()
        if topics is None:
            w.i32(-1)  # v1: null array = ALL topics (empty array = none)
        else:
            w.array(topics, lambda t: w.string(t))
        r = await self._conn(self._bootstrap).call(ApiKey.METADATA, 1, w.build())
        brokers: Dict[int, Tuple[str, int]] = {}
        for _ in range(r.i32()):
            node = r.i32()
            host = r.string() or ""
            port = r.i32()
            r.string()  # rack
            brokers[node] = (host, port)
        r.i32()  # controller_id
        counts: Dict[str, int] = {}
        for _ in range(r.i32()):
            err = r.i16()
            name = r.string() or ""
            r.i8()  # is_internal
            leaders: List[Tuple[str, int]] = []
            for _p in range(r.i32()):
                r.i16()  # partition error
                pid = r.i32()
                leader = r.i32()
                r.array(r.i32)  # replicas
                r.array(r.i32)  # isr
                while len(leaders) <= pid:
                    leaders.append(self._bootstrap)
                leaders[pid] = brokers.get(leader, self._bootstrap)
            if err == Err.NONE:
                self._leaders[name] = leaders
                counts[name] = len(leaders)
        return counts

    async def _leader_conn(self, topic: str, partition: int) -> _BrokerWire:
        leaders = self._leaders.get(topic)
        if leaders is None or partition >= len(leaders):
            await self._refresh_metadata([topic])
            leaders = self._leaders.get(topic)
        if leaders is None or partition >= len(leaders):
            raise KafkaError(
                f"unknown topic: {topic}", ErrorCode.UNKNOWN_TOPIC_OR_PART
            )
        return self._conn(leaders[partition])

    async def _coord_conn(self, group: str) -> _BrokerWire:
        if group not in self._coord:
            r = await self._conn(self._bootstrap).call(
                ApiKey.FIND_COORDINATOR, 0, Writer().string(group).build()
            )
            code = r.i16()
            node = r.i32()
            host = r.string() or ""
            port = r.i32()
            if code != Err.NONE:
                raise _err(code, "FindCoordinator")
            del node
            self._coord[group] = (host, port)
        return self._conn(self._coord[group])

    async def _coord_call(self, group: str, api_key: int, version: int,
                          body: bytes) -> Reader:
        """Coordinator-routed request; a moved coordinator
        (NOT_COORDINATOR / COORDINATOR_NOT_AVAILABLE) invalidates the
        cache so the next call re-runs FindCoordinator — the group
        analogue of popping the leader cache on NOT_LEADER."""
        conn = await self._coord_conn(group)
        try:
            return await conn.call(api_key, version, body)
        except KafkaError:
            self._coord.pop(group, None)
            raise

    def _check_coord_code(self, group: str, code: int, what: str) -> None:
        if code in (Err.NOT_COORDINATOR, Err.COORDINATOR_NOT_AVAILABLE):
            self._coord.pop(group, None)
        if code != Err.NONE:
            raise _err(code, what)

    async def _pick_partition(self, topic: str, key: Optional[bytes]) -> int:
        if topic not in self._leaders:
            await self._refresh_metadata([topic])
        n = len(self._leaders.get(topic) or ())
        if n == 0:
            raise KafkaError(
                f"unknown topic: {topic}", ErrorCode.UNKNOWN_TOPIC_OR_PART
            )
        if key is not None:
            # the sim partitioner's arithmetic, for cross-mode parity
            return hash_str(key.decode("latin1")) % n
        idx = self._rr.get(topic, 0)
        self._rr[topic] = idx + 1
        return idx % n

    # -- the sim request-enum surface --------------------------------------

    async def call(self, req: tuple):
        kind = req[0]
        handler = getattr(self, f"_op_{kind}", None)
        if handler is None:
            raise KafkaError(f"unknown request {kind}", ErrorCode.INVALID_ARG)
        return await handler(req)

    async def _op_create_topic(self, req):
        _k, name, partitions = req
        w = Writer()

        def topic(item):
            w.string(item).i32(partitions).i16(1)
            w.array([], lambda a: None)  # assignments
            w.array([], lambda c: None)  # configs

        w.array([name], topic)
        w.i32(30_000)  # timeout_ms
        r = await self._conn(self._bootstrap).call(ApiKey.CREATE_TOPICS, 0, w.build())
        for _ in range(r.i32()):
            _t = r.string()
            code = r.i16()
            if code != Err.NONE:
                raise _err(code, f"CreateTopics({name})")
        self._leaders.pop(name, None)  # force a metadata refresh
        return None

    async def _op_produce(self, req):
        _k, topic, partition, key, payload, ts_ms, headers = req
        if partition is None or partition < 0:
            partition = await self._pick_partition(topic, key)
        blob = encode_record_batch([(0, key, payload, ts_ms, list(headers or []))])
        w = Writer()
        w.string(None)  # transactional_id
        w.i16(-1)  # acks=all
        w.i32(30_000)

        def topic_entry(t):
            w.string(t)

            def part(p):
                w.i32(p).bytes_(blob)

            w.array([partition], part)

        w.array([topic], topic_entry)
        conn = await self._leader_conn(topic, partition)
        r = await conn.call(ApiKey.PRODUCE, 3, w.build())
        base_offset = -1
        code = Err.NONE
        for _ in range(r.i32()):
            r.string()
            for _p in range(r.i32()):
                _pid = r.i32()
                code = r.i16()
                base_offset = r.i64()
                r.i64()  # log_append_time
        r.i32()  # throttle
        if code == Err.NOT_LEADER_FOR_PARTITION:
            self._leaders.pop(topic, None)  # stale leader cache
        if code != Err.NONE:
            raise _err(code, f"Produce({topic}[{partition}])")
        return (partition, base_offset)

    async def _op_fetch(self, req):
        _k, topic, partition, offset, max_records = req
        w = Writer()
        w.i32(-1)  # replica_id
        w.i32(100)  # max_wait_ms
        w.i32(1)  # min_bytes
        w.i32(16 * 1024 * 1024)  # max_bytes (v3+)
        w.i8(0)  # isolation_level (v4+)

        def topic_entry(t):
            w.string(t)

            def part(p):
                w.i32(p).i64(max(0, offset)).i32(16 * 1024 * 1024)

            w.array([partition], part)

        w.array([topic], topic_entry)
        conn = await self._leader_conn(topic, partition)
        r = await conn.call(ApiKey.FETCH, 4, w.build())
        r.i32()  # throttle
        out: List[Message] = []
        for _ in range(r.i32()):
            tname = r.string() or topic
            for _p in range(r.i32()):
                pid = r.i32()
                code = r.i16()
                _hw = r.i64()
                _lso = r.i64()  # last_stable_offset (v4+)
                for _a in range(max(0, r.i32())):  # aborted_transactions
                    r.i64()  # producer_id
                    r.i64()  # first_offset
                blob = r.bytes_() or b""
                if code == Err.NOT_LEADER_FOR_PARTITION:
                    self._leaders.pop(topic, None)
                if code != Err.NONE:
                    raise _err(code, f"Fetch({topic}[{partition}])")
                try:
                    records = decode_record_blob(blob)
                except UnsupportedCodec as exc:
                    raise KafkaError(
                        f"{exc} — produce with compression_type=none or gzip "
                        f"for the stdlib wire client", ErrorCode.INVALID_ARG,
                    ) from None
                for off, key, value, ts, headers in records:
                    # a batch may start before the requested offset
                    if off >= offset and len(out) < max_records:
                        out.append(Message(tname, pid, off, key, value, ts, headers))
        return out

    async def _op_metadata(self, req):
        return await self._refresh_metadata(None)

    async def _list_offsets(self, topic: str, partition: int, ts: int) -> int:
        w = Writer()
        w.i32(-1)

        def topic_entry(t):
            w.string(t)

            def part(p):
                w.i32(p).i64(ts)

            w.array([partition], part)

        w.array([topic], topic_entry)
        conn = await self._leader_conn(topic, partition)
        r = await conn.call(ApiKey.LIST_OFFSETS, 1, w.build())
        offset = -1
        for _ in range(r.i32()):
            r.string()
            for _p in range(r.i32()):
                r.i32()
                code = r.i16()
                r.i64()  # timestamp
                offset = r.i64()
                if code != Err.NONE:
                    raise _err(code, f"ListOffsets({topic}[{partition}])")
        return offset

    async def _op_watermarks(self, req):
        _k, topic, partition = req
        lo = await self._list_offsets(topic, partition, -2)
        hi = await self._list_offsets(topic, partition, -1)
        return (lo, hi)

    async def _op_offsets_for_time(self, req):
        _k, topic, partition, ts_ms = req
        off = await self._list_offsets(topic, partition, ts_ms)
        return None if off < 0 else off

    async def _op_commit_offsets(self, req):
        if len(req) > 3:  # generation-fenced commit
            _k, group, offsets, member_id, generation = req
        else:
            _k, group, offsets = req
            member_id, generation = "", -1
        by_topic: Dict[str, List[Tuple[int, int]]] = {}
        for (topic, partition), off in dict(offsets).items():
            by_topic.setdefault(topic, []).append((partition, off))
        w = Writer()
        w.string(group).i32(generation).string(member_id).i64(-1)

        def topic_entry(item):
            t, parts = item
            w.string(t)

            def part(p):
                w.i32(p[0]).i64(p[1]).string(None)

            w.array(parts, part)

        w.array(sorted(by_topic.items()), topic_entry)
        r = await self._coord_call(group, ApiKey.OFFSET_COMMIT, 2, w.build())
        for _ in range(r.i32()):
            r.string()
            for _p in range(r.i32()):
                r.i32()
                self._check_coord_code(group, r.i16(), f"OffsetCommit({group})")
        return None

    async def _op_committed(self, req):
        _k, group, topic, partition = req
        w = Writer()
        w.string(group)

        def topic_entry(t):
            w.string(t)
            w.array([partition], w.i32)

        w.array([topic], topic_entry)
        r = await self._coord_call(group, ApiKey.OFFSET_FETCH, 1, w.build())
        offset = -1
        for _ in range(r.i32()):
            r.string()
            for _p in range(r.i32()):
                r.i32()
                offset = r.i64()
                r.string()  # metadata
                self._check_coord_code(group, r.i16(), f"OffsetFetch({group})")
        return None if offset < 0 else offset

    async def _op_describe_group(self, req):
        _k, group = req
        w = Writer()
        w.array([group], lambda g: w.string(g))
        r = await self._coord_call(group, ApiKey.DESCRIBE_GROUPS, 0, w.build())
        members: Dict[str, List[str]] = {}
        assignments: Dict[str, List[Tuple[str, int]]] = {}
        strategy = ""
        for _ in range(r.i32()):
            code = r.i16()
            _g = r.string()
            state = r.string()
            _ptype = r.string()
            strategy = r.string() or ""
            for _m in range(r.i32()):
                mid = r.string() or ""
                r.string()  # client_id
                r.string()  # client_host
                meta = r.bytes_() or b""
                assign = r.bytes_() or b""
                members[mid] = decode_subscription(meta)
                assignments[mid] = decode_assignment(assign)
            self._check_coord_code(group, code, f"DescribeGroups({group})")
            if state == "Dead" and not members:
                raise KafkaError(
                    f"unknown group: {group}", ErrorCode.UNKNOWN_GROUP
                )
        # generation is not exposed by DescribeGroups v0; -1 = unknown
        return {"generation": -1, "strategy": strategy,
                "members": members, "assignments": assignments}

    # -- classic group protocol (the vendored-rdkafka capability) ----------

    async def _op_join_group(self, req):
        _k, group, member_id, topics, session_ms, strategy = req
        strategy = strategy or "range"
        w = Writer()
        w.string(group).i32(session_ms).i32(max(session_ms, 30_000))
        w.string(member_id or "").string("consumer")

        def proto(name):
            w.string(name).bytes_(encode_subscription(topics))

        w.array([strategy], proto)
        r = await self._coord_call(group, ApiKey.JOIN_GROUP, 1, w.build())
        code = r.i16()
        generation = r.i32()
        proto_name = r.string() or strategy
        leader = r.string() or ""
        mid = r.string() or ""
        member_subs: Dict[str, List[str]] = {}
        for _ in range(r.i32()):
            m = r.string() or ""
            meta = r.bytes_() or b""
            member_subs[m] = decode_subscription(meta)
        self._check_coord_code(group, code, f"JoinGroup({group})")
        self._group_strategy[group] = proto_name
        # elected leader: compute the assignment client-side and carry it
        # into sync_group (real brokers store whatever the leader sends;
        # the gateway substitutes its own — both conform)
        self._pending_leader_assign = None
        if mid == leader and member_subs:
            all_topics = sorted({t for ts in member_subs.values() for t in ts})
            await self._refresh_metadata(all_topics)
            partitions = {t: len(self._leaders.get(t) or ()) for t in all_topics}
            assign = (
                _roundrobin_assign(member_subs, partitions)
                if proto_name == "roundrobin"
                else _range_assign(member_subs, partitions)
            )
            self._pending_leader_assign = (group, generation, assign)
        return (mid, generation)

    async def _op_sync_group(self, req):
        _k, group, member_id, generation = req
        w = Writer()
        w.string(group).i32(generation).string(member_id)
        pending = getattr(self, "_pending_leader_assign", None)
        if pending and pending[0] == group and pending[1] == generation:
            assign = pending[2]

            def entry(item):
                m, parts = item
                w.string(m).bytes_(encode_assignment(parts))

            w.array(sorted(assign.items()), entry)
        else:
            w.array([], lambda a: None)
        r = await self._coord_call(group, ApiKey.SYNC_GROUP, 0, w.build())
        code = r.i16()
        blob = r.bytes_() or b""
        self._check_coord_code(group, code, f"SyncGroup({group})")
        return decode_assignment(blob)

    async def _op_heartbeat(self, req):
        _k, group, member_id, generation = req
        r = await self._coord_call(
            group, ApiKey.HEARTBEAT, 0,
            Writer().string(group).i32(generation).string(member_id).build(),
        )
        self._check_coord_code(group, r.i16(), f"Heartbeat({group})")
        return None

    async def _op_leave_group(self, req):
        _k, group, member_id = req
        r = await self._coord_call(
            group, ApiKey.LEAVE_GROUP, 0,
            Writer().string(group).string(member_id).build(),
        )
        code = r.i16()
        if code not in (Err.NONE, Err.UNKNOWN_MEMBER_ID):
            self._check_coord_code(group, code, f"LeaveGroup({group})")
        return None

    def close(self) -> None:
        for conn in self._conns.values():
            conn.close()
        self._conns.clear()
