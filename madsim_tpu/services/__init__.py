"""Simulated infrastructure services (reference: madsim-etcd-client,
madsim-rdkafka, madsim-aws-sdk-s3).

Each service is ordinary application code on top of the fabric: a
`SimServer` node speaking a request protocol over `Endpoint.connect1`,
plus a client with the real service's API shape. All chaos (latency,
partitions, node kill/restart) applies to them like to any other node.
"""

from . import etcd, kafka, s3

__all__ = ["etcd", "kafka", "s3"]
