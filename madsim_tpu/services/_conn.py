"""Broker-connection strategy shared by the kafka/s3 service clients.

Sim mode: one fresh connect1 channel per request. Channels are free in
the simulator, and a timed-out/abandoned call then abandons only its own
channel — no request/response correlation needed, concurrent callers
cannot desynchronize.

Real mode: one PERSISTENT stream guarded by a lock (the per-call pattern
would churn a real TCP connection per request — an idle consumer poll
loop alone would cycle ~100 sockets/sec into TIME_WAIT). The stream is
reopened once on failure; a cancellation mid-call (timeout) drops the
stream so a late response can never be mis-paired with the next request.

The servers already speak both shapes: their handlers loop
`while (req := await rx.recv()) is not None`, serving one connection for
one or many requests.
"""

from __future__ import annotations

from typing import Any, Optional

from ..dual import IS_SIM, net as _dual_net
from ..net.network import ConnectionReset

Endpoint = _dual_net.Endpoint


class StreamCaller:
    """`await call(req) -> response payload | None` (None = unavailable).

    `idempotent=True` marks requests safe to transparently re-send after
    a response was lost mid-flight (reads). Mutations are only retried
    when the failure happened at SEND time on a stale cached stream —
    provably before the server saw anything — never after an ambiguous
    response loss (a blind produce/create retry would silently duplicate
    the operation)."""

    def __init__(self) -> None:
        self._ep = None
        self._addr = None
        self._stream = None  # (tx, rx), real mode only
        self._lock = None

    async def open(self, addr) -> None:
        self._ep = await Endpoint.bind(("0.0.0.0", 0))
        self._addr = addr
        if not IS_SIM:
            import asyncio

            self._lock = asyncio.Lock()

    async def call(self, req: tuple, idempotent: bool = False) -> Optional[Any]:
        if IS_SIM:
            tx, rx = await self._ep.connect1(self._addr)
            try:
                tx.send(req)
                return await rx.recv()
            finally:
                tx.close()

        async with self._lock:
            try:
                # separate budgets: a send-time reconnect is provably safe
                # (nothing reached the server) and must not consume the
                # single ambiguous-loss retry an idempotent request gets
                send_retries = 1
                loss_retries = 1 if idempotent else 0
                while True:
                    if self._stream is None:
                        try:
                            self._stream = await self._ep.connect1(self._addr)
                        except (ConnectionReset, OSError):
                            # server down/refusing: "unavailable", not a
                            # raw exception out of the drop-in client API
                            if send_retries > 0:
                                send_retries -= 1
                                continue
                            return None
                    tx, rx = self._stream
                    try:
                        tx.send(req)
                    except (ConnectionReset, OSError):
                        # stale cached stream detected before anything left
                        # this process: always safe to reopen + retry
                        self._drop_stream()
                        if send_retries > 0:
                            send_retries -= 1
                            continue
                        return None
                    try:
                        rsp = await rx.recv()
                    except (ConnectionReset, OSError):
                        # OSError: socket failures the real transport does
                        # not map (ETIMEDOUT, broken pipe, ...) — same
                        # "unavailable" outcome, never a raw exception out
                        # of the drop-in client API
                        rsp = None
                    if rsp is None:
                        # request may or may not have been applied
                        self._drop_stream()
                        if loss_retries > 0:
                            loss_retries -= 1
                            continue
                        return None
                    return rsp
            except BaseException:
                # cancellation (call timeout) or unexpected error mid-call:
                # the stream may carry an unconsumed response — drop it
                self._drop_stream()
                raise

    async def open_stream(self):
        """Open a DEDICATED (tx, rx) channel to the server, outside the
        shared unary stream — for long-lived subscriptions (etcd watch/
        observe). Connect failures surface as ConnectionReset so callers
        can map them to their drop-in typed error."""
        try:
            return await self._ep.connect1(self._addr)
        except ConnectionReset:
            raise
        except OSError as e:
            raise ConnectionReset(str(e)) from e

    def _drop_stream(self) -> None:
        if self._stream is not None:
            tx, _rx = self._stream
            try:
                tx.close()
            except Exception:  # noqa: BLE001 - best-effort teardown
                pass
            self._stream = None

    def close(self) -> None:
        """Release the cached stream and the local endpoint (real mode:
        the TCP fd) — client `close()` must not leak per-backend."""
        self._drop_stream()
        if self._ep is not None:
            try:
                self._ep.close()
            except Exception:  # noqa: BLE001 - best-effort teardown
                pass
            self._ep = None
