"""select — race multiple futures, first ready wins.

The reference keeps real tokio's `select!` (deterministic given the
deterministic scheduler; madsim-tokio/src/lib.rs keeps tokio `select`).
Python has no macro, so `select` takes pollables/coroutines and returns
(index, value); coroutines are spawned as tasks and losers are aborted —
the same cancel-on-loss semantics as `select!` dropping futures.
"""

from __future__ import annotations

import inspect
from typing import Any, Tuple

from .future import PENDING, Pollable, Ready, await_


class _Race(Pollable):
    __slots__ = ("pollables",)

    def __init__(self, pollables):
        self.pollables = pollables

    def poll(self, waker):
        for i, p in enumerate(self.pollables):
            r = p.poll(waker)
            if r is not PENDING:
                return Ready((i, r.value))
        return PENDING

    def drop(self) -> None:
        for p in self.pollables:
            p.drop()


async def select(*futures: Any) -> Tuple[int, Any]:
    """Await the first of `futures` (pollables or coroutines) to finish.

    Returns (winner_index, value). Losing coroutine-tasks are aborted.
    """
    from .task import spawn

    pollables = []
    spawned = []
    for f in futures:
        if isinstance(f, Pollable):
            pollables.append(f)
        elif inspect.iscoroutine(f):
            h = spawn(f)
            spawned.append(h)
            pollables.append(h)
        else:
            raise TypeError(f"select: cannot race {type(f).__name__}")
    try:
        idx, value = await await_(_Race(pollables))
    finally:
        for h in spawned:
            if not h.is_finished():
                h.abort()
    return idx, value
