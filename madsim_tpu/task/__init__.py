"""Task spawning API (reference: madsim/src/sim/task/mod.rs public surface).

`spawn` puts a coroutine on the *current node* — the simulated process
whose task is running right now — exactly like the reference's
`task::spawn` spawning onto the current `NodeInfo`.
"""

from __future__ import annotations

import sys
from typing import Any, Coroutine, Optional

from .. import _context
from ..future import yield_now
from .executor import Executor, NodeInfo, TaskEntry, MAIN_NODE_ID
from .join import AbortHandle, JoinHandle

__all__ = [
    "spawn",
    "spawn_blocking",
    "yield_now",
    "JoinHandle",
    "AbortHandle",
    "Builder",
    "NodeId",
    "current_node_id",
]

NodeId = int


def _caller_location(depth: int = 2) -> str:
    frame = sys._getframe(depth)
    return f"{frame.f_code.co_filename}:{frame.f_lineno}"


def spawn(coro: Coroutine, *, name: str = "") -> JoinHandle:
    """Spawn a task onto the current node (reference: task::spawn)."""
    ctx = _context.current()
    node = ctx.current_task.node if ctx.current_task is not None else ctx.executor.main_node
    task = ctx.executor.spawn(coro, node, location=_caller_location(), name=name)
    return JoinHandle(task)


def spawn_blocking(fn, *args: Any) -> JoinHandle:
    """Run a sync function "blocking-style".

    In simulation everything is one thread, so this just runs `fn` inside
    a task (reference: spawn_blocking is spawn in sim mode).
    """

    async def runner():
        return fn(*args)

    ctx = _context.current()
    node = ctx.current_task.node if ctx.current_task is not None else ctx.executor.main_node
    task = ctx.executor.spawn(runner(), node, location=_caller_location(), name="blocking")
    return JoinHandle(task)


def current_node_id() -> NodeId:
    """ID of the node the current task runs on."""
    ctx = _context.current()
    if ctx.current_task is not None:
        return ctx.current_task.node.id
    return ctx.executor.main_node.id


class Builder:
    """Named-task builder (reference: sim/task/builder.rs)."""

    def __init__(self) -> None:
        self._name = ""

    def name(self, name: str) -> "Builder":
        self._name = name
        return self

    def spawn(self, coro: Coroutine) -> JoinHandle:
        ctx = _context.current()
        node = ctx.current_task.node if ctx.current_task is not None else ctx.executor.main_node
        task = ctx.executor.spawn(coro, node, location=_caller_location(), name=self._name)
        return JoinHandle(task)
