"""Task spawning API (reference: madsim/src/sim/task/mod.rs public surface).

`spawn` puts a coroutine on the *current node* — the simulated process
whose task is running right now — exactly like the reference's
`task::spawn` spawning onto the current `NodeInfo`.
"""

from __future__ import annotations

import sys
from typing import Any, Coroutine, Optional

from .. import _context
from ..future import yield_now
from .executor import Executor, NodeInfo, TaskEntry, MAIN_NODE_ID
from .join import AbortHandle, JoinHandle

__all__ = [
    "spawn",
    "spawn_blocking",
    "yield_now",
    "JoinHandle",
    "AbortHandle",
    "Builder",
    "TaskLocal",
    "NodeId",
    "current_node_id",
]

NodeId = int


def _caller_location(depth: int = 2):
    """Spawn-site key. A (filename, lineno) tuple, NOT a formatted
    string: spawns are the RPC hot path (handler-per-request), and the
    f-string format was measurable; metrics format it at report time."""
    frame = sys._getframe(depth)
    return (frame.f_code.co_filename, frame.f_lineno)


def spawn(coro: Coroutine, *, name: str = "") -> JoinHandle:
    """Spawn a task onto the current node (reference: task::spawn)."""
    ctx = _context.current()
    node = ctx.current_task.node if ctx.current_task is not None else ctx.executor.main_node
    task = ctx.executor.spawn(coro, node, location=_caller_location(), name=name)
    return JoinHandle(task)


def spawn_blocking(fn, *args: Any) -> JoinHandle:
    """Run a sync function "blocking-style".

    In simulation everything is one thread, so this just runs `fn` inside
    a task (reference: spawn_blocking is spawn in sim mode).
    """

    async def runner():
        return fn(*args)

    ctx = _context.current()
    node = ctx.current_task.node if ctx.current_task is not None else ctx.executor.main_node
    task = ctx.executor.spawn(runner(), node, location=_caller_location(), name="blocking")
    return JoinHandle(task)


def current_node_id() -> NodeId:
    """ID of the node the current task runs on."""
    ctx = _context.current()
    if ctx.current_task is not None:
        return ctx.current_task.node.id
    return ctx.executor.main_node.id


class TaskLocal:
    """Task-local storage (reference: madsim-tokio keeps tokio's
    `task_local!`; here it is provided natively).

        REQUEST_ID = TaskLocal()
        with REQUEST_ID.scope(42):
            ...  # REQUEST_ID.get() == 42 inside this task
    """

    def __init__(self) -> None:
        # weak-keyed by the TaskEntry itself: values cannot bleed into a
        # different Runtime's task that reuses an id, and entries vanish
        # with the task (no leak for tasks still in scope at teardown)
        import weakref

        self._values: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()

    class _Scope:
        def __init__(self, local: "TaskLocal", value: Any):
            self.local = local
            self.value = value
            self.task = None
            self.had_prev = False
            self.prev: Any = None

        def __enter__(self):
            self.task = _context.current_task()
            self.had_prev = self.task in self.local._values
            self.prev = self.local._values.get(self.task)
            self.local._values[self.task] = self.value
            return self.value

        def __exit__(self, *exc):
            if self.had_prev:
                self.local._values[self.task] = self.prev
            else:
                self.local._values.pop(self.task, None)

    def scope(self, value: Any) -> "TaskLocal._Scope":
        return TaskLocal._Scope(self, value)

    def get(self) -> Any:
        task = _context.current_task()
        if task not in self._values:
            raise LookupError("task-local value not set in this task")
        return self._values[task]

    def try_get(self, default: Any = None) -> Any:
        task = _context.current_task()
        return self._values.get(task, default)


class Builder:
    """Named-task builder (reference: sim/task/builder.rs)."""

    def __init__(self) -> None:
        self._name = ""

    def name(self, name: str) -> "Builder":
        self._name = name
        return self

    def spawn(self, coro: Coroutine) -> JoinHandle:
        ctx = _context.current()
        node = ctx.current_task.node if ctx.current_task is not None else ctx.executor.main_node
        task = ctx.executor.spawn(coro, node, location=_caller_location(), name=self._name)
        return JoinHandle(task)
