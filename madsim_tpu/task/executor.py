"""The deterministic single-threaded executor — heart of the host engine.

Reference parity (madsim/src/sim/task/mod.rs):
  * run-to-quiescence loop: drain the ready queue in *random order*
    (schedule chaos, :263-323 + utils/mpsc.rs:73-83 `try_recv_random`),
    then jump virtual time to the next timer
  * the clock advances a random 50-100 ns per task poll (:320), so time
    strictly progresses and timer ordering is fuzzed
  * node model: every task belongs to a `NodeInfo` (simulated process)
    with killed/paused flags; killing a node drops its futures
    (:87,:133-140); restart re-runs the stored init closure (:374-401);
    pause parks tasks until resume (:404-424)
  * a panicking task either triggers `restart_on_panic` with a random
    1-10 s backoff (:296-314) or fails the whole simulation

The entire simulation runs on ONE OS thread (reference :220-260);
concurrency is cooperative coroutines only. Multiple seeds parallelize
at the harness level (one runtime per thread/process).
"""

from __future__ import annotations

import logging
import os
from typing import Any, Callable, Coroutine, Dict, List, Optional, Set

from .. import _context
from ..errors import Deadlock, JoinError, TimeLimitExceeded
from ..future import OneShotCell
from ..rand import GlobalRng
from ..time import SEC, TimeHandle, to_ns

logger = logging.getLogger("madsim_tpu")

MAIN_NODE_ID = 1


async def _drive_awaitable(aw):
    return await aw


class NodeInfo:
    """A simulated process (reference: sim/task/mod.rs:87 `NodeInfo`)."""

    def __init__(self, node_id: int, name: str):
        self.id = node_id
        self.name = name
        self.ip: Optional[str] = None
        self.cores = 1
        self.killed = False
        self.paused = False
        self.tasks: Set["TaskEntry"] = set()
        self.paused_tasks: List["TaskEntry"] = []
        self.init: Optional[Callable[[], Coroutine]] = None
        self.restart_on_panic = False
        self.restart_on_panic_matching: Optional[Callable[[BaseException], bool]] = None
        # ctrl-c subscribers (reference: sim/task/mod.rs:106-111)
        self.ctrl_c_watchers: List[OneShotCell] = []

    def __repr__(self) -> str:  # pragma: no cover
        return f"NodeInfo(id={self.id}, name={self.name!r})"


class TaskEntry:
    """One spawned task (the Python analogue of an `async-task` Runnable)."""

    __slots__ = (
        "__weakref__",
        "id",
        "coro",
        "node",
        "name",
        "scheduled",
        "finished",
        "kill_requested",
        "cell",
        "pending_on",
        "location",
        "executor",
        "waker",
    )

    def __init__(self, task_id: int, coro: Coroutine, node: NodeInfo, executor: "Executor", location: str, name: str = ""):
        self.id = task_id
        self.coro = coro
        self.node = node
        self.name = name
        self.scheduled = False
        self.finished = False
        self.kill_requested = False
        self.cell = OneShotCell()  # (value, exc) on completion
        self.pending_on = None  # Pollable currently awaited (set by future._Await)
        self.location = location
        self.executor = executor

        mod = executor._native_mod
        if mod is not None:
            # native wake callable — also fired C-internally by timers
            self.waker = mod.TaskWaker(self, executor.ready)
        else:
            def waker(task: "TaskEntry" = self) -> None:
                if task.finished or task.scheduled:
                    return
                task.scheduled = True
                task.executor.ready.append(task)

            self.waker = waker

    def cancel(self) -> None:
        """Drop the future (reference: kill path sim/task/mod.rs:133-140)."""
        if self.finished:
            return
        if self.executor.running_task is self:
            # Cannot close a coroutine from inside itself; the executor
            # closes it as soon as this poll returns.
            self.kill_requested = True
            return
        self._close()

    def _close(self) -> None:
        self.finished = True
        try:
            self.coro.close()  # raises GeneratorExit inside -> finally blocks run
        except RuntimeError:  # pragma: no cover - coroutine ignored GeneratorExit
            logger.warning("task %s ignored cancellation", self.id)
        except Exception:  # noqa: BLE001 - errors during unwind are swallowed like Rust drop
            logger.exception("error while dropping task %s", self.id)
        self.node.tasks.discard(self)
        self.cell.set((None, JoinError("task was cancelled", cancelled=True)))

    def __repr__(self) -> str:  # pragma: no cover
        return f"TaskEntry(id={self.id}, node={self.node.id}, finished={self.finished})"


class Executor:
    """Reference: sim/task/mod.rs `Executor` + `TaskHandle`."""

    def __init__(self, rng: GlobalRng, time: TimeHandle):
        self.rng = rng
        self.time = time
        # draw-hash observation folds in the virtual clock (the native
        # twin of _context.try_time_ns in GlobalRng._record)
        if rng._core is not None and time._core is not None:
            rng._core.bind_time(time._core)
        self.ready: List[TaskEntry] = []
        self.nodes: Dict[int, NodeInfo] = {}
        self._next_node_id = MAIN_NODE_ID
        self._next_task_id = 1
        self.running_task: Optional[TaskEntry] = None
        self.panic: Optional[BaseException] = None
        self.time_limit_ns: Optional[int] = None
        self._time_limit_hit = False
        # simulator reset hooks, registered by Runtime.add_simulator
        self.reset_hooks: List[Callable[[int], None]] = []
        self.create_hooks: List[Callable[[int], None]] = []
        # task census for metrics (reference: sim/runtime/metrics.rs)
        self.spawn_counts: Dict[int, Dict[str, int]] = {}
        # Native poll loop (hostcore.run_all_ready): used when the RNG +
        # clock cores are native and the determinism log/check is off
        # (the log must observe every draw). Draw-for-draw identical to
        # the Python loop, so either path replays the other's seeds.
        from .. import _native

        self._native_mod = _native.get_mod()
        self.main_node = self.create_node("madsim-main")  # reference 0.2.34 rename

    # -- nodes --------------------------------------------------------------

    def create_node(self, name: str = "") -> NodeInfo:
        node_id = self._next_node_id
        self._next_node_id += 1
        node = NodeInfo(node_id, name or f"madsim-node-{node_id}")
        self.nodes[node_id] = node
        for hook in self.create_hooks:
            hook(node_id)
        return node

    def kill(self, node_id: int) -> None:
        """Kill a node: drop all its futures, reset simulators
        (reference: sim/task/mod.rs:356-371)."""
        node = self.nodes[node_id]
        if node_id == MAIN_NODE_ID:
            raise ValueError("cannot kill the main node")
        node.killed = True
        node.paused = False
        node.paused_tasks.clear()
        for task in list(node.tasks):
            task.cancel()
        node.tasks = {t for t in node.tasks if not t.finished}
        for hook in self.reset_hooks:
            hook(node_id)

    def restart(self, node_id: int) -> None:
        """Kill then re-run the node's init closure
        (reference: sim/task/mod.rs:374-401)."""
        if node_id == MAIN_NODE_ID:
            raise ValueError("cannot restart the main node")
        node = self.nodes[node_id]
        node.killed = True
        for task in list(node.tasks):
            task.cancel()
        for hook in self.reset_hooks:
            hook(node_id)
        node.killed = False
        node.paused = False
        node.paused_tasks.clear()
        node.ctrl_c_watchers.clear()
        if node.init is not None:
            self.spawn(node.init(), node, location="<node-init>")

    def pause(self, node_id: int) -> None:
        self.nodes[node_id].paused = True

    def resume(self, node_id: int) -> None:
        node = self.nodes[node_id]
        node.paused = False
        # Parked tasks re-enter the ready queue (still marked scheduled).
        self.ready.extend(node.paused_tasks)
        node.paused_tasks.clear()

    def send_ctrl_c(self, node_id: int) -> None:
        """Deliver ctrl-c, or kill if nobody listens
        (reference: sim/task/mod.rs:166-175,:426-441)."""
        node = self.nodes[node_id]
        if node.ctrl_c_watchers:
            watchers, node.ctrl_c_watchers = node.ctrl_c_watchers, []
            for cell in watchers:
                cell.set(None)
        else:
            self.kill(node_id)

    # -- spawning -----------------------------------------------------------

    def spawn(self, coro: Coroutine, node: NodeInfo, location: str, name: str = "") -> TaskEntry:
        if not hasattr(coro, "send"):
            # plain awaitables (e.g. the sleep future) are driven via a
            # coroutine shim — spawn accepts anything awaitable, like
            # tokio::spawn takes any Future
            coro = _drive_awaitable(coro)
        if node.killed:
            coro.close()
            task = TaskEntry(0, coro, node, self, location, name)
            task.finished = True
            task.cell.set((None, JoinError("node is killed", cancelled=True)))
            return task
        task_id = self._next_task_id
        self._next_task_id += 1
        task = TaskEntry(task_id, coro, node, self, location, name)
        node.tasks.add(task)
        self.spawn_counts.setdefault(node.id, {})
        self.spawn_counts[node.id][location] = self.spawn_counts[node.id].get(location, 0) + 1
        task.waker()
        return task

    # -- the loop -----------------------------------------------------------

    def block_on(self, main_coro: Coroutine) -> Any:
        """Reference: sim/task/mod.rs:220-260 `Executor::block_on`.

        Cyclic GC is paused for the duration of the simulation: the
        executor allocates tens of thousands of tracked objects per
        simulated second (tasks, coroutines, pendings), and generational
        scans of the live runtime graph were ~20% of host-engine wall
        time. Virtually all sim garbage is acyclic (refcount-freed
        immediately — the native core's types all carry traverse/clear
        so teardown cycles break); the allocation counters keep
        accumulating while collection is paused, so the NORMAL
        threshold-triggered collections fire in the windows between
        simulations and reclaim the rare surviving cycles (measured:
        flat RSS over thousands of back-to-back seeds). Set
        MADSIM_TPU_GC=1 to keep the collector running inside
        simulations too (e.g. single very long sims on tight memory)."""
        import gc as _gc

        gc_was_enabled = _gc.isenabled() and os.environ.get("MADSIM_TPU_GC") != "1"
        if gc_was_enabled:
            _gc.disable()
        try:
            return self._block_on_inner(main_coro)
        finally:
            if gc_was_enabled:
                _gc.enable()

    def _block_on_inner(self, main_coro: Coroutine) -> Any:
        main_task = self.spawn(main_coro, self.main_node, location="<main>")
        mod = self._native_mod
        rng = self.rng
        while True:
            if (
                mod is not None
                and rng._core is not None
                and self.time._core is not None
                and (not rng.recording or rng.native_observing)
            ):
                # the whole inner loop (drain + timer jump) runs in C;
                # in check mode the core itself hashes every draw
                # (scheduling draws included), so the loop users run is
                # the loop the check validates (VERDICT r2/r3 item)
                code = mod.drive(
                    self, _context.current(), rng._core, self.time._core, main_task
                )
            else:
                self.run_all_ready()
                if self.panic is not None:
                    code = 1
                elif main_task.finished:
                    code = 0
                elif self._time_limit_hit:
                    code = 2
                elif not self.time.advance_to_next_event():
                    code = 3
                else:
                    continue
            if code == 1:
                panic, self.panic = self.panic, None
                raise panic
            if code == 0:
                value, exc = main_task.cell.peek()
                if exc is not None:
                    raise exc
                return value
            if code == 2:
                raise TimeLimitExceeded(
                    f"time limit ({self.time_limit_ns / SEC}s) exceeded at "
                    f"t={self.time.elapsed()}s"
                )
            if code == 4:
                self.rng.raise_native_mismatch()
            raise Deadlock(
                "all tasks are blocked and no timer is pending — "
                "the simulation would block forever (deadlock)"
            )

    def run_all_ready(self) -> None:
        """Drain the ready queue in random order (reference :263-323)."""
        mod = self._native_mod
        rng = self.rng
        if (
            mod is not None
            and rng._core is not None
            and self.time._core is not None
            and (not rng.recording or rng.native_observing)
        ):
            mod.run_all_ready(self, _context.current(), rng._core, self.time._core)
            return
        ready = self.ready
        while ready:
            # try_recv_random: swap-remove a uniformly random element
            # (reference: sim/utils/mpsc.rs:73-83).
            idx = rng.gen_range(0, len(ready)) if len(ready) > 1 else 0
            task = ready[idx]
            ready[idx] = ready[-1]
            ready.pop()
            task.scheduled = False
            if task.finished or task.node.killed:
                continue
            if task.node.paused:
                task.scheduled = True
                task.node.paused_tasks.append(task)
                continue
            self._poll_task(task)
            if self.panic is not None:
                return
            # Virtual time advances 50-100 ns per poll (reference :319-321).
            self.time.advance_ns(rng.gen_range(50, 101))

    def _poll_task(self, task: TaskEntry) -> None:
        ctx = _context.current()
        prev = ctx.current_task
        ctx.current_task = task
        self.running_task = task
        try:
            task.coro.send(None)
        except StopIteration as stop:
            task.finished = True
            task.node.tasks.discard(task)
            task.cell.set((stop.value, None))
        except Exception as exc:  # noqa: BLE001 - the "panic" path
            task.finished = True
            task.node.tasks.discard(task)
            self._handle_panic(task, exc)
        finally:
            self.running_task = None
            ctx.current_task = prev
        if task.kill_requested and not task.finished:
            task.kill_requested = False
            task._close()

    def _handle_panic(self, task: TaskEntry, exc: BaseException) -> None:
        """Reference: sim/task/mod.rs:284-317 (catch_unwind + restart)."""
        node = task.node
        matcher = node.restart_on_panic_matching
        should_restart = node.restart_on_panic or (matcher is not None and matcher(exc))
        if should_restart and node.id != MAIN_NODE_ID and node.init is not None:
            delay_ns = self.rng.gen_range(1 * SEC, 10 * SEC)
            logger.warning(
                "task panicked on node %s (%s); restarting in %.3fs: %r",
                node.id, node.name, delay_ns / SEC, exc,
            )
            # Joiners of the panicked task observe a JoinError rather than
            # hanging (the task is already out of node.tasks here).
            task.cell.set((None, JoinError(f"task panicked: {exc!r}", cause=exc)))
            node.killed = True
            for t in list(node.tasks):
                t.cancel()
            for hook in self.reset_hooks:
                hook(node.id)
            node_id = node.id

            def do_restart() -> None:
                self.restart(node_id)

            self.time.add_timer_ns(self.time.now_ns() + delay_ns, do_restart)
        else:
            task.cell.set((None, exc))
            self.panic = exc

    def set_time_limit(self, duration) -> None:
        """A timer at the limit raises before any later event runs
        (reference: sim/runtime/mod.rs:148 set_time_limit)."""
        self.time_limit_ns = to_ns(duration)

        def hit() -> None:
            self._time_limit_hit = True

        self.time.add_timer_ns(self.time_limit_ns, hit)
