"""JoinHandle / AbortHandle (reference: madsim/src/sim/task/join.rs)."""

from __future__ import annotations

from typing import Any, Callable

from ..future import PENDING, Pollable, Ready
from .executor import TaskEntry


class AbortHandle:
    """Cancel a task without owning its result (join.rs `AbortHandle`)."""

    def __init__(self, task: TaskEntry):
        self._task = task

    def abort(self) -> None:
        self._task.cancel()

    def is_finished(self) -> bool:
        return self._task.finished


class JoinHandle(Pollable):
    """Awaitable handle to a spawned task (join.rs `JoinHandle`).

    Dropping it detaches the task (tokio semantics). Awaiting returns the
    task's value; a cancelled task raises `JoinError(cancelled)`.
    """

    def __init__(self, task: TaskEntry):
        self._task = task

    @property
    def id(self) -> int:
        return self._task.id

    def abort(self) -> None:
        self._task.cancel()

    def abort_handle(self) -> AbortHandle:
        return AbortHandle(self._task)

    def is_finished(self) -> bool:
        return self._task.finished

    def poll(self, waker: Callable[[], None]):
        r = self._task.cell.poll(waker)
        if r is PENDING:
            return PENDING
        value, exc = r.value
        if exc is not None:
            raise exc
        return Ready(value)

    def drop(self) -> None:
        self._task.cell.drop()

    def __await__(self):
        from ..future import await_

        return await_(self).__await__()
