"""Batched event-queue primitives — the hot ops of the TPU engine.

The host engine's binary timer heap (time/__init__.py) is replaced by a
fixed-capacity unsorted slot array per lane with vectorized argmin pop —
O(Q) work that maps onto the VPU as pure elementwise + reduction, which
beats a data-dependent heap on TPU by a wide margin. Lexicographic
(time, seq) ordering uses two masked reductions instead of a packed
64-bit key so everything stays in native int32.

Reference semantics being replicated: naive-timer pop-nearest
(madsim/src/sim/time/mod.rs:45-59) with FIFO tie-break on insertion seq.

Siblings: `step_rng.py` (the versioned per-step RNG word contract),
`pallas_pop.py` (fused pop+gather kernel), `coverage.py` (the
scenario-coverage fold the observability layer rides).

Input domain: times and seqs must be < 2**31-1 (INT32_MAX doubles as the
masking sentinel). The engine's int32 microsecond horizon and monotone
next_seq counter guarantee both by construction.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

INT32_MAX = jnp.int32(2**31 - 1)


def pop_earliest(eq_time, eq_seq, eq_valid) -> Tuple[jax.Array, jax.Array]:
    """Index of the earliest (time, seq) valid event and whether any exists.

    Per-lane shapes: eq_time int32[Q], eq_seq int32[Q], eq_valid bool[Q].
    Returns (idx, any_valid).
    """
    t_masked = jnp.where(eq_valid, eq_time, INT32_MAX)
    tmin = jnp.min(t_masked)
    tie = eq_valid & (eq_time == tmin)
    s_masked = jnp.where(tie, eq_seq, INT32_MAX)
    idx = jnp.argmin(s_masked)
    return idx, jnp.any(eq_valid)


def find_free_slot(eq_valid) -> Tuple[jax.Array, jax.Array]:
    """First free slot index and whether one exists (lane overflow check)."""
    free = ~eq_valid
    idx = jnp.argmax(free)  # first True
    return idx, jnp.any(free)
