"""Scenario-coverage fold kernel — the AFL-style map the step kernel feeds.

FoundationDB-style simulation shops treat *explored-state coverage* as
the first-class signal for when a hunt is done (thousands more seeds
finding new interleavings vs a hunt that saturated long ago); AFL keeps
that signal cheap with a fixed-size hashed hit map on the hot path. This
module is the device half of that layer for the TPU engine: every popped
event hashes (abstract-state projection, event kind, fault context) into
one slot of a per-lane uint8 saturating-count map, updated with a single
gather + scatter per lane per step (NOT a one-hot masked select — a
2^14-wide select per step would dwarf the step itself).

Slot layout is structured, not a flat hash, so the map stays *decodable*
on the host (runtime/coverage.py). Two banded layouts exist (the band
width is a LAYOUT VERSION — maps carry it, old docs keep decoding):

    v1 (3 band bits, the PR-4 layout — every config without the PR-5
        chaos kinds, so historical maps and golden slots are unchanged):
    slot = [ band:3 | phase:3 | mix:(slots_log2-6) ]

    v2 (4 band bits — selected by the engine whenever pause/skew/dup/
        strict_restart can occur, which are new configs by definition):
    slot = [ band:4 | phase:3 | mix:(slots_log2-7) ]

  * band (top bits): the popped event's class — 0 timer, 1 message,
    2.. the fault KIND of a fault event (K_PAIR..K_SKEW). v2 adds two
    synthetic bands with no event class of their own: `dup` (a step
    that enqueued at least one Bernoulli duplicate) and `amnesia` (a
    strict-restart wipe was applied). Per-band slot counts are the
    "per-fault-kind marginal coverage" signal: which chaos vocabulary
    is still finding new abstract states.
  * phase (next 3 bits): the low 3 bits of the model's
    `coverage_projection` word — each model puts its coarsest progress
    notion there (raft: term bucket; 2pc: txn index; see the models).
    (band, phase) pairs are the 64 "cells" the CLI report ranks.
  * mix: an xor-multiply hash of the full projection word, the event
    tuple discriminants and the fault-context word.

Representation: one HIT BIT per slot, packed 32 to an int32 word (the
"bit" option of AFL's bit/count family). Counts were measured and
rejected: a `uint8[lanes, 2^14]` count map cost the flagship CPU bench
~15% — the read-modify-write scatter forced XLA to materialize a copy
of the 128 MiB operand every step — while the packed-word map (16x
smaller, 2 KiB per lane) folds for free; the hit-SET, which is all the
plateau/marginal/diff consumers read, is identical by construction.

The map is monotone (bits only set), so partial maps are always subsets
of final maps and OR-reducing lanes at *every* stream harvest is
idempotent — the global vector needs no done-mask bookkeeping.

Gate discipline matches the flight recorder: `EngineConfig.coverage`
off means the lane carries `{}` and the step adds literally no ops
(asserted bit-identical in tests/test_step_gates.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import kinds as _kinds

# Default map size: 2^14 slots = 512 packed int32 words = 2 KiB per
# lane. AFL's classic 64 KiB map tracks edge pairs of real binaries;
# the engine's abstract scenario space is far smaller, and 2 KiB keeps
# the [lanes, words] block at 16 MiB for the flagship 8192-lane batch.
COV_SLOTS_LOG2_DEFAULT = 14
COV_WORD_BITS = 32  # slots per packed map word

# Band index space (top bits of the slot): event class, with fault
# events split per FaultPlan kind. Names come from madsim_tpu/kinds.py
# (runtime/coverage.py binds the same table; no jax there).
COV_BAND_BITS = 3       # layout v1 (PR-4): 8 bands
COV_BAND_BITS_V2 = 4    # layout v2 (PR-5 chaos kinds): 16 bands
COV_PHASE_BITS = 3
COV_BANDS = 1 << COV_BAND_BITS
COV_BAND_NAMES = _kinds.COV_BAND_NAMES
COV_BAND_NAMES_V2 = _kinds.COV_BAND_NAMES_V2
# v2 synthetic bands (no popped-event class of their own; the engine
# passes them via cov_slot's `band` override)
COV_BAND_DUP = 10
COV_BAND_AMNESIA = 11
# Scheduled kinds past the synthetic bands (PR-6): fault kind k >= 8
# (K_TORN, K_HEAL_ASYM) lands at band 4 + k — the 2 + k rule would
# collide with the dup/amnesia slots. Only expressible in the 4-bit
# layout; the engine forces it whenever these kinds are enabled.
COV_KIND_BAND_SHIFT_AT = 8

# mix constants: murmur3 fmix / Weyl — odd multipliers, same family as
# core.digest_fold (any single-bit input change avalanches)
_MIX_SEED = 0x9E3779B9
_MIX_M = 0x85EBCA6B


def cov_mix(words) -> jax.Array:
    """xor-multiply-xorshift fold of a list of traced scalars into one
    uint32 hash word."""
    h = jnp.uint32(_MIX_SEED)
    for w in words:
        w = jnp.asarray(w).astype(jnp.uint32)
        h = (h ^ w) * jnp.uint32(_MIX_M)
        h = h ^ (h >> 13)
    return h


def cov_band(ev_kind, op_word, band_bits: int = COV_BAND_BITS) -> jax.Array:
    """Band index of a popped event: timer 0 / msg 1 / fault 2+kind
    (apply and undo share a kind; kinds >= COV_KIND_BAND_SHIFT_AT map to
    4+kind in the 4-bit layout — past the synthetic dup/amnesia bands).
    EV_FAULT mirrored as a literal (2): engine.core imports this
    module."""
    ev_kind = jnp.asarray(ev_kind).astype(jnp.int32)
    bands = 1 << band_bits
    kind = jnp.asarray(op_word).astype(jnp.int32) // 2
    if band_bits <= COV_BAND_BITS:
        # v1 layout: the PR-4 formula, bit-exact (golden slot constants)
        fault_band = 2 + jnp.clip(kind, 0, bands - 3)
    else:
        fault_band = jnp.where(
            kind >= COV_KIND_BAND_SHIFT_AT,
            4 + jnp.clip(kind, COV_KIND_BAND_SHIFT_AT, bands - 5),
            2 + jnp.clip(kind, 0, COV_KIND_BAND_SHIFT_AT - 1),
        )
    return jnp.where(ev_kind == 2, fault_band, jnp.clip(ev_kind, 0, 1))


def cov_slot(
    abstract,
    ev_kind,
    ev_node,
    op_word,
    fault_ctx,
    slots_log2: int,
    band_bits: int = COV_BAND_BITS,
    band=None,
) -> jax.Array:
    """Map one popped event to its slot index (int32 in [0, 2^slots_log2)).

    `abstract` is the model's projection word (uint32), `op_word` the
    event discriminant (payload[0] for msg/fault events, 0 for timers —
    timer ids are epoch-encoded and would inflate slots per restart),
    `fault_ctx` the packed fault-environment word built by the step
    kernel (killed count | clog/storm/spike flags). `band_bits` picks
    the banded layout (3 = the PR-4 layout, the default so every
    historical map and golden slot constant stays valid); `band`, when
    given, overrides the event-derived band — the engine uses it for
    the v2 synthetic bands (dup / amnesia).
    """
    ev_kind = jnp.asarray(ev_kind).astype(jnp.int32)
    if band is None:
        band = cov_band(ev_kind, op_word, band_bits)
    abstract = jnp.asarray(abstract).astype(jnp.uint32)
    phase = (abstract & jnp.uint32((1 << COV_PHASE_BITS) - 1)).astype(jnp.int32)
    mix_bits = slots_log2 - band_bits - COV_PHASE_BITS
    h = cov_mix([abstract, ev_kind, ev_node, op_word, fault_ctx])
    mix = (h & jnp.uint32((1 << mix_bits) - 1)).astype(jnp.int32)
    return (band << (slots_log2 - band_bits)) | (phase << mix_bits) | mix


def cov_fold(cov_map: jax.Array, slot, hit) -> jax.Array:
    """Set slot's hit bit when `hit` (traced bool); when not, the word
    ORs in 0 — a deterministic no-op, so frozen lanes stay
    bit-identical. One word gather + one word scatter per lane per
    step, never a map-wide select."""
    w = slot >> 5
    bit = (jnp.int32(1) << (slot & 31)) * hit.astype(jnp.int32)
    return cov_map.at[w].set(cov_map[w] | bit)


# Default per-lane slot-buffer depth for the flush-on-freeze buffered
# fold (EngineConfig.cov_buffer; 0 = the unbuffered per-event scatter
# above). BENCH_r11 measured the per-event map RMW at -7.37% of step
# throughput: the scatter's operand is the whole [lanes, words] map, so
# XLA touches 2 KiB/lane every step to set one bit. Buffering the slot
# indices in a tiny int32[C] per-lane ring and folding only at the
# flush cadence / segment exit removes the map from the per-event
# program entirely — the step writes one 4-byte buffer entry instead.
# 16 entries = 64 B/lane, deep enough that the flush cadence (every
# C // slots_per_step iterations) stays a cheap segment-level event.
COV_BUFFER_DEFAULT = 16


def cov_push(buf: jax.Array, n: jax.Array, slot, hit):
    """Append `slot` to the per-lane buffer when `hit`, else write a
    masked 0 into the CURRENT tail position (same write either way —
    no divergent program). `n` counts live entries; misses don't
    advance it, so the occupied prefix [0, n) holds exactly the hit
    slots in event order. The caller guarantees n < len(buf) by
    flushing on a fixed cadence (engine.core.run_segment), so the
    clip never actually redirects a write — it is defensive bounds
    hygiene for the scatter, not an overflow policy."""
    hit_i = hit.astype(jnp.int32)
    pos = jnp.clip(n, 0, buf.shape[0] - 1)
    slot = jnp.asarray(slot).astype(jnp.int32)
    return buf.at[pos].set(slot * hit_i), n + hit_i


def cov_flush(cov_map: jax.Array, buf: jax.Array, n: jax.Array) -> jax.Array:
    """Fold the buffered slot prefix [0, n) into the packed bit map.

    An unrolled sequence of `cov_fold`s with hit = (i < n): OR is
    commutative and idempotent, so the result is bit-identical to
    having folded each slot at its original event — and a sequential
    fold (not one wide scatter) is what keeps duplicate words correct:
    a single `.at[ws].set(...)` with repeated word indices would keep
    only one of the colliding ORs. len(buf) is a small static constant
    (EngineConfig.cov_buffer), so the unroll is C tiny fused ops, paid
    once per flush instead of per event."""
    for i in range(buf.shape[0]):
        cov_map = cov_fold(cov_map, buf[i], i < n)
    return cov_map


def cov_fold_words(lane_maps: jax.Array, *, shards: int = 1) -> jax.Array:
    """OR-fold the per-lane packed maps [L, W] into the global word
    vector [W] — the `cov-map-or` collective of the stream harvest.

    `shards=1` (the unsharded path) is the plain bitwise-or reduce —
    byte-for-byte the historical fold, so single-device goldens are
    untouched by construction.

    `shards=mesh.size` (the mesh path, engine.core `_stream_fns`) is
    the same fold restructured so every CROSS-DEVICE combine uses a
    reduction computation the collective runtimes implement: an
    integer bitwise-or AllReduce is UNIMPLEMENTED on the CPU backend
    the mesh path is CI-proven on (and niche on others), while sum /
    max / boolean-or are universal. Step 1 reduces shard-locally (a
    split reshape keeps the lane axis's sharding on the leading factor,
    so the [shards, L/shards, W] -> [shards, W] or-reduce never crosses
    devices). Step 2 combines the per-shard partials bit-unpacked:
    [shards, W, 32] bool `any` over the shard dim (a boolean-or
    AllReduce), repacked by summing the disjoint single-bit words —
    bits are disjoint so the sum IS the or, exactly. The intermediates
    are [shards, W, 32] (a few KiB at any batch size): the restructured
    fold costs O(devices * words), not O(lanes).

    OR is associative/commutative/idempotent, so both forms compute
    the identical [W] vector for any lane->shard split — the
    shard-count-invariance argument tests/test_mesh.py pins."""
    if shards <= 1:
        # madsim: collective(cov-map-or, reduce=or)
        return jax.lax.reduce(
            lane_maps, jnp.int32(0), jax.lax.bitwise_or, (0,)
        )
    lanes, words = lane_maps.shape
    # madsim: collective(cov-map-or, reduce=or) — the split reshape
    # keeps the lane sharding on the leading factor; the shard-local
    # or-reduce below it never crosses devices, the bool-any combine is
    # the actual cross-chip leg
    split = lane_maps.reshape(shards, lanes // shards, words)
    part = jax.lax.reduce(split, jnp.int32(0), jax.lax.bitwise_or, (1,))
    bits = jnp.arange(COV_WORD_BITS, dtype=jnp.int32)
    hit = ((part[:, :, None] >> bits) & 1).any(axis=0)  # [W, 32] bool
    return (hit.astype(jnp.int32) << bits).sum(axis=-1, dtype=jnp.int32)


def empty_cov_map(slots_log2: int) -> jax.Array:
    """Zeroed per-lane hit map: int32[(2^slots_log2)/32] packed words
    (slot s lives in word s >> 5, bit s & 31)."""
    return jnp.zeros(((1 << slots_log2) // COV_WORD_BITS,), jnp.int32)
