"""Per-step RNG word derivation — the versioned stream contract.

Every event step consumes a block of uint32 words: handler randomness,
per-message latency draws, and (config-permitting) loss, delay-spike and
restart-key draws. Two stream versions exist; an engine's
`EngineConfig.rng_stream` picks one, and corpus entries record it so
every historical seed replays byte-identically forever (the same
versioning discipline as the v1→v2 fault-plan derivation in
`core.init_lane`).

**v2 (legacy, split-chain)** — the seed-era stream. The lane key evolves
by a 3-way `jax.random.split` every step and the block is drawn from the
step key:

    key, k_step, k_restart = split(rng_key, 3)
    words = random.bits(k_step, (W2,))        # W2 = H + (4 if delay else 2)*M

Two threefry invocations per event, and the block always carries
`2*M` latency+drop words (plus `2*M` spike words when `allow_delay`)
whether or not the config can ever use them.

**v3 (counter-based)** — one threefry invocation per event, Random123
style: the lane key is immutable and the step index IS the counter
(`LaneState.step`, already carried for termination):

    words(lane_key, step) = threefry2x32(lane_key, step*W3 + iota(W3))

`W3` is sized to what the enabled config can actually consume — drop
words only when loss is statically possible, spike words only when
delay-spike windows are statically reachable, a 2-word restart key only
when kill/restart faults are enabled. Counters are unique as long as
`step * W3 < 2**32` (~300M events/lane at W3=14 — far past any
`max_steps` in use; uniqueness degrades gracefully to reuse, never to
nondeterminism). Because `jax.random.bits(key, (n,)) ==
threefry2x32(key, iota(n))`, v3 is the natural counter-offset
generalization of the v2 block draw.

Both versions share the same block layout (`StepRngLayout`):

    [ handler H | latency M | drop M? | spike M? | spike_mag M? | restart 2? | dup 2M? | torn 1? ]

v2 always materializes the drop (and, under `allow_delay`, spike)
sections; v3 omits statically-dead sections entirely. The duplication
section (`FaultPlan.allow_dup`, PR-5: gate word + fresh-latency word per
message slot) is appended at the END of both layouts — existing section
offsets never move, so every recorded stream stays byte-stable with the
flag off. The torn-write salt section (`FaultPlan.allow_torn`, PR-6: one
word per step, folded into the torn-restart damage draw) appends after
it under the same contract. The causal-provenance gate (PR-7,
`EngineConfig.provenance`) deliberately consumes NO words in either
version — lineage words are pure dataflow over values the step already
has — so it needs no section here and provably cannot move a recorded
stream. The engine
additionally elides the *compute* that consumes a section when it is
statically inert (e.g. loss_rate==0 and no storms ⇒ the drop compare
always yields False) — that elision is result-preserving in both
versions and is independent of the stream contract.

Golden word streams for both versions are pinned as literal constants in
tests/test_golden_streams.py; any change to the functions below that
disturbs a pinned stream is a corpus-breaking event and must ship as a
new version instead.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

try:  # jax >= 0.4.16
    from jax.extend.random import threefry_2x32
except Exception:  # pragma: no cover - older jax layouts
    from jax._src.prng import threefry_2x32  # type: ignore

# The stream contract also pins the PRNG *lowering*. jax's
# `jax_threefry_partitionable` flag changes the bits jax.random.split /
# jax.random.bits produce for the SAME key, and jax has flipped its
# default across releases — the PR-3 corpus-rot investigation traced
# "all 8 corpus entries and slow-seed 66531 stopped reproducing" to
# exactly this: they were recorded under partitionable=True (the
# real-chip box's newer jax) and replayed under a False-default jax,
# which silently re-derived every lane key, fault schedule and v2 step
# block. Pinned True — the value the historical corpus was recorded
# under and the one newer jax keeps — so the streams are a function of
# the seed alone, not of the installed jax version. (The raw
# threefry_2x32 kernel v3 uses is flag-independent; the lane-key
# derivation above it is not.)
jax.config.update("jax_threefry_partitionable", True)

RNG_STREAM_LEGACY = 2
RNG_STREAM_COUNTER = 3
RNG_STREAM_VERSIONS = (RNG_STREAM_LEGACY, RNG_STREAM_COUNTER)


@dataclasses.dataclass(frozen=True)
class StepRngLayout:
    """Static word-block layout for one (config, machine) pair.

    Offsets are None when the section is not materialized in this
    stream. `loss_active` / `spike_active` are the compute-elision
    flags: a section can be materialized (v2 draws it unconditionally)
    yet statically inert."""

    version: int
    handler_words: int
    max_msgs: int
    lat_off: int
    drop_off: Optional[int]
    spike_off: Optional[int]  # gate words; magnitude words follow at +max_msgs
    restart_off: Optional[int]  # v3 only; v2 takes k_restart from the split
    total_words: int
    loss_active: bool
    spike_active: bool
    restart_active: bool
    # message-duplication section (gate words; fresh-latency words follow
    # at +max_msgs). Appended at the tail of BOTH stream versions so the
    # flag-off block is bit-identical to the pre-dup layouts.
    dup_off: Optional[int] = None
    dup_active: bool = False
    # torn-write section (PR-6, `FaultPlan.allow_torn`): ONE word per
    # step that salts the torn-restart damage draw (combined with the
    # fault payload's schedule-drawn mask). Appended after the dup
    # section at the very tail of both versions — same off-bit-stability
    # contract: no existing offset ever moves.
    torn_off: Optional[int] = None
    torn_active: bool = False


def layout_for(
    version: int,
    handler_words: int,
    max_msgs: int,
    *,
    loss_possible: bool,
    spike_possible: bool,
    delay_enabled: bool,
    restart_possible: bool,
    dup_possible: bool = False,
    torn_possible: bool = False,
) -> StepRngLayout:
    """Build the block layout. `delay_enabled` is the raw
    `FaultPlan.allow_delay` flag (v2 materializes spike words on it
    alone); `spike_possible` additionally requires n_faults > 0.
    `dup_possible` (`FaultPlan.allow_dup`) appends the duplication
    section to the tail of either version — never moves an offset —
    and `torn_possible` (`FaultPlan.allow_torn`) appends the one-word
    torn-write salt section after it, under the same contract."""
    h, m = handler_words, max_msgs
    if version == RNG_STREAM_LEGACY:
        legacy_total = h + (4 if delay_enabled else 2) * m
        dup_end = legacy_total + (2 * m if dup_possible else 0)
        return StepRngLayout(
            version=version,
            handler_words=h,
            max_msgs=m,
            lat_off=h,
            drop_off=h + m,
            spike_off=h + 2 * m if delay_enabled else None,
            restart_off=None,
            total_words=dup_end + (1 if torn_possible else 0),
            loss_active=loss_possible,
            spike_active=delay_enabled and spike_possible,
            restart_active=restart_possible,
            dup_off=legacy_total if dup_possible else None,
            dup_active=dup_possible,
            torn_off=dup_end if torn_possible else None,
            torn_active=torn_possible,
        )
    if version != RNG_STREAM_COUNTER:
        raise ValueError(f"unknown rng_stream version {version!r}")
    cursor = h + m
    drop_off = None
    if loss_possible:
        drop_off = cursor
        cursor += m
    spike_off = None
    if spike_possible:
        spike_off = cursor
        cursor += 2 * m
    restart_off = None
    if restart_possible:
        restart_off = cursor
        cursor += 2
    dup_off = None
    if dup_possible:
        dup_off = cursor
        cursor += 2 * m
    torn_off = None
    if torn_possible:
        torn_off = cursor
        cursor += 1
    return StepRngLayout(
        version=version,
        handler_words=h,
        max_msgs=m,
        lat_off=h,
        drop_off=drop_off,
        spike_off=spike_off,
        restart_off=restart_off,
        total_words=cursor,
        loss_active=loss_possible,
        spike_active=spike_possible,
        restart_active=restart_possible,
        dup_off=dup_off,
        dup_active=dup_possible,
        torn_off=torn_off,
        torn_active=torn_possible,
    )


def step_words_v2(rng_key: jax.Array, layout: StepRngLayout) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Legacy split-chain step draw.

    Returns (new_key, words[total_words], k_restart). The restart key is
    its own split — never derived from a consumed key (stream-collision
    hazard)."""
    key, k_step, k_restart = jax.random.split(rng_key, 3)
    words = jax.random.bits(k_step, (layout.total_words,), jnp.uint32)
    return key, words, k_restart


def step_words_v3(rng_key: jax.Array, step: jax.Array, layout: StepRngLayout) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Counter-based step draw: one threefry invocation per event.

    Returns (new_key, words[total_words], k_restart); new_key is the
    UNCHANGED lane key (immutable by contract). The restart key, when
    materialized, is the block's trailing 2 words."""
    w = layout.total_words
    counts = step.astype(jnp.uint32) * jnp.uint32(w) + jnp.arange(w, dtype=jnp.uint32)
    words = threefry_2x32(rng_key, counts)
    if layout.restart_off is not None:
        k_restart = words[layout.restart_off : layout.restart_off + 2]
    else:
        # restart statically unreachable: the key value is dead (the
        # restart write is masked off), any constant works
        k_restart = jnp.zeros((2,), jnp.uint32)
    return rng_key, words, k_restart


def step_words(rng_key: jax.Array, step: jax.Array, layout: StepRngLayout):
    if layout.version == RNG_STREAM_COUNTER:
        return step_words_v3(rng_key, step, layout)
    return step_words_v2(rng_key, layout)
