"""Pallas TPU kernels for the batched event-queue pop (+ fused gather).

`pop_earliest` is the per-step hot op of the TPU engine: a lexicographic
(time, seq) argmin over each lane's Q event slots. The XLA lowering is
three masked reductions; the Pallas versions fuse them into one VMEM
pass per lane block so the slot arrays are read once
(guide: /opt/skills/guides/pallas_guide.md — int32 min tile 8x128, lane
axis = slots).

Two kernels:

  * `_pop_kernel` — pop only: (idx, any_valid). The original r4 kernel.
  * `_pop_gather_kernel` — pop + the 5 follow-up gathers the step does
    with the result (`eq_time[idx]`, kind, node, src, payload[idx]) in
    the SAME VMEM pass, so the popped event tuple leaves the kernel and
    the per-lane XLA gathers disappear from the step. Payload columns
    ride as separate [L, Q] operands (restacked after the call) so every
    block stays rank-2 — Mosaic-friendly, no 3-D tiling games.

Everything is min-reductions and one-hot sums over the lane axis (argmin
is expressed as min over an index encoding; gather as a one-hot masked
sum, exact for int32) — no real gathers, no cross-lane shuffles, so the
kernels lower cleanly on Mosaic.

The engine flips the fused kernel default-ON when the backend is TPU
(`Engine.use_pallas_pop`; `MADSIM_TPU_PALLAS_POP=0/1` forces either
way). The vmapped XLA path remains the fallback and the bit-identity
oracle: both paths are asserted equal in interpreter mode for queue
capacities {32, 64} and payload widths {4, 6} (tests/test_pallas.py).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from . import pop_earliest

try:  # pallas is part of jax, but keep the engine importable without it
    from jax.experimental import pallas as pl

    HAVE_PALLAS = True
except Exception:  # pragma: no cover
    HAVE_PALLAS = False

LANE_BLOCK = 8  # lanes per grid step (int32 sublane tile)


def _lex_argmin(t, s, v):
    """Fused lexicographic argmin over the minor axis; shared by both
    kernels. Returns (idx[., 1], any[., 1] int32) with idx=0 for
    all-invalid rows (matching jnp.argmin over an all-sentinel row)."""
    q = t.shape[-1]
    # create the sentinel inside the kernel trace (module-level jnp
    # constants would be captured, which pallas_call rejects)
    big = jnp.int32(2**31 - 1)
    t_masked = jnp.where(v, t, big)
    tmin = jnp.min(t_masked, axis=-1, keepdims=True)
    tie = v & (t == tmin)
    s_masked = jnp.where(tie, s, big)
    smin = jnp.min(s_masked, axis=-1, keepdims=True)
    # argmin = smallest column index among exact (tmin, smin) matches
    cols = jax.lax.broadcasted_iota(jnp.int32, t.shape, dimension=t.ndim - 1)
    idx_enc = jnp.where(tie & (s == smin), cols, jnp.int32(q))
    idx = jnp.min(idx_enc, axis=-1, keepdims=True)
    idx = jnp.where(idx == q, 0, idx)
    any_v = jnp.any(v, axis=-1, keepdims=True).astype(jnp.int32)
    return idx, any_v, cols


def _pop_kernel(time_ref, seq_ref, valid_ref, idx_ref, any_ref):
    """One grid step: LANE_BLOCK lanes x Q slots, pop only."""
    t = time_ref[...]
    s = seq_ref[...]
    v = valid_ref[...] != 0
    idx, any_v, _ = _lex_argmin(t, s, v)
    # outputs are [LANE_BLOCK, 1]: Mosaic requires rank-1 block shapes to
    # be 128-multiples, so the lane-per-row result keeps a unit minor dim
    idx_ref[...] = idx
    any_ref[...] = any_v


def _make_pop_gather_kernel(n_vals: int):
    """Kernel popping + gathering `n_vals` extra [LB, Q] value planes
    (kind, node, src, payload columns) at the popped slot."""

    def kernel(*refs):
        time_ref, seq_ref, valid_ref = refs[:3]
        val_refs = refs[3 : 3 + n_vals]
        idx_ref, any_ref, time_out = refs[3 + n_vals : 6 + n_vals]
        val_outs = refs[6 + n_vals :]
        t = time_ref[...]
        s = seq_ref[...]
        v = valid_ref[...] != 0
        idx, any_v, cols = _lex_argmin(t, s, v)
        idx_ref[...] = idx
        any_ref[...] = any_v
        # gather-at-idx as a one-hot masked sum: exactly one column
        # matches (idx is always in [0, Q)), so the sum IS the element —
        # exact for int32, negatives included
        sel = cols == idx
        time_out[...] = jnp.sum(jnp.where(sel, t, 0), axis=-1, keepdims=True)
        for ref, out in zip(val_refs, val_outs):
            out[...] = jnp.sum(jnp.where(sel, ref[...], 0), axis=-1, keepdims=True)

    return kernel


def _pad_lanes(arrs, lanes, q):
    pad = (-lanes) % LANE_BLOCK
    if not pad:
        return arrs, lanes
    return [
        jnp.concatenate([a, jnp.zeros((pad, q), a.dtype)]) for a in arrs
    ], lanes + pad


def pop_earliest_pallas(eq_time, eq_seq, eq_valid, interpret: bool = False) -> Tuple[jax.Array, jax.Array]:
    """Batched pop over [L, Q] arrays. Returns (idx[L], any_valid[L] bool).

    Input domain: seq values must be < 2**31-1 (the sentinel). The
    engine's monotone next_seq counter guarantees this by construction;
    the XLA path shares the same constraint.
    Non-multiple-of-8 lane counts are padded with invalid rows and the
    outputs sliced back, so both paths accept arbitrary L."""
    lanes, q = eq_time.shape
    (eq_time, eq_seq, eq_valid), padded = _pad_lanes(
        [eq_time, eq_seq, eq_valid.astype(jnp.int32)], lanes, q
    )
    grid = (padded // LANE_BLOCK,)
    row_spec = pl.BlockSpec((LANE_BLOCK, q), lambda i: (i, 0))
    out_spec = pl.BlockSpec((LANE_BLOCK, 1), lambda i: (i, 0))
    idx, any_valid = pl.pallas_call(
        _pop_kernel,
        grid=grid,
        in_specs=[row_spec, row_spec, row_spec],
        out_specs=[out_spec, out_spec],
        out_shape=[
            jax.ShapeDtypeStruct((padded, 1), jnp.int32),
            jax.ShapeDtypeStruct((padded, 1), jnp.int32),
        ],
        interpret=interpret,
    )(eq_time, eq_seq, eq_valid)
    return idx[:lanes, 0], any_valid[:lanes, 0] != 0


def pop_gather_pallas(
    eq_time, eq_seq, eq_valid, eq_kind, eq_node, eq_src, eq_payload,
    interpret: bool = False,
):
    """Fused pop + gather over [L, Q] (+ payload [L, Q, P]) arrays.

    Returns (idx[L], any_valid[L] bool, (time[L], kind[L], node[L],
    src[L], payload[L, P])) — the popped event tuple, bit-identical to
    the XLA path's `arr[lane, idx[lane]]` gathers (all-invalid lanes
    gather slot 0 on both paths)."""
    lanes, q = eq_time.shape
    p = eq_payload.shape[-1]
    vals = [eq_kind, eq_node, eq_src] + [eq_payload[:, :, j] for j in range(p)]
    ins, padded = _pad_lanes(
        [eq_time, eq_seq, eq_valid.astype(jnp.int32)] + vals, lanes, q
    )
    grid = (padded // LANE_BLOCK,)
    row_spec = pl.BlockSpec((LANE_BLOCK, q), lambda i: (i, 0))
    out_spec = pl.BlockSpec((LANE_BLOCK, 1), lambda i: (i, 0))
    n_vals = len(vals)
    n_out = 3 + n_vals  # idx, any, time, then the value planes
    outs = pl.pallas_call(
        _make_pop_gather_kernel(n_vals),
        grid=grid,
        in_specs=[row_spec] * (3 + n_vals),
        out_specs=[out_spec] * n_out,
        out_shape=[jax.ShapeDtypeStruct((padded, 1), jnp.int32)] * n_out,
        interpret=interpret,
    )(*ins)
    outs = [o[:lanes, 0] for o in outs]
    idx, any_valid, ev_time, ev_kind, ev_node, ev_src = outs[:6]
    ev_payload = jnp.stack(outs[6:], axis=-1)
    return idx, any_valid != 0, (ev_time, ev_kind, ev_node, ev_src, ev_payload)


def pop_earliest_batch(eq_time, eq_seq, eq_valid, use_pallas: bool = False, interpret: bool = False):
    """Reference implementation (vmapped XLA) or the fused Pallas kernel."""
    if use_pallas and HAVE_PALLAS:
        return pop_earliest_pallas(eq_time, eq_seq, eq_valid, interpret=interpret)
    return jax.vmap(pop_earliest)(eq_time, eq_seq, eq_valid)


def pop_gather_batch(
    eq_time, eq_seq, eq_valid, eq_kind, eq_node, eq_src, eq_payload,
    use_pallas: bool = False, interpret: bool = False,
):
    """Pop + gather the popped event tuple: the fused Pallas kernel, or
    the vmapped-XLA reference (pop + take_along_axis gathers). Both
    return (idx, any_valid, (time, kind, node, src, payload)) with
    bit-identical values."""
    if use_pallas and HAVE_PALLAS:
        return pop_gather_pallas(
            eq_time, eq_seq, eq_valid, eq_kind, eq_node, eq_src, eq_payload,
            interpret=interpret,
        )
    idx, any_valid = jax.vmap(pop_earliest)(eq_time, eq_seq, eq_valid)

    def take(a):
        return jnp.take_along_axis(a, idx[:, None], axis=1)[:, 0]

    ev_payload = jnp.take_along_axis(
        eq_payload, idx[:, None, None], axis=1
    )[:, 0, :]
    return idx, any_valid, (
        take(eq_time), take(eq_kind), take(eq_node), take(eq_src), ev_payload
    )
