"""Pallas TPU kernel for the batched event-queue pop.

`pop_earliest` is the per-step hot op of the TPU engine: a lexicographic
(time, seq) argmin over each lane's Q event slots. The XLA lowering is
three masked reductions; this Pallas version fuses them into one VMEM
pass per lane block so the slot arrays are read once
(guide: /opt/skills/guides/pallas_guide.md — int32 min tile 8x128, lane
axis = slots).

Everything is min-reductions over the lane axis (argmin is expressed as
min over an index encoding) — no gathers, no cross-lane shuffles, so the
kernel lowers cleanly on Mosaic. Until real-chip profiles justify
flipping the default, the engine keeps the XLA path; this kernel is
validated against it bit-for-bit in interpreter mode
(tests/test_pallas.py) and via `pop_earliest_batch(..., use_pallas=True)`.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from . import pop_earliest

try:  # pallas is part of jax, but keep the engine importable without it
    from jax.experimental import pallas as pl

    HAVE_PALLAS = True
except Exception:  # pragma: no cover
    HAVE_PALLAS = False

LANE_BLOCK = 8  # lanes per grid step (int32 sublane tile)


def _pop_kernel(time_ref, seq_ref, valid_ref, idx_ref, any_ref):
    """One grid step: LANE_BLOCK lanes x Q slots, fused lexicographic argmin."""
    t = time_ref[...]
    s = seq_ref[...]
    v = valid_ref[...] != 0
    q = t.shape[-1]
    # create the sentinel inside the kernel trace (module-level jnp
    # constants would be captured, which pallas_call rejects)
    big = jnp.int32(2**31 - 1)

    t_masked = jnp.where(v, t, big)
    tmin = jnp.min(t_masked, axis=-1, keepdims=True)
    tie = v & (t == tmin)
    s_masked = jnp.where(tie, s, big)
    smin = jnp.min(s_masked, axis=-1, keepdims=True)
    # argmin = smallest column index among exact (tmin, smin) matches
    cols = jax.lax.broadcasted_iota(jnp.int32, t.shape, dimension=t.ndim - 1)
    idx_enc = jnp.where(tie & (s == smin), cols, jnp.int32(q))
    idx = jnp.min(idx_enc, axis=-1, keepdims=True)
    # outputs are [LANE_BLOCK, 1]: Mosaic requires rank-1 block shapes to
    # be 128-multiples, so the lane-per-row result keeps a unit minor dim
    idx_ref[...] = jnp.where(idx == q, 0, idx)
    any_ref[...] = jnp.any(v, axis=-1, keepdims=True).astype(jnp.int32)


def pop_earliest_pallas(eq_time, eq_seq, eq_valid, interpret: bool = False) -> Tuple[jax.Array, jax.Array]:
    """Batched pop over [L, Q] arrays. Returns (idx[L], any_valid[L] bool).

    Input domain: seq values must be < 2**31-1 (the sentinel). The
    engine's monotone next_seq counter guarantees this by construction;
    the XLA path shares the same constraint.
    Non-multiple-of-8 lane counts are padded with invalid rows and the
    outputs sliced back, so both paths accept arbitrary L."""
    lanes, q = eq_time.shape
    pad = (-lanes) % LANE_BLOCK
    if pad:
        eq_time = jnp.concatenate([eq_time, jnp.zeros((pad, q), eq_time.dtype)])
        eq_seq = jnp.concatenate([eq_seq, jnp.zeros((pad, q), eq_seq.dtype)])
        eq_valid = jnp.concatenate([eq_valid, jnp.zeros((pad, q), bool)])
    padded = lanes + pad
    grid = (padded // LANE_BLOCK,)
    row_spec = pl.BlockSpec((LANE_BLOCK, q), lambda i: (i, 0))
    out_spec = pl.BlockSpec((LANE_BLOCK, 1), lambda i: (i, 0))
    idx, any_valid = pl.pallas_call(
        _pop_kernel,
        grid=grid,
        in_specs=[row_spec, row_spec, row_spec],
        out_specs=[out_spec, out_spec],
        out_shape=[
            jax.ShapeDtypeStruct((padded, 1), jnp.int32),
            jax.ShapeDtypeStruct((padded, 1), jnp.int32),
        ],
        interpret=interpret,
    )(eq_time, eq_seq, eq_valid.astype(jnp.int32))
    return idx[:lanes, 0], any_valid[:lanes, 0] != 0


def pop_earliest_batch(eq_time, eq_seq, eq_valid, use_pallas: bool = False, interpret: bool = False):
    """Reference implementation (vmapped XLA) or the fused Pallas kernel."""
    if use_pallas and HAVE_PALLAS:
        return pop_earliest_pallas(eq_time, eq_seq, eq_valid, interpret=interpret)
    return jax.vmap(pop_earliest)(eq_time, eq_seq, eq_valid)
