"""Pallas TPU kernels for the batched event-queue pop (+ fused step prefix).

`pop_earliest` is the per-step hot op of the TPU engine: a lexicographic
(time, seq) argmin over each lane's Q event slots. The XLA lowering is
three masked reductions; the Pallas versions fuse them into one VMEM
pass per lane block so the slot arrays are read once
(guide: /opt/skills/guides/pallas_guide.md — int32 min tile 8x128, lane
axis = slots).

Three kernels:

  * `_pop_kernel` — pop only: (idx, any_valid). The original r4 kernel.
  * `_pop_gather_kernel` — pop + the 5 follow-up gathers the step does
    with the result (`eq_time[idx]`, kind, node, src, payload[idx]) in
    the SAME VMEM pass, so the popped event tuple leaves the kernel and
    the per-lane XLA gathers disappear from the step. Payload columns
    ride as separate [L, Q] operands (restacked after the call) so every
    block stays rank-2 — Mosaic-friendly, no 3-D tiling games.
  * the STEP MEGAKERNEL (`step_megakernel`, r11) — the whole
    model-independent prefix of the step in ONE VMEM pass per lane
    block: lexicographic-argmin pop → popped-tuple gather → the
    counter-based v3 RNG word block (an in-kernel Threefry-2x32,
    bit-exact vs jax's `threefry_2x32` primitive — the stream contract)
    → when the flight recorder is on, the whole digest fold over the
    popped tuple + word block. The queue planes are read once and the
    RNG block + digest never round-trip through HBM between step
    stages. What stays in XLA: handler dispatch (machine code is
    arbitrary JAX — the Machine contract), fault-branch state writes,
    outbox pushes and the coverage slot hash (it needs the POST-step
    model projection). `Engine.use_megakernel` / `EngineConfig.
    pallas_megakernel` gates it (default-ON only on TPU, requires
    `rng_stream=3`); the XLA path remains the bit-identity oracle
    everywhere (interpreter-mode equivalence over the Q/P grid in
    tests/test_pallas.py + end-to-end in tests/test_step_gates.py).

Everything is min-reductions, one-hot sums and elementwise ARX rounds
over the lane axis (argmin is expressed as min over an index encoding;
gather as a one-hot masked sum, exact for int32) — no real gathers, no
cross-lane shuffles, so the kernels lower cleanly on Mosaic.

The engine flips the fused kernels default-ON when the backend is TPU
(`Engine.use_pallas_pop` / `Engine.use_megakernel`;
`MADSIM_TPU_PALLAS_POP=0/1` and `MADSIM_TPU_PALLAS_MEGAKERNEL=0/1`
force either way). The vmapped XLA path remains the fallback and the
bit-identity oracle: both paths are asserted equal in interpreter mode
for queue capacities {32, 64} and payload widths {4, 6}
(tests/test_pallas.py).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from . import pop_earliest

try:  # pallas is part of jax, but keep the engine importable without it
    from jax.experimental import pallas as pl

    HAVE_PALLAS = True
except Exception:  # pragma: no cover
    HAVE_PALLAS = False

LANE_BLOCK = 8  # lanes per grid step (int32 sublane tile)


def _lex_argmin(t, s, v):
    """Fused lexicographic argmin over the minor axis; shared by both
    kernels. Returns (idx[., 1], any[., 1] int32) with idx=0 for
    all-invalid rows (matching jnp.argmin over an all-sentinel row)."""
    q = t.shape[-1]
    # create the sentinel inside the kernel trace (module-level jnp
    # constants would be captured, which pallas_call rejects)
    big = jnp.int32(2**31 - 1)
    t_masked = jnp.where(v, t, big)
    tmin = jnp.min(t_masked, axis=-1, keepdims=True)
    tie = v & (t == tmin)
    s_masked = jnp.where(tie, s, big)
    smin = jnp.min(s_masked, axis=-1, keepdims=True)
    # argmin = smallest column index among exact (tmin, smin) matches
    cols = jax.lax.broadcasted_iota(jnp.int32, t.shape, dimension=t.ndim - 1)
    idx_enc = jnp.where(tie & (s == smin), cols, jnp.int32(q))
    idx = jnp.min(idx_enc, axis=-1, keepdims=True)
    idx = jnp.where(idx == q, 0, idx)
    any_v = jnp.any(v, axis=-1, keepdims=True).astype(jnp.int32)
    return idx, any_v, cols


def _pop_kernel(time_ref, seq_ref, valid_ref, idx_ref, any_ref):
    """One grid step: LANE_BLOCK lanes x Q slots, pop only."""
    t = time_ref[...]
    s = seq_ref[...]
    v = valid_ref[...] != 0
    idx, any_v, _ = _lex_argmin(t, s, v)
    # outputs are [LANE_BLOCK, 1]: Mosaic requires rank-1 block shapes to
    # be 128-multiples, so the lane-per-row result keeps a unit minor dim
    idx_ref[...] = idx
    any_ref[...] = any_v


def _make_pop_gather_kernel(n_vals: int):
    """Kernel popping + gathering `n_vals` extra [LB, Q] value planes
    (kind, node, src, payload columns) at the popped slot."""

    def kernel(*refs):
        time_ref, seq_ref, valid_ref = refs[:3]
        val_refs = refs[3 : 3 + n_vals]
        idx_ref, any_ref, time_out = refs[3 + n_vals : 6 + n_vals]
        val_outs = refs[6 + n_vals :]
        t = time_ref[...]
        s = seq_ref[...]
        v = valid_ref[...] != 0
        idx, any_v, cols = _lex_argmin(t, s, v)
        idx_ref[...] = idx
        any_ref[...] = any_v
        # gather-at-idx as a one-hot masked sum: exactly one column
        # matches (idx is always in [0, Q)), so the sum IS the element —
        # exact for int32, negatives included
        sel = cols == idx
        time_out[...] = jnp.sum(jnp.where(sel, t, 0), axis=-1, keepdims=True)
        for ref, out in zip(val_refs, val_outs):
            out[...] = jnp.sum(jnp.where(sel, ref[...], 0), axis=-1, keepdims=True)

    return kernel


def _pad_lanes(arrs, lanes, q=None):
    """Pad the lane (major) axis of each [L, *] operand to a LANE_BLOCK
    multiple with zero rows (each operand keeps its own minor width —
    the megakernel mixes [L, Q] queue planes with [L, 1] per-lane
    scalars). `q` is accepted for backward compatibility and ignored."""
    pad = (-lanes) % LANE_BLOCK
    if not pad:
        return arrs, lanes
    return [
        jnp.concatenate([a, jnp.zeros((pad, a.shape[1]), a.dtype)])
        for a in arrs
    ], lanes + pad


def pop_earliest_pallas(eq_time, eq_seq, eq_valid, interpret: bool = False) -> Tuple[jax.Array, jax.Array]:
    """Batched pop over [L, Q] arrays. Returns (idx[L], any_valid[L] bool).

    Input domain: seq values must be < 2**31-1 (the sentinel). The
    engine's monotone next_seq counter guarantees this by construction;
    the XLA path shares the same constraint.
    Non-multiple-of-8 lane counts are padded with invalid rows and the
    outputs sliced back, so both paths accept arbitrary L."""
    lanes, q = eq_time.shape
    (eq_time, eq_seq, eq_valid), padded = _pad_lanes(
        [eq_time, eq_seq, eq_valid.astype(jnp.int32)], lanes, q
    )
    grid = (padded // LANE_BLOCK,)
    row_spec = pl.BlockSpec((LANE_BLOCK, q), lambda i: (i, 0))
    out_spec = pl.BlockSpec((LANE_BLOCK, 1), lambda i: (i, 0))
    idx, any_valid = pl.pallas_call(
        _pop_kernel,
        grid=grid,
        in_specs=[row_spec, row_spec, row_spec],
        out_specs=[out_spec, out_spec],
        out_shape=[
            jax.ShapeDtypeStruct((padded, 1), jnp.int32),
            jax.ShapeDtypeStruct((padded, 1), jnp.int32),
        ],
        interpret=interpret,
    )(eq_time, eq_seq, eq_valid)
    return idx[:lanes, 0], any_valid[:lanes, 0] != 0


def pop_gather_pallas(
    eq_time, eq_seq, eq_valid, eq_kind, eq_node, eq_src, eq_payload,
    interpret: bool = False,
):
    """Fused pop + gather over [L, Q] (+ payload [L, Q, P]) arrays.

    Returns (idx[L], any_valid[L] bool, (time[L], kind[L], node[L],
    src[L], payload[L, P])) — the popped event tuple, bit-identical to
    the XLA path's `arr[lane, idx[lane]]` gathers (all-invalid lanes
    gather slot 0 on both paths)."""
    lanes, q = eq_time.shape
    p = eq_payload.shape[-1]
    vals = [eq_kind, eq_node, eq_src] + [eq_payload[:, :, j] for j in range(p)]
    ins, padded = _pad_lanes(
        [eq_time, eq_seq, eq_valid.astype(jnp.int32)] + vals, lanes, q
    )
    grid = (padded // LANE_BLOCK,)
    row_spec = pl.BlockSpec((LANE_BLOCK, q), lambda i: (i, 0))
    out_spec = pl.BlockSpec((LANE_BLOCK, 1), lambda i: (i, 0))
    n_vals = len(vals)
    n_out = 3 + n_vals  # idx, any, time, then the value planes
    outs = pl.pallas_call(
        _make_pop_gather_kernel(n_vals),
        grid=grid,
        in_specs=[row_spec] * (3 + n_vals),
        out_specs=[out_spec] * n_out,
        out_shape=[jax.ShapeDtypeStruct((padded, 1), jnp.int32)] * n_out,
        interpret=interpret,
    )(*ins)
    outs = [o[:lanes, 0] for o in outs]
    idx, any_valid, ev_time, ev_kind, ev_node, ev_src = outs[:6]
    ev_payload = jnp.stack(outs[6:], axis=-1)
    return idx, any_valid != 0, (ev_time, ev_kind, ev_node, ev_src, ev_payload)


# -- the whole-event step megakernel (r11) -----------------------------------

# Threefry-2x32 rotation schedule + key-schedule parity constant — the
# Random123 algorithm exactly as jax's `threefry2x32` primitive unrolls
# it, so the in-kernel word block is bit-identical to `jax.extend.
# random.threefry_2x32` (tests/test_pallas.py pins the equivalence over
# keys/counters; the golden v3 stream constants pin it transitively).
_TF_ROT = ((13, 15, 26, 6), (17, 29, 16, 24))
_TF_PARITY = 0x1BD11BDA


def threefry2x32_pair(k0, k1, x0, x1):
    """Threefry-2x32 on paired uint32 operands (any broadcastable
    shape): 20 ARX rounds with the key schedule injected every 4.
    Elementwise only — traces inside a Pallas kernel and in plain XLA
    identically; both must (and do) match jax's fused primitive
    bit-for-bit."""
    ks = (k0, k1, k0 ^ k1 ^ jnp.uint32(_TF_PARITY))
    x0 = x0 + ks[0]
    x1 = x1 + ks[1]
    for i in range(5):
        for r in _TF_ROT[i % 2]:
            x0 = x0 + x1
            x1 = (x1 << r) | (x1 >> (32 - r))
            x1 = x0 ^ x1
        x0 = x0 + ks[(i + 1) % 3]
        x1 = x1 + ks[(i + 2) % 3] + jnp.uint32(i + 1)
    return x0, x1


def step_rng_words_fused(k0, k1, step_u32, total_words: int):
    """The v3 counter-based word block, computed from [·, 1] per-lane
    key halves + step counters as one batched Threefry-2x32 call —
    bit-identical to `ops.step_rng.step_words_v3` (which routes through
    jax's primitive, including its odd-length pad-with-zero-then-split
    packing; replicated here exactly)."""
    w = total_words
    half = (w + 1) // 2
    wp = 2 * half
    lb = step_u32.shape[0]
    base = step_u32 * jnp.uint32(w)
    i0 = jax.lax.broadcasted_iota(jnp.uint32, (lb, half), 1)
    c0 = base + i0
    i1 = i0 + jnp.uint32(half)
    # odd block: jax pads the counter vector with one trailing zero
    # before splitting — the pad position's COUNT is 0, not step·w+w
    c1 = jnp.where(i1 < jnp.uint32(w), base + i1, jnp.uint32(0)) \
        if wp != w else base + i1
    y0, y1 = threefry2x32_pair(k0, k1, c0, c1)
    words = jnp.concatenate([y0, y1], axis=-1)
    return words[:, :w] if wp != w else words


def _make_step_kernel(n_vals: int, total_words: int, digest_fold=None):
    """The megakernel body: pop + gather `n_vals` planes + the v3 RNG
    block, plus (when `digest_fold` — the engine's fold callable — is
    given) the flight-recorder digest over exactly the words the XLA
    path folds: popped tuple, payload columns, then the word block."""

    def kernel(*refs):
        time_ref, seq_ref, valid_ref = refs[:3]
        val_refs = refs[3 : 3 + n_vals]
        pos = 3 + n_vals
        k0_ref, k1_ref, step_ref = refs[pos : pos + 3]
        pos += 3
        if digest_fold is not None:
            d0_ref, d1_ref = refs[pos : pos + 2]
            pos += 2
        outs = refs[pos:]
        idx_ref, any_ref, time_out = outs[:3]
        val_outs = outs[3 : 3 + n_vals]
        words_out = outs[3 + n_vals]
        t = time_ref[...]
        s = seq_ref[...]
        v = valid_ref[...] != 0
        idx, any_v, cols = _lex_argmin(t, s, v)
        idx_ref[...] = idx
        any_ref[...] = any_v
        sel = cols == idx
        ev_time = jnp.sum(jnp.where(sel, t, 0), axis=-1, keepdims=True)
        time_out[...] = ev_time
        vals = []
        for ref, out in zip(val_refs, val_outs):
            val = jnp.sum(jnp.where(sel, ref[...], 0), axis=-1, keepdims=True)
            out[...] = val
            vals.append(val)
        words = step_rng_words_fused(
            k0_ref[...], k1_ref[...], step_ref[...], total_words
        )
        words_out[...] = words
        if digest_fold is not None:
            nd0, nd1 = digest_fold(
                d0_ref[...],
                d1_ref[...],
                [ev_time] + vals
                + [words[:, i : i + 1] for i in range(total_words)],
            )
            outs[4 + n_vals][...] = nd0
            outs[5 + n_vals][...] = nd1

    return kernel


def step_megakernel(
    eq_time, eq_seq, eq_valid, eq_kind, eq_node, eq_src, eq_payload,
    rng_key, step, total_words: int,
    d0=None, d1=None, digest_fold=None,
    interpret: bool = False,
):
    """One VMEM pass per lane block: pop + gather + the v3 RNG word
    block (+ the digest fold when `d0`/`d1`/`digest_fold` are given).

    `rng_key` is the [L, 2] uint32 immutable v3 lane key, `step` the
    int32 step counter. Returns `(idx[L], any_valid[L] bool,
    (time, kind, node, src, payload[L, P]), words[L, W] uint32,
    digest)` where digest is `(nd0[L], nd1[L])` under the recorder and
    `()` without it — every value bit-identical to the XLA path
    (`pop_gather_batch` + `step_words_v3` + `core.digest_fold`)."""
    lanes, q = eq_time.shape
    p = eq_payload.shape[-1]
    with_digest = digest_fold is not None
    vals = [eq_kind, eq_node, eq_src] + [eq_payload[:, :, j] for j in range(p)]
    scalars = [
        rng_key[:, :1].astype(jnp.uint32),
        rng_key[:, 1:].astype(jnp.uint32),
        step[:, None].astype(jnp.uint32),
    ]
    if with_digest:
        scalars += [d0[:, None].astype(jnp.uint32), d1[:, None].astype(jnp.uint32)]
    ins, padded = _pad_lanes(
        [eq_time, eq_seq, eq_valid.astype(jnp.int32)] + vals + scalars, lanes
    )
    grid = (padded // LANE_BLOCK,)
    row_spec = pl.BlockSpec((LANE_BLOCK, q), lambda i: (i, 0))
    one_spec = pl.BlockSpec((LANE_BLOCK, 1), lambda i: (i, 0))
    words_spec = pl.BlockSpec((LANE_BLOCK, total_words), lambda i: (i, 0))
    n_vals = len(vals)
    out_specs = [one_spec] * (3 + n_vals) + [words_spec]
    out_shape = [jax.ShapeDtypeStruct((padded, 1), jnp.int32)] * (3 + n_vals) + [
        jax.ShapeDtypeStruct((padded, total_words), jnp.uint32)
    ]
    if with_digest:
        out_specs += [one_spec, one_spec]
        out_shape += [jax.ShapeDtypeStruct((padded, 1), jnp.uint32)] * 2
    in_specs = [row_spec] * (3 + n_vals) + [one_spec] * len(scalars)
    outs = pl.pallas_call(
        _make_step_kernel(n_vals, total_words, digest_fold if with_digest else None),
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(*ins)
    idx, any_valid, ev_time = (o[:lanes, 0] for o in outs[:3])
    val_cols = [o[:lanes, 0] for o in outs[3 : 3 + n_vals]]
    ev_kind, ev_node, ev_src = val_cols[:3]
    ev_payload = jnp.stack(val_cols[3:], axis=-1)
    words = outs[3 + n_vals][:lanes]
    digest = (
        (outs[4 + n_vals][:lanes, 0], outs[5 + n_vals][:lanes, 0])
        if with_digest
        else ()
    )
    return (
        idx, any_valid != 0,
        (ev_time, ev_kind, ev_node, ev_src, ev_payload),
        words, digest,
    )


# -- buffered-coverage flush kernel (r12) ------------------------------------
#
# The flush-on-freeze buffered coverage path (EngineConfig.cov_buffer)
# moved the per-event map scatter out of the step; what remains is a
# per-segment fold of each lane's int32[C] slot buffer into its
# int32[W] packed bit map. The coverage SLOT HASH still cannot join the
# megakernel (it needs the POST-step model projection — see the module
# docstring), so the Pallas treatment lands here instead: one VMEM pass
# per lane block ORing every buffered entry's one-hot word into the
# map. One-hot-over-words is the same trick the gather kernels use in
# reverse, and OR is order-independent, so the kernel is bit-identical
# to the sequential `coverage.cov_flush` oracle by construction
# (asserted over the C/W grid in tests/test_pallas.py).


def _make_cov_flush_kernel(n_entries: int):
    def kernel(map_ref, buf_ref, n_ref, out_ref):
        m = map_ref[...]
        buf = buf_ref[...]
        n = n_ref[...]  # [LB, 1] live-entry counts
        cols = jax.lax.broadcasted_iota(jnp.int32, m.shape, dimension=1)
        for i in range(n_entries):
            slot = buf[:, i : i + 1]
            hit = (jnp.int32(i) < n).astype(jnp.int32)
            bit = (jnp.int32(1) << (slot & 31)) * hit
            m = m | jnp.where(cols == (slot >> 5), bit, 0)
        out_ref[...] = m

    return kernel


def cov_flush_pallas(cov_map, buf, n, interpret: bool = False):
    """Fold [L, C] buffered slot indices (live prefix per `n[L]`) into
    the [L, W] packed bit maps in one VMEM pass per lane block."""
    lanes, w = cov_map.shape
    c = buf.shape[1]
    ins, padded = _pad_lanes(
        [cov_map, buf, n[:, None].astype(jnp.int32)], lanes
    )
    grid = (padded // LANE_BLOCK,)
    out = pl.pallas_call(
        _make_cov_flush_kernel(c),
        grid=grid,
        in_specs=[
            pl.BlockSpec((LANE_BLOCK, w), lambda i: (i, 0)),
            pl.BlockSpec((LANE_BLOCK, c), lambda i: (i, 0)),
            pl.BlockSpec((LANE_BLOCK, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((LANE_BLOCK, w), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((padded, w), jnp.int32),
        interpret=interpret,
    )(*ins)
    return out[:lanes]


def cov_flush_batch(cov_map, buf, n, use_pallas: bool = False, interpret: bool = False):
    """Batched buffer→map fold: the Pallas VMEM kernel, or the vmapped
    sequential `coverage.cov_flush` reference (the bit-identity
    oracle)."""
    if use_pallas and HAVE_PALLAS:
        return cov_flush_pallas(cov_map, buf, n, interpret=interpret)
    from .coverage import cov_flush

    return jax.vmap(cov_flush)(cov_map, buf, n)


def pop_earliest_batch(eq_time, eq_seq, eq_valid, use_pallas: bool = False, interpret: bool = False):
    """Reference implementation (vmapped XLA) or the fused Pallas kernel."""
    if use_pallas and HAVE_PALLAS:
        return pop_earliest_pallas(eq_time, eq_seq, eq_valid, interpret=interpret)
    return jax.vmap(pop_earliest)(eq_time, eq_seq, eq_valid)


def pop_gather_batch(
    eq_time, eq_seq, eq_valid, eq_kind, eq_node, eq_src, eq_payload,
    use_pallas: bool = False, interpret: bool = False,
):
    """Pop + gather the popped event tuple: the fused Pallas kernel, or
    the vmapped-XLA reference (pop + take_along_axis gathers). Both
    return (idx, any_valid, (time, kind, node, src, payload)) with
    bit-identical values."""
    if use_pallas and HAVE_PALLAS:
        return pop_gather_pallas(
            eq_time, eq_seq, eq_valid, eq_kind, eq_node, eq_src, eq_payload,
            interpret=interpret,
        )
    idx, any_valid = jax.vmap(pop_earliest)(eq_time, eq_seq, eq_valid)

    def take(a):
        return jnp.take_along_axis(a, idx[:, None], axis=1)[:, 0]

    ev_payload = jnp.take_along_axis(
        eq_payload, idx[:, None, None], axis=1
    )[:, 0, :]
    return idx, any_valid, (
        take(eq_time), take(eq_kind), take(eq_node), take(eq_src), ev_payload
    )
