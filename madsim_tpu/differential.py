"""Cross-engine differential harness — SURVEY.md §7's "two engines, one
semantics spec" promise made checkable (VERDICT r2 item 2).

The TPU engine (`engine/core.py`) explores seeds at chip rate over
protocol *step functions*; the host engine (`runtime/`, `task/`, `net/`)
runs the same protocol as free-form async code (the reference's
authoring model, examples/raft_host.py). The engines use different RNG
streams and schedulers, so their traces are not bit-comparable — what
must agree is the *semantics*: the same protocol, under the same fault
schedule, upholds (or, for a seeded bug variant, violates) the same
invariants.

Three bridges:

1. `fault_schedule(engine, seed)` — decode the device lane's fault
   events. A pure function of (seed, FaultPlan); this IS the pinned
   chaos schedule for the seed.
2. `run_host_raft(seed, schedule, ...)` — replay that exact schedule
   (partition/heal, kill/restart, directional clog, group partition,
   loss storm) against the host-engine Raft protocol at the same
   virtual times, recording every applied chaos op.
3. `differential_raft(seeds, ...)` — run both engines per seed and
   compare: safety verdicts (election safety, committed-prefix log
   matching), election liveness, and the applied chaos event stream
   event-for-event against the device schedule.

A drift in either engine's scheduler, fabric, chaos machinery, or Raft
semantics breaks the agreement and fails CI (tests/test_differential.py)
— the cross-engine analogue of the reference's determinism contract
(madsim/src/sim/runtime/mod.rs:178-203).
"""

from __future__ import annotations

import importlib.util
import os
from typing import Dict, List, Optional

from .engine.core import (
    EV_FAULT,
    F_CLOG_DIR,
    F_CLOG_GROUP,
    F_CLOG_PAIR,
    F_DELAY_END,
    F_DELAY_SPIKE,
    F_KILL,
    F_LOSS_END,
    F_LOSS_STORM,
    F_RESTART,
    F_UNCLOG_DIR,
    F_UNCLOG_GROUP,
    F_UNCLOG_PAIR,
    Engine,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_raft_host():
    """Import the example protocol (examples/raft_host.py) — the
    differential harness deliberately reuses the *example* code so the
    comparison covers what users actually write, not a purpose-built
    twin."""
    path = os.path.join(_REPO, "examples", "raft_host.py")
    spec = importlib.util.spec_from_file_location("raft_host_example", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def fault_schedule(engine: Engine, seed: int) -> List[Dict[str, int]]:
    """Decode the fault events the device lane for `seed` will execute:
    [{"t_us", "op", "a", "b"}, ...] sorted by (time, seq). `a` is a node
    for pair/dir/kill ops, a node *bitmask* for group ops, and the loss
    rate (1/65536 units) for storm ops."""
    import numpy as np

    state = engine.init_lane(seed)
    kind = np.asarray(state.eq_kind)
    valid = np.asarray(state.eq_valid)
    sel = valid & (kind == EV_FAULT)
    t = np.asarray(state.eq_time)[sel]
    seq = np.asarray(state.eq_seq)[sel]
    pay = np.asarray(state.eq_payload)[sel]
    order = np.lexsort((seq, t))
    return [
        {"t_us": int(t[i]), "op": int(pay[i][0]), "a": int(pay[i][1]), "b": int(pay[i][2])}
        for i in order
    ]


def run_host_raft(
    seed: int,
    schedule: List[Dict[str, int]],
    n: int = 5,
    horizon_us: int = 5_000_000,
    node_cls=None,
    base_loss: float = 0.0,
) -> Dict:
    """Run the host-engine example Raft under the pinned `schedule`.

    `base_loss` mirrors the device engine's static
    `EngineConfig.packet_loss_rate`: it is installed in the host fabric at
    setup, storms composite on top of it (rate = min(1, base + a/65536)),
    and F_LOSS_END restores it (not 0.0) — so both engines run under the
    same loss conditions.

    Returns {"violation": None | "ELECTION_SAFETY" | "LOG_MATCHING",
    "elected": bool, "max_commit": int, "chaos_applied": [(t_us, op, a, b)],
    "loss_trace": [(t_us, rate), ...]}.
    """
    from . import rand as sim_rand  # noqa: F401  (package side effects)
    from . import time as sim_time
    from .net import NetSim
    from .plugin import simulator
    from .runtime import Handle, Runtime
    from .task import spawn

    ex = _load_raft_host()
    cls = node_cls or ex.RaftNode

    async def scenario():
        handle = Handle.current()
        net = simulator(NetSim)
        # NetSim.config is the outer Config; the fabric reads
        # Network.config == config.net (net/network.py:154) — mutate THAT.
        net.config.net.packet_loss_rate = base_loss
        state: dict = {"loss_trace": [(0, base_loss)]}
        peers = [f"10.3.0.{i+1}:{5000+i}" for i in range(n)]
        nodes = []
        for i in range(n):
            node = (
                handle.create_node()
                .name(f"draft-{i}")
                .ip(f"10.3.0.{i+1}")
                .init(lambda i=i: cls(i, peers, state).run())
                .build()
            )
            nodes.append(node)
        ids = [nd.id for nd in nodes]

        async def chaos():
            applied = state.setdefault("chaos_applied", [])
            start = sim_time.now()

            def group_split(mask_lo, mask_hi):
                # two-word mask: lo carries bits [0, 30), hi [30, 60)
                def bit(i):
                    return (mask_lo >> i) & 1 if i < 30 else (mask_hi >> (i - 30)) & 1

                g = [ids[i] for i in range(n) if bit(i)]
                rest = [ids[i] for i in range(n) if not bit(i)]
                return g, rest

            for ev in schedule:
                target = start + ev["t_us"] / 1e6
                delta = target - sim_time.now()
                if delta > 0:
                    await sim_time.sleep(delta)
                op, a, b = ev["op"], ev["a"], ev["b"]
                if op == F_CLOG_PAIR:
                    net.partition([ids[a]], [ids[b]])
                elif op == F_UNCLOG_PAIR:
                    net.heal([ids[a]], [ids[b]])
                elif op == F_KILL:
                    handle.kill(ids[a])
                elif op == F_RESTART:
                    handle.restart(ids[a])
                elif op == F_CLOG_DIR:
                    net.clog_link(ids[a], ids[b])
                elif op == F_UNCLOG_DIR:
                    net.unclog_link(ids[a], ids[b])
                elif op == F_CLOG_GROUP:
                    net.partition(*group_split(a, b))
                elif op == F_UNCLOG_GROUP:
                    net.heal(*group_split(a, b))
                elif op == F_LOSS_STORM:
                    rate = min(1.0, base_loss + a / 65536.0)
                    net.config.net.packet_loss_rate = rate
                    state["loss_trace"].append((ev["t_us"], rate))
                elif op == F_LOSS_END:
                    net.config.net.packet_loss_rate = base_loss
                    state["loss_trace"].append((ev["t_us"], base_loss))
                elif op == F_DELAY_SPIKE:
                    # device K_DELAY window: ~10% of packets +1-5 s
                    # (the engine's DELAY_PROB/EXTRA constants mirror
                    # these fabric knobs — one semantics, two engines)
                    net.config.net.delay_spike_prob = 0.1
                    state.setdefault("delay_trace", []).append((ev["t_us"], 0.1))
                elif op == F_DELAY_END:
                    net.config.net.delay_spike_prob = 0.0
                    state.setdefault("delay_trace", []).append((ev["t_us"], 0.0))
                applied.append((ev["t_us"], op, a, b))

        spawn(chaos())
        await sim_time.sleep(horizon_us / 1e6)

        violation: Optional[str] = None
        for _term, leaders in state.get("leaders_by_term", {}).items():
            if len(leaders) > 1:
                violation = "ELECTION_SAFETY"
        # committed prefixes must agree pairwise (device invariant twin)
        stable = state.get("stable", {})
        commits = state.get("commits", {})
        for i in commits:
            for j in commits:
                if i >= j:
                    continue
                upto = min(commits[i], commits[j])
                li = stable.get(i, {}).get("log", [])
                lj = stable.get(j, {}).get("log", [])
                for idx in range(1, min(upto + 1, len(li), len(lj))):
                    if li[idx][0] != lj[idx][0]:
                        violation = violation or "LOG_MATCHING"
        return {
            "violation": violation,
            "elected": len(state.get("leaders_by_term", {})) > 0,
            "max_commit": state.get("max_commit", 0),
            "chaos_applied": list(state.get("chaos_applied", [])),
            "loss_trace": list(state.get("loss_trace", [])),
            "delay_trace": list(state.get("delay_trace", [])),
        }

    return Runtime(seed=seed).block_on(scenario())


def run_device_raft(engine: Engine, seed: int, max_steps: int = 3000) -> Dict:
    """One seed on the TPU engine, reduced to the same verdict shape."""
    import jax.numpy as jnp

    from .models.raft import ELECTION_SAFETY, LOG_MATCHING

    res = engine.make_runner(max_steps=max_steps)(
        jnp.asarray([seed], dtype=jnp.uint32)
    )
    code = int(res.fail_code[0])
    names = {ELECTION_SAFETY: "ELECTION_SAFETY", LOG_MATCHING: "LOG_MATCHING"}
    return {
        "violation": names.get(code, str(code)) if bool(res.failed[0]) else None,
        "elected": int(res.summary["max_term"][0]) > 0
        and int(res.summary["max_commit"][0]) > 0,
        "max_commit": int(res.summary["max_commit"][0]),
    }


def differential_raft(
    engine: Engine,
    seeds,
    n: int = 5,
    host_node_cls=None,
    max_steps: int = 3000,
) -> Dict:
    """Run every seed on both engines under the device's fault schedule.

    Returns per-seed rows plus aggregates:
      {"rows": [...], "device_violations": int, "host_violations": int,
       "safety_disagreements": int, "schedule_mismatches": int,
       "device_elected": int, "host_elected": int}
    """
    horizon = engine.config.horizon_us
    base_loss = float(getattr(engine.config, "packet_loss_rate", 0.0))
    rows = []
    for seed in seeds:
        seed = int(seed)
        sched = fault_schedule(engine, seed)
        dev = run_device_raft(engine, seed, max_steps=max_steps)
        host = run_host_raft(
            seed, sched, n=n, horizon_us=horizon, node_cls=host_node_cls,
            base_loss=base_loss,
        )
        rows.append(
            {
                "seed": seed,
                "schedule": sched,
                "device": dev,
                "host": host,
                # the host chaos task is abandoned when the scenario
                # returns at the horizon, so events scheduled at or past
                # it are (correctly) never applied — compare only the
                # in-horizon prefix
                "schedule_ok": host["chaos_applied"]
                == [
                    (e["t_us"], e["op"], e["a"], e["b"])
                    for e in sched
                    if e["t_us"] < horizon
                ],
            }
        )
    return {
        "rows": rows,
        "device_violations": sum(1 for r in rows if r["device"]["violation"]),
        "host_violations": sum(1 for r in rows if r["host"]["violation"]),
        "safety_disagreements": sum(
            1
            for r in rows
            if bool(r["device"]["violation"]) != bool(r["host"]["violation"])
        ),
        "schedule_mismatches": sum(1 for r in rows if not r["schedule_ok"]),
        "device_elected": sum(1 for r in rows if r["device"]["elected"]),
        "host_elected": sum(1 for r in rows if r["host"]["elected"]),
    }
