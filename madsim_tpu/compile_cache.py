"""Opt-in JAX persistent compilation cache wiring.

PROFILE_r5 measured multi-second `lane_step` / streaming-executor
recompiles paid once per *process*; hunts, sweeps and CI shards spawn
many processes over the same configs, so they should pay each compile
once per *machine*. Enabling is one env var (or `EngineConfig` /
`--compile-cache`):

    MADSIM_TPU_COMPILE_CACHE=~/.cache/madsim_tpu python -m madsim_tpu ...

The cache is keyed by (HLO, jaxlib version, XLA flags, device kind), so
it is safe to share a directory across configs and machines of the same
software image; a mismatched key is simply a miss. Works on CPU, GPU and
TPU backends with current jaxlib.
"""

from __future__ import annotations

import os
from typing import Optional

_active_dir: Optional[str] = None


def enable_compile_cache(path: Optional[str] = None) -> Optional[str]:
    """Enable the JAX persistent compilation cache.

    `path` falls back to $MADSIM_TPU_COMPILE_CACHE; with neither set
    this is a no-op returning None. Idempotent — the first directory
    wins for the process (jax's cache is global); later calls with a
    different directory return the ACTIVE one rather than silently
    rebinding half the jit cache. Returns the active directory."""
    global _active_dir
    path = path or os.environ.get("MADSIM_TPU_COMPILE_CACHE")
    if not path:
        return _active_dir
    path = os.path.abspath(os.path.expanduser(path))
    if _active_dir is not None:
        return _active_dir
    import jax

    # cache wiring lands on the host timeline (madsim_tpu/perf) so a
    # --perf-timeline run shows whether its compiles could hit a
    # persistent cache at all
    from .perf.recorder import maybe_count

    maybe_count("compile_cache_enabled")
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # cache every compile, not just the multi-second ones: a hunt's many
    # small jits (replay steps, shrink candidates) add up too. -1 on the
    # entry-size floor disables the filesystem-specific override that 0
    # would allow (which can silently skip small entries).
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    # the cache module latches "no cache" on the first compile of the
    # process; a reset makes the next compile re-initialize against the
    # directory just configured (no-op if nothing compiled yet)
    try:
        from jax.experimental.compilation_cache import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:  # pragma: no cover - layout drift across jax versions
        pass
    _active_dir = path
    return _active_dir


def active_compile_cache() -> Optional[str]:
    """The directory enabled for this process, or None."""
    return _active_dir
