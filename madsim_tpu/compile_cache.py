"""Opt-in JAX persistent compilation cache wiring (+ warm-start keys).

PROFILE_r5 measured multi-second `lane_step` / streaming-executor
recompiles paid once per *process*; hunts, sweeps and CI shards spawn
many processes over the same configs, so they should pay each compile
once per *machine*. Enabling is one env var (or `EngineConfig` /
`--compile-cache`):

    MADSIM_TPU_COMPILE_CACHE=~/.cache/madsim_tpu python -m madsim_tpu ...

The cache is keyed by (HLO, jaxlib version, XLA flags, device kind), so
it is safe to share a directory across configs and machines of the same
software image; a mismatched key is simply a miss. Works on CPU, GPU and
TPU backends with current jaxlib.

Warm-start discipline (r11): jax's internal key makes sharing SAFE but
says nothing about what a given worker will actually *hit* — a fleet
primes per-(jax version, gate tuple, stream version, shape) so a cold
worker's first compile is a deserialize, not a build. `cache_subkey`
renders exactly that tuple as a directory-name-safe string; bench.py
routes its cache under it and reports `compile_s_cold` vs
`compile_s_warm` (the warm number is measured by dropping the
in-process jit caches and recompiling against the just-written
persistent entries — the path every warm fleet worker takes). CI keys
its actions/cache on the same string.

Failure discipline: `enable_compile_cache` used to degrade silently
when the directory could not be created or written — a fleet that
*thinks* it is warm but recompiles everywhere is the worst of both
worlds. It now probes writability: `strict=True` (bench, priming jobs)
raises; the default logs a warning and leaves the cache off.
"""

from __future__ import annotations

import contextlib
import logging
import os
import re
from typing import Optional

_active_dir: Optional[str] = None

_log = logging.getLogger("madsim_tpu.compile_cache")

# -- AOT supersegment serialization (r12) ------------------------------------
#
# The persistent XLA cache above removes the *compile* half of a warm
# worker's start cost; BENCH_r11 measured the remaining 18.2 s flagship
# warm start as TRACE-dominated — jax re-traces the streaming program
# every process even when the executable deserializes. `jax.export`
# closes that half: the engine serializes the exported (traced +
# lowered) supersegment under $MADSIM_TPU_AOT_CACHE keyed by the
# warm-start subkey PLUS a sha1 fingerprint of the package sources and
# the full engine/machine configuration, so a warm worker deserializes
# StableHLO instead of re-tracing Python. The fingerprint is the
# staleness guard: jax's internal cache key protects the *executable*
# layer, but a deserialized export IS the program — a stale artifact
# must be a miss, never a silently different trace. Load/save are
# best-effort (corrupt or unwritable entries degrade to a plain
# re-trace, logged); `_AOT_SCHEMA` bumps invalidate every entry.

_AOT_SCHEMA = 1
_aot_disabled = False
_src_fingerprint: Optional[str] = None


def aot_cache_dir() -> Optional[str]:
    """The AOT artifact directory ($MADSIM_TPU_AOT_CACHE), or None."""
    return os.environ.get("MADSIM_TPU_AOT_CACHE") or None


def aot_enabled() -> bool:
    """True when AOT serialization is configured and not suspended."""
    return aot_cache_dir() is not None and not _aot_disabled


@contextlib.contextmanager
def disable_aot():
    """Suspend AOT load/save for the dynamic extent — the honest
    no-AOT warm path `measure_warm_compile(cold_trace=True)` times."""
    global _aot_disabled
    prev = _aot_disabled
    _aot_disabled = True
    try:
        yield
    finally:
        _aot_disabled = prev


def source_fingerprint() -> str:
    """sha1 over every .py source in the madsim_tpu package (sorted
    relative-path walk) — the part of an AOT artifact's identity the
    warm-start subkey cannot see. Computed once per process: the
    sources don't change under a running engine, and a fleet's many
    _stream_fns builds must not re-hash the tree each time."""
    global _src_fingerprint
    if _src_fingerprint is None:
        import hashlib

        root = os.path.dirname(os.path.abspath(__file__))
        h = hashlib.sha1()
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(
                d for d in dirnames if d != "__pycache__"
            )
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                h.update(os.path.relpath(path, root).encode())
                with open(path, "rb") as f:
                    h.update(f.read())
        _src_fingerprint = h.hexdigest()[:16]
    return _src_fingerprint


def _aot_path(subkey: str, name: str) -> Optional[str]:
    base = aot_cache_dir()
    if base is None:
        return None
    base = os.path.abspath(os.path.expanduser(base))
    return os.path.join(
        base, f"schema{_AOT_SCHEMA}", subkey, f"{name}.jaxexp"
    )


def load_aot(subkey: str, name: str) -> Optional[bytes]:
    """Read a serialized export, or None (disabled / missing). The
    caller deserializes and falls back to a live trace on failure."""
    if not aot_enabled():
        return None
    path = _aot_path(subkey, name)
    try:
        with open(path, "rb") as f:
            return f.read()
    except OSError:
        return None


def save_aot(subkey: str, name: str, blob: bytes) -> Optional[str]:
    """Atomically persist a serialized export (tmp + rename, so a
    concurrent fleet worker never reads a torn artifact). Best-effort:
    an unwritable directory logs and returns None — the process keeps
    its live trace."""
    if not aot_enabled():
        return None
    path = _aot_path(subkey, name)
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)
    except OSError as e:
        _log.warning("could not persist AOT artifact %s: %s", path, e)
        return None
    return path


def cache_subkey(
    *,
    gates: Optional[dict] = None,
    rng_stream: Optional[int] = None,
    lanes: Optional[int] = None,
    segment_steps: Optional[int] = None,
    devices: Optional[int] = None,
    import_jax: bool = True,
) -> str:
    """A directory-name-safe warm-start key: (jax/jaxlib version, gate
    tuple, stream version, shape key, device topology). Two processes
    with equal subkeys compile byte-identical HLO for the streaming
    path, so priming one warms the other; anything that changes the
    compiled step (a jax upgrade, a gate flip, a new lane count, a
    different mesh shape) lands in its own subdirectory instead of
    growing one stale shared pile forever.

    `devices` is the 1-D "batch" mesh size the program spans (1 =
    unsharded). It is part of the key because a serialized AOT export
    is topology-specific — a single-device export must never
    deserialize into a mesh run and vice versa — and because the fleet
    allocator's warm-compile grouping must keep a mesh job and a
    single-device job in different groups (their compiled programs
    share nothing).

    `gates` is the bench-style dict ({"rng_stream": 3, "coverage":
    True, ...}); bool values render as 0/1, the rest as-is. Unknown /
    None fields are simply omitted — the key is best-effort
    discrimination, jax's internal (HLO, jaxlib, flags, device) key is
    what guarantees correctness.

    `import_jax=False` pins the version prefix to `jax-unknown`
    WITHOUT touching jax (even when it is importable): the fleet
    control plane computes job-grouping subkeys jax-free, and a
    grouping key must be identical no matter which process renders it
    — the allocator needs EQUALITY, not version discrimination (jax's
    internal cache key still provides that for the actual entries)."""
    if not import_jax:
        parts = ["jax-unknown"]
    else:
        try:
            import jax
            import jaxlib

            parts = [f"jax{jax.__version__}-jaxlib{jaxlib.__version__}"]
        except Exception:  # pragma: no cover - jax-free callers
            parts = ["jax-unknown"]
    if rng_stream is not None:
        parts.append(f"rng{rng_stream}")
    if gates:
        bits = []
        for k in sorted(gates):
            v = gates[k]
            if v is None:
                continue
            short = "".join(w[0] for w in k.split("_")) or k
            bits.append(f"{short}{int(v) if isinstance(v, bool) else v}")
        if bits:
            parts.append(".".join(bits))
    if lanes is not None:
        shape = f"l{lanes}"
        if segment_steps is not None:
            shape += f"x{segment_steps}"
        parts.append(shape)
    if devices is not None:
        parts.append(f"d{devices}")
    return re.sub(r"[^A-Za-z0-9._-]", "_", "-".join(parts))


def _probe_writable(path: str) -> Optional[str]:
    """Create `path` and prove a write lands. Returns an error string
    instead of raising (the caller decides strict vs warn). A plain
    os.access check is not enough: this repo's CI and the reference box
    run as root, where access() says yes to read-only mounts."""
    try:
        os.makedirs(path, exist_ok=True)
        probe = os.path.join(path, ".madsim-tpu-write-probe")
        with open(probe, "w") as f:
            f.write("ok")
        os.remove(probe)
    except OSError as e:
        return f"{type(e).__name__}: {e}"
    return None


def enable_compile_cache(
    path: Optional[str] = None,
    *,
    strict: bool = False,
    subdir: Optional[str] = None,
) -> Optional[str]:
    """Enable the JAX persistent compilation cache.

    `path` falls back to $MADSIM_TPU_COMPILE_CACHE; with neither set
    this is a no-op returning None. `subdir` (usually a `cache_subkey`)
    nests the cache under the base path — pick it BEFORE the first jit,
    because enabling is idempotent: the first directory wins for the
    process (jax's cache is global); later calls with a different
    directory return the ACTIVE one rather than silently rebinding half
    the jit cache. Returns the active directory.

    An unwritable directory raises RuntimeError under `strict` and
    logs a warning (cache left off) otherwise — never the old silent
    no-op that let a fleet believe it was warm while every worker
    recompiled."""
    global _active_dir
    path = path or os.environ.get("MADSIM_TPU_COMPILE_CACHE")
    if not path:
        return _active_dir
    path = os.path.abspath(os.path.expanduser(path))
    if subdir:
        path = os.path.join(path, subdir)
    if _active_dir is not None:
        return _active_dir
    err = _probe_writable(path)
    if err is not None:
        msg = (
            f"compile cache directory {path!r} is not writable ({err}); "
            f"every process will silently recompile"
        )
        if strict:
            raise RuntimeError(msg)
        _log.warning("%s — persistent cache left DISABLED", msg)
        return None
    import jax

    # cache wiring lands on the host timeline (madsim_tpu/perf) so a
    # --perf-timeline run shows whether its compiles could hit a
    # persistent cache at all
    from .perf.recorder import maybe_count

    maybe_count("compile_cache_enabled")
    jax.config.update("jax_compilation_cache_dir", path)
    # cache every compile, not just the multi-second ones: a hunt's many
    # small jits (replay steps, shrink candidates) add up too. -1 on the
    # entry-size floor disables the filesystem-specific override that 0
    # would allow (which can silently skip small entries).
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    # the cache module latches "no cache" on the first compile of the
    # process; a reset makes the next compile re-initialize against the
    # directory just configured (no-op if nothing compiled yet)
    try:
        from jax.experimental.compilation_cache import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:  # pragma: no cover - layout drift across jax versions
        pass
    _active_dir = path
    return _active_dir


def active_compile_cache() -> Optional[str]:
    """The directory enabled for this process, or None."""
    return _active_dir


def measure_warm_compile(build_and_run, cold_trace: bool = False) -> Optional[float]:
    """Time the WARM compile path: drop every in-process jit cache,
    then run `build_and_run` (which must construct fresh jitted
    callables and force their compilation — invoke once, or compile
    without executing via `Engine.compile_stream` / `.lower().compile()`
    so device execution stays out of the timed window) against the
    persistent entries the cold path just wrote — the exact path a new
    fleet worker or a post-restart replay pays. Returns seconds, or
    None when no persistent cache is active (there is no warm path to
    measure; the honest answer is "same as cold", not a fabricated
    number).

    `cold_trace=True` additionally suspends the AOT export cache for
    the rebuild: the r11 number silently *included* any AOT entries
    the cold run wrote, so "warm" conflated deserialize-the-trace with
    re-trace-everything. The two are now separately measurable — warm
    (AOT allowed, the real fleet-worker path) vs cold-trace (persistent
    XLA cache only, every trace re-paid), and tests/test_perf.py
    asserts warm-with-AOT beats warm-without."""
    if _active_dir is None:
        return None
    import time

    import jax

    jax.clear_caches()
    ctx = disable_aot() if cold_trace else contextlib.nullcontext()
    with ctx:
        t0 = time.perf_counter()  # madsim: allow(D001) — host-side timing
        build_and_run()
        return time.perf_counter() - t0  # madsim: allow(D001)
