"""D-rules: determinism hazards, pure AST (stdlib only, no jax).

The failure mode these guard against is the silent kind: the code runs,
the hunt finds a seed, and the seed stops reproducing on another box,
another day, or another PYTHONHASHSEED — the exact corpus-rot class the
PR-3 investigation chased for a whole session. Each rule names a
nondeterminism source the Rust reference intercepts at runtime behind
`cfg(madsim)` and Python cannot:

D001  wall-clock reads (`time.time`, `perf_counter`, `datetime.now`…)
D002  OS/global entropy (`random.*` module functions, legacy
      `np.random.*` globals, unseeded `default_rng()`, `os.urandom`,
      `uuid.uuid1/4`, `secrets.*`)
D003  iteration over a set (hash-order leaks; strings vary per process
      with PYTHONHASHSEED) — fixable: wrap in `sorted(...)`
D004  `id()` / builtin `hash()` (CPython process addresses /
      PYTHONHASHSEED; both differ across runs)
D005  unordered host callbacks (`jax.debug.callback` without
      `ordered=True`, `io_callback(ordered=False)`) — the compiler may
      reorder or elide them, so observable side effects lose their
      deterministic interleaving — fixable: `ordered=True`
D006  python truthiness on a traced value inside a Machine handler
      (`if`/`while`/`bool()`/`assert` on names derived from
      `nodes`/`payload`/jnp expressions) — under jit this is a trace
      error at best and a silently-static branch at worst

Rules fire on direct syntax only (see astutils). Severity: D006 is a
heuristic taint pass, so it reports as warning; the rest are errors.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from .astutils import (
    ImportMap,
    TRACED_METHODS,
    dotted_name,
    machine_classes,
    resolve_call,
)
from .findings import Finding, Severity

WALL_CLOCK_CALLS = {
    "time.time", "time.time_ns",
    "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns",
    "time.process_time", "time.process_time_ns",
    "time.localtime", "time.gmtime",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
}

ENTROPY_CALLS = {
    "os.urandom", "uuid.uuid1", "uuid.uuid4",
    "secrets.token_bytes", "secrets.token_hex", "secrets.token_urlsafe",
    "secrets.randbits", "secrets.choice", "secrets.randbelow",
    "random.random", "random.randint", "random.randrange",
    "random.choice", "random.choices", "random.shuffle",
    "random.sample", "random.uniform", "random.getrandbits",
    "random.gauss", "random.normalvariate", "random.expovariate",
    "random.betavariate", "random.triangular", "random.vonmisesvariate",
    "numpy.random.rand", "numpy.random.randn", "numpy.random.randint",
    "numpy.random.random", "numpy.random.random_sample",
    "numpy.random.ranf", "numpy.random.sample",
    "numpy.random.choice", "numpy.random.shuffle",
    "numpy.random.permutation", "numpy.random.uniform",
    "numpy.random.normal", "numpy.random.bytes", "numpy.random.seed",
}

# seeded-generator constructors: fine WITH a seed argument, OS entropy
# without one
SEEDED_CTORS = {"numpy.random.default_rng", "random.Random", "numpy.random.RandomState"}

UNORDERED_CALLBACKS = {"jax.debug.callback"}
IO_CALLBACKS = {"jax.experimental.io_callback"}

# attribute reads that turn a traced value back into static python
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "at"}
# calls that return static python regardless of argument taint
STATIC_CALLS = {"len", "range", "isinstance", "type", "getattr", "hasattr", "repr", "str"}


def _find(findings: List[Finding], rule: str, sev: str, path: str,
          node: ast.AST, message: str, fixable: bool = False) -> None:
    findings.append(Finding(
        rule=rule, severity=sev, path=path,
        line=getattr(node, "lineno", 0), col=getattr(node, "col_offset", 0),
        message=message, fixable=fixable,
    ))


def _is_set_expr(node: ast.expr, imports: ImportMap) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        name = resolve_call(node, imports)
        if name in ("set", "frozenset"):
            return True
    return False


def _callback_ordered_kw(node: ast.Call) -> Optional[bool]:
    """The `ordered=` keyword's constant value, None when absent or
    non-constant."""
    for kw in node.keywords:
        if kw.arg == "ordered":
            if isinstance(kw.value, ast.Constant):
                return bool(kw.value.value)
            return True  # non-constant: assume the author thought about it
    return None


def check_module(tree: ast.Module, source: str, path: str) -> List[Finding]:
    imports = ImportMap(tree)
    findings: List[Finding] = []

    in_hash_method: Set[int] = set()  # line spans of __hash__/__eq__ bodies
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name in ("__hash__", "__eq__"):
            in_hash_method.update(range(node.lineno, (node.end_lineno or node.lineno) + 1))

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = resolve_call(node, imports)
            if name in WALL_CLOCK_CALLS:
                _find(findings, "D001", Severity.ERROR, path, node,
                      f"wall-clock read `{name}` — virtual time only; use the "
                      f"sim clock (madsim_tpu.time) or gate behind real mode")
            elif name in ENTROPY_CALLS:
                _find(findings, "D002", Severity.ERROR, path, node,
                      f"OS/global entropy `{name}` — draw from the seeded "
                      f"stream (madsim_tpu.rand / handler rand_u32 words)")
            elif name in SEEDED_CTORS:
                unseeded = not node.args or (
                    isinstance(node.args[0], ast.Constant) and node.args[0].value is None
                )
                if unseeded and not node.keywords:
                    _find(findings, "D002", Severity.ERROR, path, node,
                          f"`{name}()` without a seed draws OS entropy — pass "
                          f"an explicit seed derived from the lane seed")
            elif name == "id":
                _find(findings, "D004", Severity.ERROR, path, node,
                      "`id()` is a process address — varies across runs; key "
                      "on an explicit stable identifier instead")
            elif name == "hash" and node.lineno not in in_hash_method:
                arg_const = node.args and isinstance(node.args[0], ast.Constant)
                if not arg_const:
                    _find(findings, "D004", Severity.ERROR, path, node,
                          "builtin `hash()` is PYTHONHASHSEED-dependent for "
                          "str/bytes — use a content hash (core.digest_fold "
                          "family) for anything that can reach sim state")
            elif name in UNORDERED_CALLBACKS:
                if _callback_ordered_kw(node) is not True:
                    _find(findings, "D005", Severity.ERROR, path, node,
                          f"`{name}` is unordered by default — the compiler "
                          f"may reorder or drop it; pass ordered=True",
                          fixable=True)
            elif name in IO_CALLBACKS:
                if _callback_ordered_kw(node) is not True:
                    _find(findings, "D005", Severity.ERROR, path, node,
                          f"`{name}` without ordered=True may be reordered "
                          f"or elided by the compiler", fixable=True)

        iter_expr = None
        if isinstance(node, ast.For):
            iter_expr = node.iter
        elif isinstance(node, ast.comprehension):
            iter_expr = node.iter
        if iter_expr is not None and _is_set_expr(iter_expr, imports):
            _find(findings, "D003", Severity.ERROR, path, iter_expr,
                  "iteration over a set — hash order can leak into "
                  "simulation state (and varies with PYTHONHASHSEED for "
                  "strings); iterate sorted(...)", fixable=True)

    findings.extend(_check_traced_truthiness(tree, path))
    return findings


# -- D006: truthiness on traced values inside handlers -----------------------


def _taint_expr(node: ast.expr, tainted: Set[str]) -> bool:
    """Conservative 'does this expression carry a traced value'."""
    if isinstance(node, ast.Name):
        return node.id in tainted
    if isinstance(node, ast.Attribute):
        if node.attr in STATIC_ATTRS:
            return False
        # self.X is static config; anything_else.attr inherits taint
        base = node.value
        if isinstance(base, ast.Name) and base.id == "self":
            return False
        return _taint_expr(base, tainted)
    if isinstance(node, ast.Subscript):
        return _taint_expr(node.value, tainted)
    if isinstance(node, ast.Call):
        name = dotted_name(node.func)
        if name:
            head = name.split(".")[0]
            if name.split(".")[-1] in STATIC_CALLS or head in STATIC_CALLS:
                return False
            if head in ("jnp", "jax", "lax"):
                return True
        return any(_taint_expr(a, tainted) for a in node.args) or any(
            _taint_expr(kw.value, tainted) for kw in node.keywords
        )
    if isinstance(node, (ast.BinOp,)):
        return _taint_expr(node.left, tainted) or _taint_expr(node.right, tainted)
    if isinstance(node, ast.UnaryOp):
        return _taint_expr(node.operand, tainted)
    if isinstance(node, ast.Compare):
        return _taint_expr(node.left, tainted) or any(
            _taint_expr(c, tainted) for c in node.comparators
        )
    if isinstance(node, ast.BoolOp):
        return any(_taint_expr(v, tainted) for v in node.values)
    if isinstance(node, (ast.Tuple, ast.List)):
        return any(_taint_expr(e, tainted) for e in node.elts)
    if isinstance(node, ast.IfExp):
        return (_taint_expr(node.body, tainted)
                or _taint_expr(node.orelse, tainted))
    return False


def _check_traced_truthiness(tree: ast.Module, path: str) -> List[Finding]:
    findings: List[Finding] = []
    for cls in machine_classes(tree).values():
        for fn in cls.body:
            if not isinstance(fn, ast.FunctionDef):
                continue
            if fn.name not in TRACED_METHODS:
                continue
            tainted: Set[str] = {
                a.arg for a in fn.args.args + fn.args.kwonlyargs
                if a.arg != "self"
            }

            def flag(expr: ast.expr, what: str) -> None:
                findings.append(Finding(
                    rule="D006", severity=Severity.WARNING, path=path,
                    line=expr.lineno, col=expr.col_offset,
                    message=f"python truthiness on a likely-traced value in "
                            f"handler `{fn.name}` ({what}) — under jit this "
                            f"is a trace error or a silently-static branch; "
                            f"use jnp.where / masked writes",
                ))

            for node in ast.walk(fn):
                # propagate taint through simple assignments, in source
                # order (ast.walk is BFS by nesting, close enough for
                # straight-line handler bodies)
                if isinstance(node, ast.Assign) and _taint_expr(node.value, tainted):
                    for tgt in node.targets:
                        for n in ast.walk(tgt):
                            if isinstance(n, ast.Name):
                                tainted.add(n.id)
                elif isinstance(node, ast.If) and _taint_expr(node.test, tainted):
                    flag(node.test, "if")
                elif isinstance(node, ast.While) and _taint_expr(node.test, tainted):
                    flag(node.test, "while")
                elif isinstance(node, ast.Assert) and _taint_expr(node.test, tainted):
                    flag(node.test, "assert")
                elif isinstance(node, ast.IfExp) and _taint_expr(node.test, tainted):
                    flag(node.test, "conditional expression")
                elif (isinstance(node, ast.Call)
                      and isinstance(node.func, ast.Name)
                      and node.func.id == "bool"
                      and node.args
                      and _taint_expr(node.args[0], tainted)):
                    flag(node, "bool()")
                elif isinstance(node, ast.BoolOp) and _taint_expr(node, tainted):
                    flag(node, "and/or")
    return findings
