"""`lint --fix`: the two mechanically safe rewrites.

* D003 — wrap the set iterable in `sorted(...)`: same elements,
  deterministic order. (Sorting cost is irrelevant off the device hot
  path, and a set that reaches a `for` is host code by construction.)
* D005 — add `ordered=True` to `jax.debug.callback`/`io_callback`
  calls (or flip an explicit `ordered=False`).

Everything else needs judgment (what IS the right seed source?), so it
stays a finding. Edits are computed from AST spans against the current
source and applied bottom-up so earlier spans stay valid; the caller
re-lints after fixing.
"""

from __future__ import annotations

import ast
from typing import List, Tuple

from .astutils import ImportMap, resolve_call
from .drules import IO_CALLBACKS, UNORDERED_CALLBACKS, _is_set_expr


def _span(source_lines: List[str], node: ast.expr) -> Tuple[int, int]:
    """(start, end) absolute character offsets of a node."""
    starts = [0]
    for line in source_lines:
        starts.append(starts[-1] + len(line) + 1)
    start = starts[node.lineno - 1] + node.col_offset
    end = starts[node.end_lineno - 1] + node.end_col_offset
    return start, end


def fix_source(source: str, path: str) -> Tuple[str, int]:
    """Apply the mechanical fixes; returns (new_source, n_edits)."""
    tree = ast.parse(source, filename=path)
    imports = ImportMap(tree)
    lines = source.split("\n")
    edits: List[Tuple[int, int, str]] = []  # (start, end, replacement)

    for node in ast.walk(tree):
        iter_expr = None
        if isinstance(node, ast.For):
            iter_expr = node.iter
        elif isinstance(node, ast.comprehension):
            iter_expr = node.iter
        if iter_expr is not None and _is_set_expr(iter_expr, imports):
            start, end = _span(lines, iter_expr)
            edits.append((start, end, f"sorted({source[start:end]})"))
            continue

        if isinstance(node, ast.Call):
            name = resolve_call(node, imports)
            if name in UNORDERED_CALLBACKS or name in IO_CALLBACKS:
                ordered_kw = next(
                    (kw for kw in node.keywords if kw.arg == "ordered"), None
                )
                if ordered_kw is None:
                    # insert before the closing paren of the call
                    start, end = _span(lines, node)
                    inner = source[start:end]
                    close = inner.rfind(")")
                    if close > 0:
                        sep = "" if inner[:close].rstrip().endswith("(") else ", "
                        edits.append((
                            start + close, start + close, f"{sep}ordered=True"
                        ))
                elif (
                    isinstance(ordered_kw.value, ast.Constant)
                    and ordered_kw.value.value is not True
                ):
                    start, end = _span(lines, ordered_kw.value)
                    edits.append((start, end, "True"))

    # apply bottom-up; drop overlapping edits (outer wins are fine for
    # the rare nested case — the re-lint catches anything left)
    edits.sort(key=lambda e: e[0], reverse=True)
    out = source
    last_start = len(source) + 1
    applied = 0
    for start, end, repl in edits:
        if end > last_start:
            continue
        out = out[:start] + repl + out[end:]
        last_start = start
        applied += 1
    return out, applied
