"""C-rules: Machine authoring-contract checks.

Two halves. The AST half is free (no imports, runs on any file):

C001  `self.*` mutation inside a pure handler (`on_message`/`on_timer`/
      `invariant`/`is_done`/`summary`/`coverage_projection`) — handler
      state MUST live in the `nodes` pytree; instance state survives
      across lanes and steps in trace order, which is exactly the
      cross-lane leak the vmap model cannot tolerate
C005  a voter/ack-bitmask tally without the 31-node cap assertion —
      int32 one-hot bitmasks alias beyond bit 30 (sign bit), so any
      class shifting `1 << node` into a mask must loudly refuse
      num_nodes > 31 (the PR-6 discipline, both raft variants)

The import half instantiates each Machine subclass (constructors must
be fully defaulted — every shipped model is) and verifies, WITHOUT
running a simulation:

C002  `durable_spec()` congruent with `init()`'s pytree structure,
      every leaf a python bool
C003  `torn_spec()` congruent with `init()`'s structure, every leaf a
      legal atomicity class (TORN_ATOMIC/TORN_LOSE/TORN_PREFIX), and
      never declared without the `durable_spec()` it refines
C004  `coverage_projection(nodes, 0)` returns a scalar integer word
      (shape (), integer dtype) — the coverage hash folds exactly one
      word per step

The import half is the only lint pass allowed to import jax (models are
jax programs); `--no-import-check` skips it for jax-free pre-commit
runs. Engine construction re-validates C002/C003 at runtime — the lint
pass exists so the contract breaks in review, not in the first hunt.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from .astutils import PURE_HANDLERS, class_methods, machine_classes
from .findings import Finding, Severity


# -- AST half ----------------------------------------------------------------


def _self_mutations(fn: ast.FunctionDef) -> List[ast.AST]:
    """Statements that rebind/mutate `self.*` inside `fn`."""

    def is_self_attr(node: ast.AST) -> bool:
        return (
            isinstance(node, (ast.Attribute, ast.Subscript))
            and _root_is_self(node)
        )

    def _root_is_self(node: ast.AST) -> bool:
        while isinstance(node, (ast.Attribute, ast.Subscript)):
            node = node.value
        return isinstance(node, ast.Name) and node.id == "self"

    hits: List[ast.AST] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            if any(is_self_attr(t) for t in node.targets):
                hits.append(node)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            if is_self_attr(node.target):
                hits.append(node)
        elif isinstance(node, ast.Delete):
            if any(is_self_attr(t) for t in node.targets):
                hits.append(node)
        elif isinstance(node, ast.Call):
            # self.x.append(...) / self.x.update(...): container mutation
            if isinstance(node.func, ast.Attribute) and node.func.attr in (
                "append", "extend", "add", "update", "insert", "pop",
                "remove", "clear", "setdefault",
            ):
                if _root_is_self(node.func.value):
                    hits.append(node)
    return hits


_MASK_NAME_HINTS = ("vote", "ack", "grant", "voter")


def _bitmask_tally_lines(cls: ast.ClassDef) -> List[int]:
    """Lines where the class shifts a one-hot bit into a named
    vote/ack mask — the dup-safe tally idiom the 31-node cap guards."""
    lines: List[int] = [
        node.lineno
        for node in ast.walk(cls)
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.LShift)
    ]
    if not lines:
        return []
    # require a mask-ish attribute/name in the class at all; otherwise
    # shifts are generic bit math (clog words, coverage packing)
    for node in ast.walk(cls):
        name = None
        if isinstance(node, ast.Attribute):
            name = node.attr.lower()
        elif isinstance(node, ast.Name):
            name = node.id.lower()
        if name and "mask" in name and any(h in name for h in _MASK_NAME_HINTS):
            return lines
    return []


def _has_31_cap(cls: ast.ClassDef) -> bool:
    """An assert/raise-bearing comparison against the 31/32 node cap
    anywhere in the class."""
    for node in ast.walk(cls):
        if isinstance(node, ast.Constant) and node.value in (31, 32):
            return True
    return False


def check_module(tree: ast.Module, source: str, path: str) -> List[Finding]:
    findings: List[Finding] = []
    for cls in machine_classes(tree).values():
        for fn in class_methods(cls):
            if fn.name not in PURE_HANDLERS:
                continue
            for hit in _self_mutations(fn):
                findings.append(Finding(
                    rule="C001", severity=Severity.ERROR, path=path,
                    line=hit.lineno, col=hit.col_offset,
                    message=f"`self.*` mutation inside pure handler "
                            f"`{cls.name}.{fn.name}` — handler state must "
                            f"live in the `nodes` pytree (instance state "
                            f"leaks across lanes under vmap and across "
                            f"steps in trace order)",
                ))
        tally_lines = _bitmask_tally_lines(cls)
        if tally_lines and not _has_31_cap(cls):
            findings.append(Finding(
                rule="C005", severity=Severity.ERROR, path=path,
                line=tally_lines[0], col=0,
                message=f"`{cls.name}` tallies a voter/ack bitmask but "
                        f"never asserts the 31-node cap — int32 one-hot "
                        f"bits alias at bit 31 (sign); refuse "
                        f"num_nodes > 31 in __init__",
            ))
    return findings


# -- import half -------------------------------------------------------------


def _method_lines(tree: ast.Module) -> Dict[str, Dict[str, int]]:
    """{class: {method: lineno, "": class lineno}} for finding anchors."""
    out: Dict[str, Dict[str, int]] = {}
    for name, cls in machine_classes(tree).items():
        out[name] = {"": cls.lineno}
        for fn in class_methods(cls):
            out[name][fn.name] = fn.lineno
    return out


def _import_module_from(path: str):
    import importlib.util
    import os
    import sys

    # inside the package tree, import canonically (respects relative
    # imports); otherwise load by file path
    norm = os.path.abspath(path)
    parts = norm.replace(os.sep, "/").split("/")
    if "madsim_tpu" in parts:
        rel = parts[parts.index("madsim_tpu"):]
        if rel[-1].endswith(".py"):
            rel[-1] = rel[-1][:-3]
        if rel[-1] == "__init__":
            rel = rel[:-1]
        import importlib
        return importlib.import_module(".".join(rel))
    import re

    modname = "_madsim_lint_" + re.sub(r"\W", "_", norm.strip("/"))
    spec = importlib.util.spec_from_file_location(modname, norm)
    if spec is None or spec.loader is None:
        raise ImportError(f"cannot load {path}")
    mod = importlib.util.module_from_spec(spec)
    sys.modules[modname] = mod
    spec.loader.exec_module(mod)
    return mod


def check_module_contracts(
    tree: ast.Module, source: str, path: str
) -> Tuple[List[Finding], List[str]]:
    """The import half for one file. Returns (findings, skipped-notes).
    Imports jax — call only when the caller opted into import checks."""
    anchors = _method_lines(tree)
    if not anchors:
        return [], []

    # This function IS the gate the layer map (L003) asks about: the C
    # import half is the one lint pass allowed to import jax (models
    # are jax programs), and callers opt in via --no-import-check.
    import jax  # madsim: allow(L003) — the documented import-check gate
    import jax.numpy as jnp  # madsim: allow(L003) — same gate

    # madsim: allow(L003) — same gate (engine.machine hosts the Machine
    # base class the contract checks instantiate)
    from ..engine.machine import (
        Machine,
        TORN_ATOMIC,
        TORN_LOSE,
        TORN_PREFIX,
    )

    findings: List[Finding] = []
    skipped: List[str] = []
    try:
        mod = _import_module_from(path)
    except Exception as exc:  # pragma: no cover - import environment issues
        skipped.append(f"{path}: import failed ({exc!r}); C002-C004 skipped")
        return findings, skipped

    def anchor(cls_name: str, method: str) -> int:
        per = anchors.get(cls_name, {})
        return per.get(method) or per.get("") or 0

    for cls_name in anchors:
        obj = getattr(mod, cls_name, None)
        if obj is None or not isinstance(obj, type) or not issubclass(obj, Machine):
            continue
        if obj is Machine:
            continue
        try:
            machine = obj()
        except Exception as exc:
            skipped.append(
                f"{path}: {cls_name}() not default-constructible ({exc!r}); "
                f"C002-C004 skipped"
            )
            continue

        def emit(rule: str, method: str, message: str) -> None:
            findings.append(Finding(
                rule=rule, severity=Severity.ERROR, path=path,
                line=anchor(cls_name, method), col=0, message=message,
            ))

        try:
            nodes = machine.init(jax.random.PRNGKey(0))
        except Exception as exc:
            skipped.append(f"{path}: {cls_name}.init() raised {exc!r}; C002-C004 skipped")
            continue
        node_treedef = jax.tree.structure(nodes)

        spec = None
        try:
            spec = machine.durable_spec()
        except Exception as exc:
            emit("C002", "durable_spec", f"{cls_name}.durable_spec() raised {exc!r}")
        if spec is not None:
            if jax.tree.structure(spec) != node_treedef:
                emit("C002", "durable_spec",
                     f"{cls_name}.durable_spec() is not pytree-congruent "
                     f"with init(): {jax.tree.structure(spec)} vs "
                     f"{node_treedef}")
            else:
                bad = [
                    type(leaf).__name__
                    for leaf in jax.tree.leaves(spec)
                    if not isinstance(leaf, bool)
                ]
                if bad:
                    emit("C002", "durable_spec",
                         f"{cls_name}.durable_spec() leaves must be python "
                         f"bools (durable yes/no), got {sorted(set(bad))}")

        tspec = None
        try:
            tspec = machine.torn_spec()
        except Exception as exc:
            emit("C003", "torn_spec", f"{cls_name}.torn_spec() raised {exc!r}")
        if tspec is not None:
            if spec is None:
                emit("C003", "torn_spec",
                     f"{cls_name}.torn_spec() without durable_spec() — the "
                     f"atomicity contract refines the durable contract; "
                     f"torn restarts would be refused at engine build")
            if jax.tree.structure(tspec) != node_treedef:
                emit("C003", "torn_spec",
                     f"{cls_name}.torn_spec() is not pytree-congruent with "
                     f"init(): {jax.tree.structure(tspec)} vs {node_treedef}")
            else:
                legal = (TORN_ATOMIC, TORN_LOSE, TORN_PREFIX)
                bad_vals = sorted({
                    repr(leaf) for leaf in jax.tree.leaves(tspec)
                    if not (isinstance(leaf, int) and leaf in legal)
                })
                if bad_vals:
                    emit("C003", "torn_spec",
                         f"{cls_name}.torn_spec() leaves must be TORN_ATOMIC/"
                         f"TORN_LOSE/TORN_PREFIX, got {bad_vals}")

        try:
            proj = jax.eval_shape(
                machine.coverage_projection, nodes, jnp.int32(0)
            )
        except Exception as exc:
            emit("C004", "coverage_projection",
                 f"{cls_name}.coverage_projection(nodes, now_us) failed to "
                 f"trace: {exc!r}")
        else:
            shape = getattr(proj, "shape", None)
            dtype = getattr(proj, "dtype", None)
            if shape != () or dtype is None or not jnp.issubdtype(dtype, jnp.integer):
                emit("C004", "coverage_projection",
                     f"{cls_name}.coverage_projection must return a scalar "
                     f"integer word (shape (), integer dtype); got shape "
                     f"{shape}, dtype {dtype} — the coverage hash folds "
                     f"exactly one uint32 per step")
    return findings, skipped
