"""G-rules: gate-discipline cross-checks over the whole repo (no jax).

The chaos palette's growth (6 -> 11 kinds over PRs 5-7) spread
load-bearing mirrors of one table across eight files; ROADMAP's "every
new kind keeps the gate-off-bit-identical discipline" was enforced by
reviewers remembering all of them. These rules make the checklist
machine-run. `madsim_tpu/kinds.py` is the source of truth (itself
parsed STATICALLY — pure tuple literals and `+`-concatenations, so a
drifted consumer cannot corrupt the reference the check compares
against); each consumer must either bind its table from `kinds` or
carry a literal equal to it:

G001  flight-recorder counter mirror (runtime/metrics.py)
G002  coverage band mirrors (ops/coverage.py, runtime/coverage.py):
      equal tables, and every kind (plus dup/amnesia) owns a band
G003  shrink's ablation table covers the whole vocabulary
G004  CLI `--fault-kinds` vocabulary (__main__.py)
G005  every non-default chaos flag exercised in the test_step_gates
      gate-off matrix
G006  every chaos flag pinned in tests/test_golden_streams.py
G007  engine/core.py K_* indices match FAULT_KIND_NAMES order, the
      FaultPlan has one bool flag per kind, and enabled_kinds() maps
      flag -> K_* in table order
G008  RNG-layout manifest audit (ops/rng_layout.manifest): the
      StepRngLayout section order is append-only — tail-only growth is
      the invariant that keeps every recorded stream byte-stable
G009  guided-search escalation ladder (search/bias.py): every rung
      must be DERIVED from kinds.FAULT_KIND_NAMES (slices /
      concatenations of the bound table, never a literal mirror),
      rungs must strictly widen, and the final rung must cover the
      full CLI vocabulary — recorded guided trails name these rungs,
      so a drifted ladder would silently re-key every recorded hunt

All findings are repo-level (line 0 or the defining line) — inline
suppressions don't apply; fix the drift or version the contract.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

from .findings import Finding, Severity

# files, relative to repo root
KINDS_PY = "madsim_tpu/kinds.py"
CORE_PY = "madsim_tpu/engine/core.py"
METRICS_PY = "madsim_tpu/runtime/metrics.py"
OPS_COV_PY = "madsim_tpu/ops/coverage.py"
RT_COV_PY = "madsim_tpu/runtime/coverage.py"
SHRINK_PY = "madsim_tpu/engine/shrink.py"
MAIN_PY = "madsim_tpu/__main__.py"
STEP_RNG_PY = "madsim_tpu/ops/step_rng.py"
MANIFEST = "madsim_tpu/ops/rng_layout.manifest"
SEARCH_BIAS_PY = "madsim_tpu/search/bias.py"
GATES_TEST = "tests/test_step_gates.py"
GOLDEN_TEST = "tests/test_golden_streams.py"


def find_repo_root(start: str) -> Optional[str]:
    """Walk up from `start` to the directory holding the madsim_tpu
    package (identified by the engine core, not just the name)."""
    cur = os.path.abspath(start)
    if os.path.isfile(cur):
        cur = os.path.dirname(cur)
    while True:
        if os.path.isfile(os.path.join(cur, CORE_PY)):
            return cur
        parent = os.path.dirname(cur)
        if parent == cur:
            return None
        cur = parent


# -- static literal resolution ----------------------------------------------


class ModuleFacts:
    """Module-level bindings of one parsed file: literal values where
    statically resolvable, plus which names were imported from the
    kinds module (the 'binds the source of truth' evidence)."""

    def __init__(self, tree: ast.Module):
        self.assigns: Dict[str, ast.expr] = {}
        self.from_kinds: Dict[str, str] = {}  # local name -> kinds attr
        self.kinds_aliases: List[str] = []  # module aliases for kinds
        for node in tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                if isinstance(tgt, ast.Name):
                    self.assigns[tgt.id] = node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                if isinstance(node.target, ast.Name):
                    self.assigns[node.target.id] = node.value
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if mod.split(".")[-1] == "kinds":
                    # from ..kinds import NAME [as ALIAS]
                    for alias in node.names:
                        self.from_kinds[alias.asname or alias.name] = alias.name
                else:
                    # from .. import kinds [as _kinds]
                    for alias in node.names:
                        if alias.name == "kinds":
                            self.kinds_aliases.append(alias.asname or "kinds")
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name.split(".")[-1] == "kinds":
                        self.kinds_aliases.append(alias.asname or alias.name)

    def resolve(self, name: str, depth: int = 0) -> Optional[tuple]:
        """Statically resolve `name` to a tuple of constants, following
        in-module Name references and `+` concatenations."""
        if depth > 8 or name not in self.assigns:
            return None
        return self.resolve_expr(self.assigns[name], depth)

    def resolve_expr(self, node: ast.expr, depth: int = 0) -> Optional[tuple]:
        if isinstance(node, ast.Tuple):
            out = []
            for elt in node.elts:
                if isinstance(elt, ast.Constant):
                    out.append(elt.value)
                elif isinstance(elt, ast.Tuple):
                    inner = self.resolve_expr(elt, depth + 1)
                    if inner is None:
                        return None
                    out.append(inner)
                else:
                    return None
            return tuple(out)
        if isinstance(node, ast.Constant) and isinstance(node.value, tuple):
            return node.value
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
            left = self.resolve_expr(node.left, depth + 1)
            right = self.resolve_expr(node.right, depth + 1)
            if left is None or right is None:
                return None
            return left + right
        if isinstance(node, ast.Name):
            return self.resolve(node.id, depth + 1)
        if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
            # _kinds.FAULT_KIND_NAMES style — resolved by the caller
            # against the kinds facts when node.value.id is an alias
            return None
        return None

    def binding_of(self, name: str) -> Optional[Tuple[str, str]]:
        """If `name` is bound (directly or via one rebind) to an
        attribute of the kinds module, return ("kinds", attrname)."""
        if name in self.from_kinds:
            return ("kinds", self.from_kinds[name])
        node = self.assigns.get(name)
        if isinstance(node, ast.Name):
            return self.binding_of(node.id)
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id in self.kinds_aliases
        ):
            return ("kinds", node.attr)
        return None


class _Repo:
    def __init__(self, root: str):
        self.root = root
        self._trees: Dict[str, ast.Module] = {}
        self._facts: Dict[str, ModuleFacts] = {}
        self._sources: Dict[str, str] = {}

    def source(self, rel: str) -> Optional[str]:
        if rel not in self._sources:
            path = os.path.join(self.root, rel)
            if not os.path.isfile(path):
                return None
            with open(path, "r", encoding="utf-8") as fh:
                self._sources[rel] = fh.read()
        return self._sources[rel]

    def tree(self, rel: str) -> Optional[ast.Module]:
        if rel not in self._trees:
            src = self.source(rel)
            if src is None:
                return None
            self._trees[rel] = ast.parse(src, filename=rel)
        return self._trees[rel]

    def facts(self, rel: str) -> Optional[ModuleFacts]:
        if rel not in self._facts:
            tree = self.tree(rel)
            if tree is None:
                return None
            self._facts[rel] = ModuleFacts(tree)
        return self._facts[rel]


def _mirror_value(
    repo: _Repo, rel: str, local_name: str, kinds: Dict[str, tuple]
) -> Tuple[Optional[tuple], Optional[str]]:
    """The effective value of `local_name` in file `rel`: a literal if
    one is there, else the kinds table it binds. Returns (value,
    how) where how is 'literal' / 'kinds:<attr>' / None."""
    facts = repo.facts(rel)
    if facts is None:
        return None, None
    bound = facts.binding_of(local_name)
    if bound is not None:
        attr = bound[1]
        return kinds.get(attr), f"kinds:{attr}"
    value = facts.resolve(local_name)
    if value is not None:
        return value, "literal"
    return None, None


def _kinds_tables(repo: _Repo) -> Optional[Dict[str, tuple]]:
    facts = repo.facts(KINDS_PY)
    if facts is None:
        return None
    out = {}
    for name in (
        "FAULT_KIND_NAMES", "FR_EXTRA_NAMES", "KIND_TO_FLAG",
        "EXTRA_FLAGS", "CLI_KIND_TO_FLAG", "COV_BAND_NAMES",
        "COV_BAND_NAMES_V2",
    ):
        val = facts.resolve(name)
        if val is None:
            return None
        out[name] = val
    return out


def _finding(rule: str, path: str, message: str, line: int = 0) -> Finding:
    return Finding(
        rule=rule, severity=Severity.ERROR, path=path, line=line, col=0,
        message=message,
    )


def check_repo(root: str) -> List[Finding]:
    repo = _Repo(root)
    findings: List[Finding] = []

    kinds = _kinds_tables(repo)
    if kinds is None:
        return [_finding(
            "G001", KINDS_PY,
            "cannot statically resolve the kind tables in "
            "madsim_tpu/kinds.py — they must stay pure tuple literals "
            "(the G-pass refuses to trust a computed source of truth)",
        )]

    kind_names = kinds["FAULT_KIND_NAMES"]
    extra_names = kinds["FR_EXTRA_NAMES"]
    kind_flags = kinds["KIND_TO_FLAG"]
    extra_flags = kinds["EXTRA_FLAGS"]
    cli_flags = kinds["CLI_KIND_TO_FLAG"]

    # in-file consistency of kinds.py itself (literal duplication inside
    # the single file is allowed — this is what guards it)
    if tuple(n for n, _f in kind_flags) != kind_names:
        findings.append(_finding(
            "G007", KINDS_PY,
            f"kinds.KIND_TO_FLAG names {tuple(n for n, _ in kind_flags)} "
            f"!= FAULT_KIND_NAMES {kind_names} (same table, same order)",
        ))
    if set(n for n, _f in cli_flags) != set(kind_names) | {"dup"}:
        findings.append(_finding(
            "G004", KINDS_PY,
            f"kinds.CLI_KIND_TO_FLAG must cover every scheduled kind plus "
            f"'dup'; got {sorted(n for n, _ in cli_flags)} vs "
            f"{sorted(set(kind_names) | {'dup'})}",
        ))
    flag_by_name = dict(kind_flags) | dict(extra_flags)
    for name, field in cli_flags:
        if flag_by_name.get(name) != field:
            findings.append(_finding(
                "G004", KINDS_PY,
                f"kinds.CLI_KIND_TO_FLAG maps {name!r} -> {field!r} but "
                f"KIND_TO_FLAG/EXTRA_FLAGS say {flag_by_name.get(name)!r}",
            ))
    band_names_v1 = ("timer", "msg") + tuple(
        n.replace("-", "_") for n in kind_names[:6]
    )
    if kinds["COV_BAND_NAMES"] != band_names_v1:
        findings.append(_finding(
            "G002", KINDS_PY,
            f"kinds.COV_BAND_NAMES {kinds['COV_BAND_NAMES']} != "
            f"('timer','msg') + the first six kinds {band_names_v1}",
        ))
    v2 = kinds["COV_BAND_NAMES_V2"]
    missing_bands = [
        n for n in tuple(kind_names) + tuple(extra_names)
        if n.replace("-", "_") not in v2
    ]
    if missing_bands:
        findings.append(_finding(
            "G002", KINDS_PY,
            f"kinds.COV_BAND_NAMES_V2 is missing bands for "
            f"{missing_bands} — every kind and chaos channel needs a "
            f"decodable coverage band",
        ))

    # G001: flight-recorder mirror
    for local, attr, want in (
        ("FR_FAULT_KINDS", "FAULT_KIND_NAMES", kind_names),
        ("FR_EXTRAS", "FR_EXTRA_NAMES", extra_names),
    ):
        value, how = _mirror_value(repo, METRICS_PY, local, kinds)
        if value is None:
            findings.append(_finding(
                "G001", METRICS_PY,
                f"cannot find {local} as a kinds binding or literal in "
                f"runtime/metrics.py — the fr counter decoder must mirror "
                f"kinds.{attr}",
            ))
        elif tuple(value) != tuple(want):
            findings.append(_finding(
                "G001", METRICS_PY,
                f"{local} ({how}) = {value} drifted from kinds.{attr} = "
                f"{want} — harvested fr vectors would decode under wrong "
                f"labels",
            ))

    # G002: coverage band mirrors
    for rel in (OPS_COV_PY, RT_COV_PY):
        for local in ("COV_BAND_NAMES", "COV_BAND_NAMES_V2"):
            value, how = _mirror_value(repo, rel, local, kinds)
            if value is None:
                findings.append(_finding(
                    "G002", rel,
                    f"cannot find {local} as a kinds binding or literal in "
                    f"{rel}",
                ))
            elif tuple(value) != tuple(kinds[local]):
                findings.append(_finding(
                    "G002", rel,
                    f"{local} ({how}) = {value} drifted from kinds.{local} "
                    f"= {kinds[local]}",
                ))

    # G003: shrink ablation table
    ablation, how = _mirror_value(repo, SHRINK_PY, "ABLATION_ORDER", kinds)
    if ablation is None:
        # legacy literal form: ABLATABLE_KINDS as (name, field) pairs
        pairs, how = _mirror_value(repo, SHRINK_PY, "ABLATABLE_KINDS", kinds)
        ablation = tuple(p[0] for p in pairs) if pairs else None
        if pairs:
            for name, field in pairs:
                if flag_by_name.get(name) != field:
                    findings.append(_finding(
                        "G003", SHRINK_PY,
                        f"ABLATABLE_KINDS maps {name!r} -> {field!r}; the "
                        f"kinds table says {flag_by_name.get(name)!r}",
                    ))
    if ablation is None:
        findings.append(_finding(
            "G003", SHRINK_PY,
            "cannot resolve shrink's ablation table (ABLATION_ORDER or "
            "literal ABLATABLE_KINDS)",
        ))
    else:
        want_abl = set(kind_names) | {"dup", "strict-restart"}
        got_abl = set(ablation)
        if got_abl != want_abl:
            missing = sorted(want_abl - got_abl)
            extra = sorted(got_abl - want_abl)
            findings.append(_finding(
                "G003", SHRINK_PY,
                f"shrink ablation table out of sync with the vocabulary: "
                f"missing {missing}, unknown {extra} — a kind shrink "
                f"cannot ablate silently survives into every minimal "
                f"repro",
            ))

    # G004: CLI vocabulary
    main_facts = repo.facts(MAIN_PY)
    if main_facts is None:
        findings.append(_finding("G004", MAIN_PY, "cannot parse __main__.py"))
    else:
        main_src = repo.source(MAIN_PY) or ""
        binds_cli = "CLI_KIND_TO_FLAG" in main_src and ".kinds import" in main_src
        if not binds_cli:
            findings.append(_finding(
                "G004", MAIN_PY,
                "__main__.py no longer binds CLI_KIND_TO_FLAG from "
                "madsim_tpu/kinds.py — --fault-kinds parsing and the "
                "shrink repro printer must share the one vocabulary table",
            ))

    # G005/G006: gate matrix and golden pins must exercise the flags.
    # Flags whose FaultPlan default is True (the legacy pair/kill) are
    # on in every config, so the gate matrix exercises them implicitly;
    # golden pins must name every flag explicitly.
    defaults = _faultplan_defaults(repo)
    all_flags = tuple(f for _n, f in kind_flags) + tuple(f for _n, f in extra_flags)
    for rel, rule, exempt_default_true in (
        (GATES_TEST, "G005", True),
        (GOLDEN_TEST, "G006", False),
    ):
        src = repo.source(rel)
        if src is None:
            findings.append(_finding(rule, rel, f"{rel} not found"))
            continue
        missing = [
            f for f in all_flags
            if not re.search(rf"\b{re.escape(f)}\b", src)
            and not (exempt_default_true and defaults.get(f) is True)
        ]
        if missing:
            what = (
                "gate-off bit-identity matrix" if rule == "G005"
                else "golden-stream pins"
            )
            findings.append(_finding(
                rule, rel,
                f"chaos flags {missing} never appear in the {what} "
                f"({rel}) — every kind ships gate-off-bit-identical and "
                f"stream-pinned, or it doesn't ship",
            ))

    # G007: core.py K_* indices + FaultPlan fields + source binding
    findings.extend(_check_core(repo, kinds, defaults))

    # G008: RNG layout manifest
    findings.extend(_check_rng_layout(repo))

    # G009: guided-search escalation ladder
    findings.extend(_check_escalation_ladder(repo, kinds))

    return findings


def _faultplan_defaults(repo: _Repo) -> Dict[str, bool]:
    tree = repo.tree(CORE_PY)
    out: Dict[str, bool] = {}
    if tree is None:
        return out
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "FaultPlan":
            for stmt in node.body:
                if (
                    isinstance(stmt, ast.AnnAssign)
                    and isinstance(stmt.target, ast.Name)
                    and isinstance(stmt.value, ast.Constant)
                    and isinstance(stmt.value.value, bool)
                ):
                    out[stmt.target.id] = stmt.value.value
    return out


def _check_core(
    repo: _Repo, kinds: Dict[str, tuple], defaults: Dict[str, bool]
) -> List[Finding]:
    findings: List[Finding] = []
    tree = repo.tree(CORE_PY)
    facts = repo.facts(CORE_PY)
    if tree is None or facts is None:
        return [_finding("G007", CORE_PY, "cannot parse engine/core.py")]
    kind_names = kinds["FAULT_KIND_NAMES"]

    for local, attr in (
        ("FAULT_KIND_NAMES", "FAULT_KIND_NAMES"),
        ("FR_EXTRA_NAMES", "FR_EXTRA_NAMES"),
    ):
        value, how = _mirror_value(repo, CORE_PY, local, kinds)
        if value is None or tuple(value) != tuple(kinds[attr]):
            findings.append(_finding(
                "G007", CORE_PY,
                f"core.{local} must bind or equal kinds.{attr} "
                f"(got {value!r} via {how})",
            ))

    # K_<NAME> == index in FAULT_KIND_NAMES
    k_consts: Dict[str, int] = {}
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id.startswith("K_")
            and isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, int)
        ):
            k_consts[node.targets[0].id] = node.value.value
    for idx, name in enumerate(kind_names):
        kname = "K_" + name.upper().replace("-", "_")
        if k_consts.get(kname) != idx:
            findings.append(_finding(
                "G007", CORE_PY,
                f"{kname} should be {idx} (= FAULT_KIND_NAMES.index"
                f"({name!r})), got {k_consts.get(kname)!r} — recorded "
                f"fault schedules bake these indices",
            ))

    # FaultPlan carries one bool flag per kind + the extras
    for _name, field in tuple(kinds["KIND_TO_FLAG"]) + tuple(kinds["EXTRA_FLAGS"]):
        if field not in defaults:
            findings.append(_finding(
                "G007", CORE_PY,
                f"FaultPlan has no bool field {field!r} (or its default "
                f"is not a bool literal) — the kinds table maps "
                f"{_name!r} to it",
            ))

    # enabled_kinds(): the If(allow_X) -> append(K_Y) ladder must walk
    # the table in order
    ladder: List[Tuple[str, str]] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == "enabled_kinds":
            for stmt in ast.walk(node):
                if not isinstance(stmt, ast.If):
                    continue
                flag = None
                t = stmt.test
                if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name):
                    if t.value.id == "self":
                        flag = t.attr
                kconst = None
                for sub in ast.walk(stmt):
                    if (
                        isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr == "append"
                        and sub.args
                        and isinstance(sub.args[0], ast.Name)
                    ):
                        kconst = sub.args[0].id
                if flag and kconst:
                    ladder.append((flag, kconst))
    want_ladder = [
        (field, "K_" + name.upper().replace("-", "_"))
        for name, field in kinds["KIND_TO_FLAG"]
    ]
    if ladder and ladder != want_ladder:
        findings.append(_finding(
            "G007", CORE_PY,
            f"FaultPlan.enabled_kinds() ladder {ladder} != the kinds "
            f"table order {want_ladder} — schedule derivation draws kinds "
            f"by this order",
        ))
    return findings


def _check_escalation_ladder(
    repo: _Repo, kinds: Dict[str, tuple]
) -> List[Finding]:
    """G009: `search/bias.py`'s ESCALATION_LADDER must be DERIVED from
    the kinds tables (slices / `+`-concatenations of names bound from
    madsim_tpu/kinds.py — a literal kind-name tuple here is exactly
    the mirror class every other G-rule exists to refuse), each rung
    must strictly widen the previous one, and the final rung must
    cover the full CLI vocabulary."""
    facts = repo.facts(SEARCH_BIAS_PY)
    if facts is None:
        return [_finding(
            "G009", SEARCH_BIAS_PY,
            f"{SEARCH_BIAS_PY} not found — the guided-search escalation "
            f"ladder is a recorded contract and must stay auditable",
        )]
    node = facts.assigns.get("ESCALATION_LADDER")
    if node is None or not isinstance(node, ast.Tuple):
        return [_finding(
            "G009", SEARCH_BIAS_PY,
            "ESCALATION_LADDER must be a module-level tuple literal of "
            "kinds-derived rungs (it is the recorded escalation "
            "contract guided trails reference by step index)",
        )]

    used_binding = [False]

    def resolve(expr: ast.expr) -> Optional[tuple]:
        """Resolve a rung against the kinds tables: bound names,
        constant-slice subscripts of bound names, literal tuples and
        `+`-concatenations."""
        if isinstance(expr, ast.Name):
            bound = facts.binding_of(expr.id)
            if bound is not None:
                used_binding[0] = True
                return kinds.get(bound[1])
            return facts.resolve(expr.id)
        if isinstance(expr, ast.Tuple):
            out = []
            for elt in expr.elts:
                if not isinstance(elt, ast.Constant):
                    return None
                out.append(elt.value)
            return tuple(out)
        if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
            left, right = resolve(expr.left), resolve(expr.right)
            if left is None or right is None:
                return None
            return left + right
        if isinstance(expr, ast.Subscript):
            base = resolve(expr.value)
            if base is None:
                return None
            sl = expr.slice
            if isinstance(sl, ast.Slice):
                lo = sl.lower.value if isinstance(sl.lower, ast.Constant) else None
                hi = sl.upper.value if isinstance(sl.upper, ast.Constant) else None
                if sl.step is None and (sl.lower is None or lo is not None) \
                        and (sl.upper is None or hi is not None):
                    return base[lo:hi]
            return None
        return None

    rungs = [resolve(elt) for elt in node.elts]
    if any(r is None for r in rungs) or not rungs:
        return [_finding(
            "G009", SEARCH_BIAS_PY,
            "cannot statically resolve every ESCALATION_LADDER rung "
            "from the kinds tables (rungs must be slices or "
            "`+`-concatenations of names bound from madsim_tpu/kinds.py)",
        )]
    findings: List[Finding] = []
    if not used_binding[0]:
        findings.append(_finding(
            "G009", SEARCH_BIAS_PY,
            "ESCALATION_LADDER does not bind madsim_tpu/kinds.py — a "
            "hand-maintained mirror of the kind vocabulary here is "
            "exactly the drift class the kinds table exists to prevent",
        ))
    cli_names = set(n for n, _f in kinds["CLI_KIND_TO_FLAG"])
    prev: set = set()
    for i, rung in enumerate(rungs):
        cur = set(rung)
        if not cur <= cli_names:
            findings.append(_finding(
                "G009", SEARCH_BIAS_PY,
                f"ESCALATION_LADDER rung {i} names unknown kinds "
                f"{sorted(cur - cli_names)} (vocabulary: "
                f"{sorted(cli_names)})",
            ))
        if not prev < cur:
            findings.append(_finding(
                "G009", SEARCH_BIAS_PY,
                f"ESCALATION_LADDER rung {i} does not strictly widen "
                f"rung {i - 1} — escalation must always ADD kinds "
                f"(recorded trails reference rungs by index)",
            ))
        prev = cur
    if prev != cli_names:
        findings.append(_finding(
            "G009", SEARCH_BIAS_PY,
            f"ESCALATION_LADDER's final rung must cover the full CLI "
            f"vocabulary {sorted(cli_names)}; got {sorted(prev)} — a "
            f"kind the ladder never reaches is a scenario class no "
            f"plateau can unlock",
        ))
    return findings


def _layout_sections(repo: _Repo) -> Optional[List[str]]:
    """StepRngLayout's `*_off` fields in declaration order — the block
    section order (the implicit handler head carries no offset)."""
    tree = repo.tree(STEP_RNG_PY)
    if tree is None:
        return None
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "StepRngLayout":
            fields = []
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
                    name = stmt.target.id
                    if name.endswith("_off"):
                        fields.append(name[: -len("_off")])
            return fields
    return None


def _check_rng_layout(repo: _Repo) -> List[Finding]:
    sections = _layout_sections(repo)
    if sections is None:
        return [_finding(
            "G008", STEP_RNG_PY,
            "cannot find StepRngLayout in ops/step_rng.py for the "
            "layout-manifest audit",
        )]
    manifest_src = repo.source(MANIFEST)
    if manifest_src is None:
        return [_finding(
            "G008", MANIFEST,
            f"RNG layout manifest {MANIFEST} is missing — it records the "
            f"step-block section order so growth stays tail-only",
        )]
    manifest = [
        line.strip() for line in manifest_src.splitlines()
        if line.strip() and not line.strip().startswith("#")
    ]
    if sections[: len(manifest)] != manifest:
        return [_finding(
            "G008", STEP_RNG_PY,
            f"StepRngLayout section order {sections} no longer starts "
            f"with the manifest order {manifest} — a section was "
            f"inserted, removed or reordered. That moves recorded "
            f"stream offsets (corpus-breaking); ship a new rng_stream "
            f"version instead",
        )]
    if len(sections) > len(manifest):
        new = sections[len(manifest):]
        return [_finding(
            "G008", MANIFEST,
            f"StepRngLayout grew new tail section(s) {new} not recorded "
            f"in {MANIFEST} — append them (tail growth is legal; "
            f"unrecorded growth is not reviewable)",
        )]
    return []
