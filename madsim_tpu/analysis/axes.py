"""Lane-axis abstract interpretation — the substrate of the S-rules.

The ROADMAP's [scale] item rebuilds `run_stream` on
`NamedSharding(mesh, P('batch'))` over the lane axis of `StreamCarry`.
Its stated precondition is a whole-program claim: per-lane state never
crosses chips except at a few designed collectives. This module is the
machine that checks it — an abstract interpreter over the
`projectmodel` call graph that tracks, for every value in the
streaming step path, whether it still carries the LANE (batch-leading)
axis:

* **LANE** — a lane-leading array (`[L, ...]`): shards for free under
  `P('batch')`; any op that reduces/gathers/reshapes ACROSS axis 0
  becomes a cross-chip collective under the mesh.
* **CARRY** — a struct of classified leaves (`StreamCarry`,
  `LaneState`, `BatchResult`): attribute reads classify by the field
  tables the S-rules declare (`srules.LANE_FIELDS` / `FREE_FIELDS`).
* **FREE** — no lane axis (scalars, ring buffers, the global coverage
  map): replicated under the mesh, crossing chips costs nothing.
* tuples of the above (`("tuple", [...])`) so `lax.while_loop` /
  `lax.cond` carries thread element-wise.

Propagation is the jnp/lax op semantics the step path actually uses:
elementwise ops and `where`/`select` join their operands; reductions
(`.sum()`, `jnp.any`, `lax.reduce`, `np.<ufunc>.reduce`) consult their
axis argument — minor-axis reductions (`axis=-1`, `axis=1`) are
lane-parallel, axis-0/axis-None reductions are CROSS-LANE; gathers
(`x[i]`, `x[-1]`, `x[mask]`, `searchsorted`) on the lane axis are
cross-lane, leading-slice/`[:, k]`/`take_along_axis(axis=1)` are not;
`reshape`/`ravel`/`transpose` on a lane value drops the axis (the
sharding would not survive, so it counts as cross-lane);
`lax.while_loop`/`lax.cond`/`lax.scan` thread carries element-wise
through their branch functions; `jax.vmap(f)(...)` produces a LANE
result and its body is per-lane code (never walked at batch level —
cross-lane ops are impossible inside it). Helper calls descend
context-sensitively with real argument axes, memoized; findings carry
the propagation chain, same shape as `trules`.

A cross-lane op is not automatically a finding: the step path NEEDS a
few (the while-cond done-mask, the harvest folds, the ring appends).
Each designed one carries an inline

    # madsim: collective(<name>, reduce=or|sum|any|max|min|gather|scan)

annotation (on the flagged line, or a comment-only line directly above
— same placement semantics as `# madsim: allow`). The annotation
*sanitizes* the op's result (a reduced/gathered value no longer
carries the lane axis) and must name an entry in the committed
registry (`srules.COLLECTIVES`) — which is exactly the all-reduce plan
the mesh rebuild implements. Everything else the S-rules refuse; the
rule semantics themselves live in `srules.py`.

Honesty bar matches `astutils`: syntactic resolution only. Runtime
indirection (getattr strings, fn tables) is out of scope; `jax.vmap`
bodies, Pallas kernel fns (reached only as refs through
`pallas_call`), and modules outside the entry closures are never
walked. Nothing here imports jax.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from .findings import Finding
from .projectmodel import (
    FunctionInfo,
    ModuleInfo,
    ProjectModel,
    resolve_callee,
)
from .astutils import dotted_name

# -- the axis lattice ---------------------------------------------------------

FREE = "free"
LANE = "lane"
CARRY = "carry"

Axis = object  # FREE | LANE | CARRY | ("tuple", [Axis, ...]) | ("list", Axis)


def is_tuple(ax) -> bool:
    return isinstance(ax, tuple) and len(ax) == 2 and ax[0] == "tuple"


def is_list(ax) -> bool:
    """A HOST container of arrays (python list/set literal, list
    concatenation, the list `pallas_call` returns): iterating or
    int-indexing it is host-side plumbing, NOT lane-axis traffic —
    only its ELEMENTS carry (or don't carry) the lane axis."""
    return isinstance(ax, tuple) and len(ax) == 2 and ax[0] == "list"


def elem_of(ax) -> Axis:
    return ax[1] if is_list(ax) else collapse(ax)


def join(*axes) -> Axis:
    """Least upper bound; LANE dominates (a value that MIGHT carry the
    lane axis must be treated as carrying it), CARRY beats FREE.
    Tuples join element-wise when shapes agree, lists join on their
    element axis, mixed forms collapse."""
    if axes and all(is_list(a) for a in axes):
        return ("list", join(*(a[1] for a in axes)))
    tuples = [a for a in axes if is_tuple(a)]
    if tuples:
        n = len(tuples[0][1])
        if all(is_tuple(a) and len(a[1]) == n for a in axes):
            return ("tuple", [
                join(*(a[1][i] for a in axes)) for i in range(n)
            ])
    axes = [collapse(a) for a in axes]
    if LANE in axes:
        return LANE
    if CARRY in axes:
        return CARRY
    return FREE


def collapse(ax) -> Axis:
    """A tuple/list axis flattened to one scalar verdict (used when a
    structured value flows somewhere structure-unaware)."""
    if is_tuple(ax):
        return join(*(collapse(a) for a in ax[1])) if ax[1] else FREE
    if is_list(ax):
        return collapse(ax[1])
    return ax


def laneish(ax) -> bool:
    """Does the value (or any element of it) still carry the lane axis?"""
    return collapse(ax) in (LANE, CARRY)


# -- collective annotations ---------------------------------------------------

_COLLECTIVE_RE = re.compile(
    r"#\s*madsim:\s*collective\(\s*([A-Za-z0-9_-]+)\s*,\s*reduce=([a-z]+)\s*\)"
)


@dataclasses.dataclass
class Annotation:
    name: str
    reduce: str
    lineno: int  # the comment's own line


class CollectiveAnnotations:
    """Per-file `# madsim: collective(...)` map. `line_map[n]` is the
    annotation governing code line n (1-based). A comment-only line's
    annotation extends through the comment block to the first code line
    below it — same placement contract as inline `allow(...)`."""

    def __init__(self, source: str):
        self.line_map: Dict[int, Annotation] = {}
        self.all: List[Annotation] = []
        lines = source.splitlines()
        for lineno, text in enumerate(lines, start=1):
            m = _COLLECTIVE_RE.search(text)
            if not m:
                continue
            ann = Annotation(m.group(1), m.group(2), lineno)
            self.all.append(ann)
            self.line_map.setdefault(lineno, ann)
            if text.lstrip().startswith("#"):
                target = lineno + 1
                while (
                    target <= len(lines)
                    and lines[target - 1].lstrip().startswith("#")
                ):
                    target += 1
                self.line_map.setdefault(target, ann)


# -- op tables ----------------------------------------------------------------

# callables whose name (post import-map) reduces over an axis argument
_REDUCE_FNS = {
    "sum", "prod", "mean", "max", "min", "any", "all", "argmin", "argmax",
    "count_nonzero", "cumsum", "cumprod", "sort", "argsort", "median",
    "bincount", "nonzero", "unique",
}
_REDUCE_PREFIXES = ("jnp.", "jax.numpy.", "np.", "numpy.", "lax.", "jax.lax.")
# method names on an array receiver with the same axis semantics
_REDUCE_METHODS = {
    "sum", "prod", "mean", "max", "min", "any", "all", "argmin", "argmax",
    "cumsum", "cumprod", "sort", "argsort",
}
# axis-dropping reshapes: the sharded axis does not survive these
_RESHAPE_METHODS = {"reshape", "ravel", "flatten", "transpose", "swapaxes"}
# gathers whose FIRST array argument is indexed along the given axis
_GATHER_FNS = {"searchsorted", "take", "compress", "roll", "flip"}
# python sinks that force the lane axis through host control flow
_HOST_SINKS = {"len", "int", "float", "bool", "list", "tuple", "sorted",
               "enumerate", "sum", "max", "min", "any", "all"}
# attribute reads returning static python regardless of the base's axis
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "itemsize", "nbytes"}


def _axis_kw(call: ast.Call, positional: Optional[int] = None):
    """The reduction's axis argument as a python value: int, tuple of
    ints, None (explicit axis=None or absent), or "?" when dynamic."""
    node = None
    for kw in call.keywords:
        if kw.arg in ("axis", "dimensions", "axes"):
            node = kw.value
    if node is None and positional is not None and len(call.args) > positional:
        node = call.args[positional]
    if node is None:
        return None
    if isinstance(node, ast.Constant):
        return node.value  # int or None
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub) and \
            isinstance(node.operand, ast.Constant):
        return -node.operand.value
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
            else:
                return "?"
        return tuple(out)
    return "?"


def axis_hits_lane(axis_val) -> bool:
    """Does this reduction axis touch axis 0 (the lane axis)? None =
    reduce everything = yes. Negative literals are minor-axis by
    convention (rank >= 2 on the step path's [L, Q]/[L, N] planes) —
    EXCEPT the common 1-D case has no minor axis, so a bare `.sum()`
    with no axis on a 1-D mask is the caller's (frequent) cross-lane
    fold; `None` covers it."""
    if axis_val is None:
        return True
    if axis_val == "?":
        return True  # dynamic axis: assume the worst
    if isinstance(axis_val, int):
        return axis_val == 0
    if isinstance(axis_val, tuple):
        return 0 in axis_val or not axis_val
    return True


# -- cross-lane events --------------------------------------------------------


@dataclasses.dataclass
class CrossLaneOp:
    """One cross-lane op the interpreter met, annotated or not. The
    S-rules turn these into findings and the registry audit."""

    kind: str  # reduce | gather | scan | reshape | iterate
    reduce: str  # or|sum|any|max|min|gather|scan|? — best-effort op class
    module: str
    rel: str
    line: int
    col: int
    region: str
    chain: Tuple[str, ...]
    detail: str
    annotation: Optional[Annotation]  # the governing collective(...) if any


@dataclasses.dataclass
class HostSink:
    """Python control flow / iteration / len() on a lane-carrying value
    (S003 raw material)."""

    what: str
    module: str
    rel: str
    line: int
    col: int
    region: str
    chain: Tuple[str, ...]


@dataclasses.dataclass
class RebuildKwarg:
    """One keyword at a carry rebuild site (`StreamCarry(...)` or
    `.replace(...)`) with the computed axis of its value (S002 raw
    material)."""

    cls: str
    field: str
    axis: Axis
    module: str
    rel: str
    line: int
    col: int
    chain: Tuple[str, ...]


# -- the interpreter ----------------------------------------------------------


@dataclasses.dataclass
class EntryPoint:
    module: str
    qualname: str
    region: str  # step | segment | init | final
    params: Dict[str, Axis]
    pinned: Dict[str, Axis] = dataclasses.field(default_factory=dict)


class AxisEngine:
    """Walk entry contexts, descending through project calls with real
    argument axes. Collects CrossLaneOp / HostSink / RebuildKwarg
    events; rule policy lives in srules."""

    def __init__(
        self,
        model: ProjectModel,
        *,
        lane_fields: Set[str],
        free_fields: Set[str],
        carry_fields: Set[str],
        carry_classes: Set[str],
        region_overrides: Dict[Tuple[str, str], str],
        reduce_name: Callable[[str], str] = lambda fn: fn,
    ):
        self.model = model
        self.lane_fields = lane_fields
        self.free_fields = free_fields
        self.carry_fields = carry_fields
        self.carry_classes = carry_classes
        self.region_overrides = region_overrides
        self.cross_ops: List[CrossLaneOp] = []
        self.host_sinks: List[HostSink] = []
        self.rebuilds: List[RebuildKwarg] = []
        self.walked_modules: Set[str] = set()
        self.consumed_annotations: Set[Tuple[str, int]] = set()  # (rel, lineno)
        self._annotations: Dict[str, CollectiveAnnotations] = {}
        self._memo: Dict[Tuple, Axis] = {}
        self._in_progress: Set[Tuple] = set()
        self._budget = 4000

    # -- entry ----------------------------------------------------------------

    def run(self, entrypoints: Sequence[EntryPoint]) -> None:
        for ep in entrypoints:
            fn = self.model.function(ep.module, ep.qualname)
            if fn is None:
                continue
            self._walk(
                fn, args={**ep.params}, region=ep.region, chain=(),
                closure=None, pinned=dict(ep.pinned),
            )

    def annotations_of(self, mi: ModuleInfo) -> CollectiveAnnotations:
        ann = self._annotations.get(mi.name)
        if ann is None:
            ann = self._annotations[mi.name] = CollectiveAnnotations(mi.source)
        return ann

    # -- function walks -------------------------------------------------------

    def _walk(
        self,
        fn: FunctionInfo,
        args: Dict[str, Axis],
        region: str,
        chain: Tuple[str, ...],
        closure: Optional[Dict[str, Axis]],
        pinned: Optional[Dict[str, Axis]] = None,
    ) -> Axis:
        region = self.region_overrides.get((fn.module, fn.qualname), region)
        nested = "<locals>" in fn.qualname
        key = None
        if not nested and closure is None:
            key = (
                fn.module, fn.qualname, region,
                tuple(sorted((k, repr(v)) for k, v in args.items())),
            )
            if key in self._memo:
                return self._memo[key]
            if key in self._in_progress:
                return FREE  # recursion: converge to bottom
            self._in_progress.add(key)
        if len(chain) > 10 or self._budget <= 0:
            if key is not None:
                self._in_progress.discard(key)
            return FREE
        self._budget -= 1
        self.walked_modules.add(fn.module)
        env: Dict[str, Axis] = {}
        if closure is not None:
            env.update(closure)
        for p in fn.params:
            env[p] = args.get(p, FREE)
        walk = _AxisWalk(
            self, fn, env=env, region=region,
            chain=chain + (fn.qualname,), pinned=pinned or {},
        )
        walk.run()
        result = walk.return_axis()
        if key is not None:
            self._in_progress.discard(key)
            self._memo[key] = result
        return result


class _AxisWalk:
    """One function body, walked twice in document order (round 2
    approximates loop-carried flows), tracking per-name axis state."""

    def __init__(self, engine: AxisEngine, fn: FunctionInfo,
                 env: Dict[str, Axis], region: str,
                 chain: Tuple[str, ...], pinned: Dict[str, Axis]):
        self.engine = engine
        self.fn = fn
        self.mi: ModuleInfo = engine.model.modules[fn.module]
        self.env = env
        self.region = region
        self.chain = chain
        self.pinned = pinned
        self.returns: List[Axis] = []
        self._seen_events: Set[Tuple[str, int, int, str]] = set()

    # -- driver ---------------------------------------------------------------

    def run(self) -> None:
        body = list(self.fn.node.body)
        for _round in (1, 2):
            self.returns = []
            self._stmts(body)

    def return_axis(self) -> Axis:
        return join(*self.returns) if self.returns else FREE

    # -- events ---------------------------------------------------------------

    def _cross(self, node: ast.AST, kind: str, reduce: str, detail: str) -> Axis:
        """Record a cross-lane op at `node`; consult the annotation map.
        Returns the result axis: sanitized FREE either way (the value no
        longer lane-indexes after a reduce/gather, and cascading LANE
        through an already-reported op would only duplicate findings)."""
        line = getattr(node, "lineno", 0)
        col = getattr(node, "col_offset", 0)
        dedup = (self.mi.rel, line, col, kind)
        if dedup not in self._seen_events:
            self._seen_events.add(dedup)
            ann = self.engine.annotations_of(self.mi).line_map.get(line)
            if ann is not None:
                self.engine.consumed_annotations.add((self.mi.rel, ann.lineno))
            self.engine.cross_ops.append(CrossLaneOp(
                kind=kind, reduce=reduce, module=self.fn.module,
                rel=self.mi.rel, line=line, col=col, region=self.region,
                chain=self.chain, detail=detail, annotation=ann,
            ))
        return FREE

    def _host_sink(self, node: ast.AST, what: str) -> None:
        self.engine.host_sinks.append(HostSink(
            what=what, module=self.fn.module, rel=self.mi.rel,
            line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0),
            region=self.region, chain=self.chain,
        ))

    def _rebuild(self, call: ast.Call, cls: str) -> None:
        for kw in call.keywords:
            if kw.arg is None:
                self._axis(kw.value)
                continue
            self.engine.rebuilds.append(RebuildKwarg(
                cls=cls, field=kw.arg, axis=self._axis(kw.value),
                module=self.fn.module, rel=self.mi.rel,
                line=kw.value.lineno, col=kw.value.col_offset,
                chain=self.chain,
            ))
        for a in call.args:
            self._axis(a)

    # -- statements -----------------------------------------------------------

    def _stmts(self, stmts) -> None:
        for stmt in stmts:
            self._stmt(stmt)

    def _stmt(self, node: ast.stmt) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested defs walk when called, with the closure env
        if isinstance(node, ast.Return):
            if node.value is not None:
                self.returns.append(self._axis(node.value))
            else:
                self.returns.append(FREE)
            return
        if isinstance(node, ast.Assign):
            ax = self._axis(node.value)
            for tgt in node.targets:
                self._assign(tgt, ax)
            return
        if isinstance(node, ast.AugAssign):
            ax = join(self._axis(node.value), self._axis(node.target))
            self._assign(node.target, ax)
            return
        if isinstance(node, ast.AnnAssign):
            if node.value is not None:
                self._assign(node.target, self._axis(node.value))
            return
        if isinstance(node, ast.For):
            it = self._axis(node.iter)
            if is_list(it) or is_tuple(it):
                self._assign(node.target, elem_of(it))
            elif laneish(it):
                self._host_sink(node.iter, "for-loop iteration")
                self._assign(node.target, FREE)
            else:
                self._assign(node.target, FREE)
            self._stmts(node.body)
            self._stmts(node.orelse)
            return
        if isinstance(node, ast.While):
            self._test_sink(node.test, "while")
            self._stmts(node.body)
            self._stmts(node.orelse)
            return
        if isinstance(node, ast.If):
            self._test_sink(node.test, "if")
            self._stmts(node.body)
            self._stmts(node.orelse)
            return
        if isinstance(node, ast.Assert):
            self._test_sink(node.test, "assert")
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                ax = self._axis(item.context_expr)
                if item.optional_vars is not None:
                    self._assign(item.optional_vars, ax)
            self._stmts(node.body)
            return
        if isinstance(node, ast.Try):
            self._stmts(node.body)
            for h in node.handlers:
                self._stmts(h.body)
            self._stmts(node.orelse)
            self._stmts(node.finalbody)
            return
        if isinstance(node, ast.Expr):
            self._axis(node.value)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._axis(child)

    def _test_sink(self, test: ast.expr, what: str) -> None:
        ax = self._axis(test)
        if laneish(ax) and not is_list(ax):
            self._host_sink(test, f"python `{what}` on a lane-axis value")

    def _assign(self, tgt: ast.expr, ax: Axis) -> None:
        if isinstance(tgt, ast.Name):
            if tgt.id in self.pinned:
                self.env[tgt.id] = self.pinned[tgt.id]
            else:
                self.env[tgt.id] = ax
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            if is_tuple(ax) and len(ax[1]) == len(tgt.elts):
                elems = ax[1]
            else:
                elems = [elem_of(ax)] * len(tgt.elts)
            for e, a in zip(tgt.elts, elems):
                self._assign(e, a)
        elif isinstance(tgt, (ast.Attribute, ast.Subscript)):
            self._axis(tgt.value)

    # -- expressions ----------------------------------------------------------

    def _axis(self, node: ast.expr) -> Axis:
        if isinstance(node, ast.Name):
            return self.env.get(node.id, FREE)
        if isinstance(node, ast.Constant):
            return FREE
        if isinstance(node, ast.Attribute):
            return self._attr_axis(node)
        if isinstance(node, ast.Subscript):
            return self._subscript_axis(node)
        if isinstance(node, ast.Call):
            return self._call_axis(node)
        if isinstance(node, ast.BinOp):
            left, right = self._axis(node.left), self._axis(node.right)
            if is_list(left) or is_list(right):
                # python list concatenation keeps the container form
                return ("list", join(elem_of(left), elem_of(right)))
            return join(left, right)
        if isinstance(node, ast.UnaryOp):
            return self._axis(node.operand)
        if isinstance(node, ast.Compare):
            out = self._axis(node.left)
            for c in node.comparators:
                out = join(out, self._axis(c))
            return out
        if isinstance(node, ast.BoolOp):
            return join(*(self._axis(v) for v in node.values))
        if isinstance(node, ast.IfExp):
            self._test_sink(node.test, "conditional expression")
            return join(self._axis(node.body), self._axis(node.orelse))
        if isinstance(node, ast.Tuple):
            return ("tuple", [self._axis(e) for e in node.elts])
        if isinstance(node, (ast.List, ast.Set)):
            elems = [self._axis(e) for e in node.elts]
            return ("list", join(*(elem_of(a) for a in elems)) if elems else FREE)
        if isinstance(node, ast.Dict):
            out: Axis = FREE
            for v in node.values:
                out = join(out, self._axis(v))
            for k in node.keys:
                if k is not None:
                    self._axis(k)
            return out
        if isinstance(node, ast.Starred):
            return self._axis(node.value)
        if isinstance(node, ast.Lambda):
            return FREE  # a function object; its body walks when applied
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            for gen in node.generators:
                it = self._axis(gen.iter)
                if is_list(it) or is_tuple(it):
                    self._assign(gen.target, elem_of(it))
                elif laneish(it):
                    self._host_sink(gen.iter, "comprehension over the lane axis")
                    self._assign(gen.target, FREE)
                else:
                    self._assign(gen.target, FREE)
                for cond in gen.ifs:
                    self._axis(cond)
            return ("list", elem_of(self._axis(node.elt)))
        if isinstance(node, ast.DictComp):
            for gen in node.generators:
                it = self._axis(gen.iter)
                if is_list(it) or is_tuple(it):
                    self._assign(gen.target, elem_of(it))
                elif laneish(it):
                    self._host_sink(gen.iter, "comprehension over the lane axis")
                    self._assign(gen.target, FREE)
                else:
                    self._assign(gen.target, FREE)
            return join(self._axis(node.key), self._axis(node.value))
        if isinstance(node, ast.JoinedStr):
            for v in node.values:
                if isinstance(v, ast.FormattedValue):
                    self._axis(v.value)
            return FREE
        if isinstance(node, ast.NamedExpr):
            ax = self._axis(node.value)
            self._assign(node.target, ax)
            return ax
        if isinstance(node, ast.Await):
            return self._axis(node.value)
        if isinstance(node, ast.Slice):
            for part in (node.lower, node.upper, node.step):
                if part is not None:
                    self._axis(part)
            return FREE
        return FREE

    def _attr_axis(self, node: ast.Attribute) -> Axis:
        if node.attr in _STATIC_ATTRS:
            self._axis(node.value)
            return FREE
        if isinstance(node.value, ast.Name) and node.value.id == "self":
            return FREE  # engine config / cached fns: static host state
        base = self._axis(node.value)
        base_c = collapse(base)
        if base_c == CARRY:
            if node.attr in self.engine.carry_fields:
                return CARRY
            if node.attr in self.engine.lane_fields:
                return LANE
            if node.attr in self.engine.free_fields:
                return FREE
            return FREE
        if base_c == LANE:
            # degraded carry: field classification is lost, every leaf
            # reads as lane-leading (sound for LaneState, whose leaves
            # all are; `.at` property rides through unchanged)
            return LANE
        return FREE

    def _subscript_axis(self, node: ast.Subscript) -> Axis:
        base = self._axis(node.value)
        sl = node.slice
        # host containers index host-side: element pick / sub-container
        if is_list(base):
            self._axis(sl)
            return base if isinstance(sl, ast.Slice) else base[1]
        if is_tuple(base):
            self._axis(sl)
            if isinstance(sl, ast.Constant) and isinstance(sl.value, int) \
                    and 0 <= sl.value < len(base[1]):
                return base[1][sl.value]
            return elem_of(base)
        base_c = collapse(base)
        if base_c not in (LANE, CARRY):
            self._axis(sl)
            return base_c
        # lane-carrying base: classify the index
        if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
            return base_c  # dict-of-lane-arrays access ({"map": ...})
        if isinstance(sl, ast.Slice):
            self._axis(sl)
            return base_c  # leading slice keeps the lane axis
        if isinstance(sl, ast.Tuple) and sl.elts and isinstance(
            sl.elts[0], (ast.Slice, ast.Constant)
        ) and (
            isinstance(sl.elts[0], ast.Slice)
            or sl.elts[0].value is Ellipsis
        ):
            for e in sl.elts:
                self._axis(e)
            return base_c  # [:, k] / [..., None]: lane axis intact
        if isinstance(sl, ast.Constant) and sl.value is Ellipsis:
            return base_c
        # anything else — int literal, negative index, mask, array —
        # indexes ALONG the lane axis: a cross-lane gather
        self._axis(sl)
        return self._cross(
            node, "gather", "gather",
            "lane-axis indexed gather (`x[i]`/`x[mask]` drops or "
            "permutes the sharded axis)",
        )

    # -- calls ----------------------------------------------------------------

    def _call_axis(self, node: ast.Call) -> Axis:
        name = dotted_name(node.func)
        resolved = self.mi.importmap.resolve(name) if name else None

        # jax.vmap(f)(...) / jax.pmap(f)(...): the mapped result is
        # lane-leading; the body is per-lane code — never walked here
        if isinstance(node.func, ast.Call):
            inner = dotted_name(node.func.func)
            inner_res = self.mi.importmap.resolve(inner) if inner else None
            if inner_res in ("jax.vmap", "jax.pmap"):
                for a in node.args:
                    self._axis(a)
                return LANE
            # call of a call we can't see (pallas_call(...)(*ins), cached
            # runners): a host container of results whose elements join
            # the outer args — covers the pallas_call list-return idiom
            # without reading `outs[i]` as a lane gather
            out: Axis = FREE
            for a in node.args:
                out = join(out, elem_of(self._axis(a))
                           if not isinstance(a, ast.Starred)
                           else elem_of(self._axis(a.value)))
            return ("list", collapse(out))

        # control-flow combinators thread carries element-wise
        if resolved in ("lax.while_loop", "jax.lax.while_loop"):
            return self._while_loop_axis(node)
        if resolved in ("lax.cond", "jax.lax.cond"):
            return self._cond_axis(node)
        if resolved in ("lax.scan", "jax.lax.scan"):
            return self._scan_axis(node)
        if resolved in ("lax.reduce", "jax.lax.reduce"):
            operand = self._axis(node.args[0]) if node.args else FREE
            for a in node.args[1:]:
                self._axis(a)
            if laneish(operand) and axis_hits_lane(_axis_kw(node, positional=3)):
                return self._cross(
                    node, "reduce", "or",
                    "`lax.reduce` over the lane axis",
                )
            return operand if laneish(operand) else FREE

        # np.<ufunc>.reduce(x, axis=...) — the host-side fold idiom
        if resolved and resolved.endswith(".reduce") and resolved.split(".")[0] in (
            "np", "numpy", "jnp", "jax"
        ):
            operand = self._axis(node.args[0]) if node.args else FREE
            for a in node.args[1:]:
                self._axis(a)
            for kw in node.keywords:
                self._axis(kw.value)
            if laneish(operand) and axis_hits_lane(_axis_kw(node)):
                ufunc = resolved.split(".")[-2]
                return self._cross(
                    node, "reduce",
                    {"bitwise_or": "or", "logical_or": "or", "add": "sum"}.get(
                        ufunc, "?"
                    ),
                    f"`{resolved}` over the lane axis",
                )
            return operand

        # reductions by dotted name (jnp.any(x), np.sum(x, axis=0), ...)
        if resolved:
            head, _, tail = resolved.rpartition(".")
            if tail in _REDUCE_FNS and (head + ".") .startswith(_REDUCE_PREFIXES):
                return self._reduction(node, tail, first_arg=True)
            if tail in _GATHER_FNS and (head + ".").startswith(_REDUCE_PREFIXES):
                operand = self._axis(node.args[0]) if node.args else FREE
                for a in node.args[1:]:
                    self._axis(a)
                if laneish(operand):
                    return self._cross(
                        node, "gather", "gather",
                        f"`{resolved}` indexes along the lane axis",
                    )
                return FREE
            if tail == "take_along_axis" and (head + ".").startswith(_REDUCE_PREFIXES):
                operand = self._axis(node.args[0]) if node.args else FREE
                for a in node.args[1:]:
                    self._axis(a)
                ax_val = _axis_kw(node, positional=2)
                if laneish(operand) and axis_hits_lane(ax_val):
                    return self._cross(
                        node, "gather", "gather",
                        "`take_along_axis` over the lane axis",
                    )
                return operand
            if tail in ("reshape", "ravel") and (head + ".").startswith(
                _REDUCE_PREFIXES
            ):
                operand = self._axis(node.args[0]) if node.args else FREE
                for a in node.args[1:]:
                    self._axis(a)
                if laneish(operand):
                    return self._cross(
                        node, "reshape", "?",
                        f"`{resolved}` on a lane-axis value — the sharded "
                        f"axis does not survive a reshape",
                    )
                return FREE

        # python host sinks on lane values (S003 raw material); host
        # containers (len of a list of arrays) are plumbing, not traffic
        if resolved in _HOST_SINKS and "." not in (resolved or "."):
            args_ax = [self._axis(a) for a in node.args]
            if any(
                laneish(a) and not is_list(a) and not is_tuple(a)
                for a in args_ax
            ):
                self._host_sink(node, f"`{resolved}()` on a lane-axis value")
            return FREE

        # method calls on an array receiver
        if isinstance(node.func, ast.Attribute):
            recv_attr = node.func.attr
            if recv_attr in _REDUCE_METHODS:
                recv = self._axis(node.func.value)
                for a in node.args:
                    self._axis(a)
                for kw in node.keywords:
                    self._axis(kw.value)
                if laneish(recv) and axis_hits_lane(_axis_kw(node)):
                    return self._cross(
                        node, "reduce",
                        {"sum": "sum", "any": "any", "all": "any",
                         "max": "max", "min": "min", "cumsum": "scan",
                         "cumprod": "scan"}.get(recv_attr, "?"),
                        f"`.{recv_attr}()` over the lane axis",
                    )
                return recv if laneish(recv) else FREE
            if recv_attr in _RESHAPE_METHODS:
                recv = self._axis(node.func.value)
                for a in node.args:
                    self._axis(a)
                if laneish(recv):
                    return self._cross(
                        node, "reshape", "?",
                        f"`.{recv_attr}()` on a lane-axis value — the "
                        f"sharded axis does not survive",
                    )
                return FREE
            if recv_attr in ("astype", "copy", "clip", "block_until_ready",
                            "tolist", "item", "squeeze", "view"):
                recv = self._axis(node.func.value)
                for a in node.args:
                    self._axis(a)
                if recv_attr in ("tolist", "item"):
                    return FREE
                return recv
            if recv_attr == "replace":
                recv = self._axis(node.func.value)
                if collapse(recv) == CARRY:
                    # flax struct rebuild: same S002 site as a constructor
                    cls = self._carry_class_of(node.func.value)
                    self._rebuild(node, cls or "replace")
                    return CARRY
            if recv_attr in ("set", "add", "multiply", "get"):  # .at[w].set(v)
                recv = self._axis(node.func.value)
                for a in node.args:
                    self._axis(a)
                return recv

        # project calls descend with real argument axes
        kind, target = resolve_callee(node, self.fn, self.engine.model)
        if kind == "project":
            assert isinstance(target, FunctionInfo)
            if target.class_name is None and target.qualname in \
                    self.engine.carry_classes:
                pass  # constructor resolved as fn — handled below
            args = self._map_args(node, target)
            closure = None
            if "<locals>" in target.qualname and target.module == self.fn.module:
                closure = dict(self.env)  # nested def: python closure
            return self.engine._walk(
                target, args=args, region=self.region, chain=self.chain,
                closure=closure,
            )

        # carry constructors (rebuild sites)
        if resolved:
            tail = resolved.split(".")[-1]
            if tail in self.engine.carry_classes:
                self._rebuild(node, tail)
                return CARRY

        # np.asarray keeps the axis (a host copy still lane-indexes);
        # np.zeros/arange/... make fresh FREE values
        if resolved in ("np.asarray", "numpy.asarray", "np.array",
                        "numpy.array", "jnp.asarray", "jax.numpy.asarray"):
            return join(*(self._axis(a) for a in node.args)) if node.args else FREE

        # extern/opaque: conservative join of arguments
        out: Axis = FREE
        for a in node.args:
            out = join(out, self._axis(a))
        for kw in node.keywords:
            out = join(out, self._axis(kw.value))
        return collapse(out)

    def _carry_class_of(self, node: ast.expr) -> Optional[str]:
        """Best-effort class name for a `.replace()` receiver: `c` ->
        look for the nearest carry constructor assigned to that name in
        this body; falls back to None (reported as `replace`)."""
        if not isinstance(node, ast.Name):
            return None
        for n in ast.walk(self.fn.node):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                    isinstance(n.targets[0], ast.Name) and \
                    n.targets[0].id == node.id and isinstance(n.value, ast.Call):
                cname = dotted_name(n.value.func)
                if cname and cname.split(".")[-1] in self.engine.carry_classes:
                    return cname.split(".")[-1]
        return None

    def _reduction(self, node: ast.Call, opname: str, first_arg: bool) -> Axis:
        operand = self._axis(node.args[0]) if node.args else FREE
        for a in node.args[1:]:
            self._axis(a)
        for kw in node.keywords:
            self._axis(kw.value)
        if laneish(operand) and axis_hits_lane(_axis_kw(node)):
            return self._cross(
                node, "scan" if opname in ("cumsum", "cumprod") else "reduce",
                {"sum": "sum", "any": "any", "all": "any", "max": "max",
                 "min": "min", "cumsum": "scan", "bincount": "sum"}.get(
                    opname, "?"
                ),
                f"`{opname}` over the lane axis",
            )
        return operand if laneish(operand) else FREE

    # -- combinators ----------------------------------------------------------

    def _branch_fn(self, node: ast.expr) -> Optional[FunctionInfo]:
        if isinstance(node, ast.Name) and node.id in self.fn.locals_fns:
            return self.mi.functions.get(self.fn.locals_fns[node.id])
        name = dotted_name(node)
        if name is not None:
            call = ast.Call(func=node, args=[], keywords=[])
            ast.copy_location(call, node)
            kind, target = resolve_callee(call, self.fn, self.engine.model)
            if kind == "project":
                return target  # type: ignore[return-value]
        return None

    def _apply_branch(self, branch: ast.expr, args: List[Axis]) -> Axis:
        if isinstance(branch, ast.Lambda):
            lam_env = dict(self.env)
            params = [p.arg for p in branch.args.args]
            for p, a in zip(params, args):
                lam_env[p] = a
            sub = _AxisWalk(
                self.engine, self.fn, env=lam_env, region=self.region,
                chain=self.chain, pinned={},
            )
            # lambdas have an expression body; evaluate it directly
            return sub._axis(branch.body)
        target = self._branch_fn(branch)
        if target is None:
            return join(*args) if args else FREE
        params = [p for p in target.params if p != "self"]
        mapped = {p: a for p, a in zip(params, args)}
        closure = None
        if "<locals>" in target.qualname and target.module == self.fn.module:
            closure = dict(self.env)
        return self.engine._walk(
            target, args=mapped, region=self.region, chain=self.chain,
            closure=closure,
        )

    def _while_loop_axis(self, node: ast.Call) -> Axis:
        if len(node.args) < 3:
            return FREE
        cond, body, init = node.args[0], node.args[1], node.args[2]
        init_ax = self._axis(init)
        self._apply_branch(cond, [init_ax])
        self._apply_branch(body, [init_ax])
        return init_ax

    def _cond_axis(self, node: ast.Call) -> Axis:
        if len(node.args) < 3:
            return FREE
        pred, t_branch, f_branch = node.args[0], node.args[1], node.args[2]
        self._axis(pred)
        operands = [self._axis(a) for a in node.args[3:]]
        return join(
            self._apply_branch(t_branch, operands),
            self._apply_branch(f_branch, operands),
        )

    def _scan_axis(self, node: ast.Call) -> Axis:
        if len(node.args) < 2:
            return FREE
        f, init = node.args[0], node.args[1]
        init_ax = self._axis(init)
        xs_ax = [self._axis(a) for a in node.args[2:]]
        if any(laneish(a) for a in xs_ax):
            # scanning OVER the lane axis serializes the lanes — the
            # exact opposite of the sharding plan
            return self._cross(
                node, "scan", "scan",
                "`lax.scan` over the lane axis (serializes the lanes)",
            )
        self._apply_branch(f, [init_ax, FREE])
        return ("tuple", [init_ax, FREE])

    # -- argument mapping -----------------------------------------------------

    def _map_args(self, call: ast.Call, target: FunctionInfo) -> Dict[str, Axis]:
        params = [p for p in target.params if p != "self"]
        out: Dict[str, Axis] = {}
        for i, a in enumerate(call.args):
            if isinstance(a, ast.Starred):
                ax = self._axis(a.value)
                for p in params[i:]:
                    out[p] = collapse(ax)
                break
            if i < len(params):
                out[params[i]] = self._axis(a)
            else:
                self._axis(a)
        for kw in call.keywords:
            if kw.arg is not None and kw.arg in params:
                out[kw.arg] = self._axis(kw.value)
            else:
                self._axis(kw.value)
        return out


def make_finding(rule: str, severity: str, rel: str, line: int, col: int,
                 message: str) -> Finding:
    return Finding(
        rule=rule, severity=severity, path=rel, line=line, col=col,
        message=message,
    )
