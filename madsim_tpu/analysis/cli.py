"""`python -m madsim_tpu lint` — driver, output formats, exit codes.

Exit codes (pre-commit friendly):
  0  clean (or everything suppressed/baselined)
  1  findings
  2  usage / internal error (bad paths, unparseable baseline)

The D/C-AST/G passes are stdlib-only; the C import half (model
contracts) imports jax and runs by default when any linted file defines
a Machine subclass — `--no-import-check` keeps a pre-commit hook
jax-free and fast.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Sequence

from . import crules, drules, grules, layers, lintcache, rrules, srules, trules
from .findings import (
    DEFAULT_BASELINE_NAME,
    Finding,
    apply_baseline,
    baseline_growth,
    filter_suppressed,
    load_baseline,
    sarif_doc,
    save_baseline,
)

JSON_SCHEMA_VERSION = 1

# directories never worth descending into
_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules", ".claude"}


def add_lint_args(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "paths", nargs="*", default=None,
        help="files/directories to lint (default: the madsim_tpu package "
             "of the enclosing repo)",
    )
    p.add_argument(
        "--rules", default=None,
        help="comma list of rule families or IDs to run (e.g. D,G or "
             "D003,C001); default all",
    )
    p.add_argument("--json", action="store_true", help="machine-readable output")
    p.add_argument(
        "--github", action="store_true",
        help="GitHub workflow-command annotations (::error file=...)",
    )
    p.add_argument(
        "--sarif", default=None, metavar="OUT.sarif",
        help="also write a SARIF 2.1.0 report to this path (composable "
             "with any output mode)",
    )
    p.add_argument(
        "--changed", action="store_true",
        help="git-diff-scoped run: lint only files git reports changed "
             "(staged + unstaged + untracked) plus their reverse "
             "import-graph dependents; the whole-program passes scope "
             "to the zones the change can reach (T handler walks to "
             "the changed files, the T-executor/S step-path walks only "
             "when engine/ops/parallel/utils changed). The pre-commit "
             "path — a no-change run exits immediately",
    )
    p.add_argument(
        "--cache", action="store_true",
        help="reuse .madsim-lint-cache/ under the repo root: unchanged "
             "files replay their findings, a byte-identical repo "
             "replays the whole-program passes (the lint-fast / "
             "pre-commit path; CI stays cold)",
    )
    p.add_argument(
        "--force", action="store_true",
        help="with --update-baseline: allow the baseline to GROW "
             "(default is the shrink-only ratchet)",
    )
    p.add_argument(
        "--fix", action="store_true",
        help="apply the mechanical fixes (sorted() set iteration, "
             "ordered=True callbacks) in place, then re-lint",
    )
    p.add_argument(
        "--baseline", default=None,
        help=f"baseline file (default: {DEFAULT_BASELINE_NAME} at the "
             f"repo root when present)",
    )
    p.add_argument(
        "--update-baseline", action="store_true",
        help="write all current findings to the baseline file and exit 0",
    )
    p.add_argument(
        "--no-import-check", action="store_true",
        help="skip the C-rule import half (no jax import; AST-only run)",
    )
    p.add_argument(
        "--repo-root", default=None,
        help="repo root for the G-pass cross-checks (default: walk up "
             "from the first path)",
    )
    p.add_argument("-v", "--verbose", action="store_true")


def _collect_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(
                    d for d in dirnames if d not in _SKIP_DIRS
                )
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.append(os.path.join(dirpath, fn))
        else:
            raise FileNotFoundError(p)
    return out


def _rule_selected(rule: str, selector: Optional[Sequence[str]]) -> bool:
    if not selector:
        return True
    return any(rule == s or rule.startswith(s) for s in selector)


def projectmodel_build(root: str, notes: List[str]):
    from . import projectmodel

    if not os.path.isdir(os.path.join(root, projectmodel.PACKAGE)):
        notes.append(f"{root}: no {projectmodel.PACKAGE}/ package; "
                     f"L/T passes skipped")
        return None
    model = projectmodel.build_model(root)
    for rel, err in model.broken:
        notes.append(f"{rel}: unparseable for the program model ({err})")
    return model


# -- git-diff scoping (`lint --changed`) --------------------------------------

# A change under these prefixes can move the step path's lane-axis /
# taint behavior — the T-executor and S walks re-run; anything else
# leaves the step path byte-identical and those walks are skipped.
STEP_PATH_PREFIXES = (
    "madsim_tpu/engine/", "madsim_tpu/ops/", "madsim_tpu/parallel/",
    "madsim_tpu/utils",
)


def git_changed_files(root: str) -> Optional[List[str]]:
    """Repo-relative paths git reports as changed (staged, unstaged and
    untracked). None when git is unavailable or `root` is not a work
    tree — callers fall back to a full run."""
    import subprocess

    try:
        proc = subprocess.run(
            ["git", "-C", root, "status", "--porcelain"],
            capture_output=True, text=True, timeout=30,
        )
    except (OSError, subprocess.SubprocessError):
        return None
    if proc.returncode != 0:
        return None
    out: List[str] = []
    for line in proc.stdout.splitlines():
        if len(line) < 4:
            continue
        path = line[3:]
        if " -> " in path:  # rename: the new side is the linted one
            path = path.split(" -> ")[-1]
        out.append(path.strip().strip('"'))
    return out


def scoped_files(model, root: str, changed: Sequence[str]) -> List[str]:
    """Absolute paths of the changed package files PLUS their reverse
    import-graph dependents (a change to a module can move findings in
    every module that imports it — eagerly or lazily)."""
    rev: Dict[str, set] = {}
    for mi in model.modules.values():
        for edge in mi.imports:
            for target in model._project_targets(edge.target):
                rev.setdefault(target, set()).add(mi.name)
    by_rel = {mi.rel: mi for mi in model.modules.values()}
    queue = [by_rel[rel].name for rel in changed if rel in by_rel]
    seen = set(queue)
    while queue:
        cur = queue.pop()
        for dep in rev.get(cur, ()):
            if dep not in seen:
                seen.add(dep)
                queue.append(dep)
    return sorted(model.modules[name].path for name in seen)


def run_lint(
    paths: Sequence[str],
    *,
    rules: Optional[Sequence[str]] = None,
    import_check: bool = True,
    repo_root: Optional[str] = None,
    verbose: bool = False,
    notes: Optional[List[str]] = None,
    use_cache: bool = False,
    changed: Optional[Sequence[str]] = None,
) -> tuple:
    """Run the passes. Returns (findings, source_by_path) BEFORE
    suppression/baseline filtering — the caller owns policy (the cache
    also stores raw findings, so an edited suppression takes effect on
    a full cache hit). `changed` (repo-relative paths, the --changed
    scope) restricts the per-file passes to changed files + their
    reverse import-graph dependents and scopes the whole-program
    walks; None = everything."""
    import ast as _ast

    files = _collect_files(paths)
    findings: List[Finding] = []
    source_by_path: Dict[str, str] = {}
    notes = notes if notes is not None else []
    selector = [s.strip() for s in rules] if rules else None

    def family_selected(fam: str) -> bool:
        return selector is None or any(s and s[0] == fam for s in selector)

    root = repo_root or (grules.find_repo_root(files[0]) if files else None)
    cache = (
        lintcache.LintCache(root) if use_cache and root is not None else None
    )

    model = None
    if changed is not None and root is not None:
        model = projectmodel_build(root, notes)
        if model is not None:
            scope = set(scoped_files(model, root, changed))
            before = len(files)
            files = [f for f in files if os.path.abspath(f) in scope]
            notes.append(
                f"--changed: {len(files)}/{before} file(s) in scope "
                f"({len(changed)} changed)"
            )

    for path in files:
        try:
            with open(path, "r", encoding="utf-8") as fh:
                source = fh.read()
        except (OSError, UnicodeDecodeError) as exc:
            notes.append(f"{path}: unreadable ({exc!r})")
            continue
        source_by_path[path] = source
        if cache is not None:
            key = cache.file_key(source, import_check)
            cached = cache.get_file(path, key)
            if cached is not None:
                findings.extend(cached)
                continue
        per_file: List[Finding] = []
        try:
            tree = _ast.parse(source, filename=path)
        except SyntaxError as exc:
            findings.append(Finding(
                rule="D000", severity="error", path=path,
                line=exc.lineno or 0, col=(exc.offset or 1) - 1,
                message=f"syntax error: {exc.msg}",
            ))
            continue
        per_file.extend(drules.check_module(tree, source, path))
        per_file.extend(crules.check_module(tree, source, path))
        if import_check:
            from .astutils import machine_classes

            if machine_classes(tree):
                c_findings, skipped = crules.check_module_contracts(
                    tree, source, path
                )
                per_file.extend(c_findings)
                notes.extend(skipped)
        if cache is not None:
            cache.put_file(path, key, per_file)
        findings.extend(per_file)

    if root is None and files:
        notes.append(
            "no madsim_tpu repo root found above the linted paths; "
            "repo passes (G mirror cross-checks, L layer map, T taint, "
            "R RNG ledger) skipped"
        )
    elif root is not None:
        repo_findings: Optional[List[Finding]] = None
        repo_key = None
        # the repo cache only serves the FULL run (no selector, no
        # --changed scope): a partial run would poison it
        if cache is not None and selector is None and changed is None:
            repo_key = cache.repo_fileset_key(lintcache.repo_input_files(root))
            repo_findings = cache.get_repo(repo_key)
        if repo_findings is None:
            repo_findings = []
            # --changed scope for the expensive walks: the T-executor
            # and S step-path contexts only move when the step-path
            # zone moved; T handler walks scope to the changed files
            step_zone_touched = changed is None or any(
                rel.startswith(STEP_PATH_PREFIXES) for rel in changed
            )
            if family_selected("G"):
                repo_findings.extend(grules.check_repo(root))
            if family_selected("L") or family_selected("T") \
                    or family_selected("S"):
                if model is None:
                    model = projectmodel_build(root, notes)
                if model is not None:
                    if family_selected("L"):
                        repo_findings.extend(layers.check_model(model))
                    if family_selected("T"):
                        if changed is None:
                            repo_findings.extend(trules.check_model(model))
                        else:
                            repo_findings.extend(trules.check_model(
                                model,
                                executor_entrypoints=(
                                    trules.EXECUTOR_ENTRYPOINTS
                                    if step_zone_touched else ()
                                ),
                                handler_files=set(changed),
                            ))
                    if family_selected("S") and step_zone_touched:
                        repo_findings.extend(srules.check_model(model))
            if family_selected("R"):
                repo_findings.extend(rrules.check_repo(root))
            if cache is not None and repo_key is not None:
                cache.put_repo(repo_key, repo_findings)
        # repo passes report repo-relative paths; qualify with the root
        # when linting from elsewhere so editors can open them
        if os.path.abspath(root) != os.path.abspath(os.getcwd()):
            repo_findings = [
                Finding(
                    rule=f.rule, severity=f.severity,
                    path=os.path.join(root, f.path), line=f.line,
                    col=f.col, message=f.message, fixable=f.fixable,
                )
                for f in repo_findings
            ]
        findings.extend(repo_findings)
        # line-anchored repo findings (L/T/R) support inline
        # suppressions — make their sources visible to the filter
        for f in repo_findings:
            if f.line > 0 and f.path not in source_by_path:
                candidate = (
                    f.path if os.path.isabs(f.path)
                    else os.path.join(root, f.path)
                )
                try:
                    with open(candidate, "r", encoding="utf-8") as fh:
                        source_by_path[f.path] = fh.read()
                except OSError:
                    pass

    if cache is not None:
        cache.save()
        if verbose:
            notes.append(
                f"cache: {cache.hits} file hit(s), {cache.misses} miss(es)"
            )

    findings = [f for f in findings if _rule_selected(f.rule, selector)]

    # dedup (the taint pass can flag one expression through two node
    # shapes) and order stably for output + baseline
    seen = set()
    unique: List[Finding] = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule)):
        # positional dedup for source findings (the taint pass can flag
        # one expression through two node shapes); repo-level findings
        # all sit at line 0, so their identity is the message
        key = (
            (f.rule, f.path, f.line, f.col) if f.line
            else (f.rule, f.path, f.message)
        )
        if key in seen:
            continue
        seen.add(key)
        unique.append(f)
    return unique, source_by_path


def main(args: argparse.Namespace) -> int:
    paths = list(args.paths or [])
    repo_root = args.repo_root
    if not paths:
        root = grules.find_repo_root(os.getcwd())
        if root is None:
            print(
                "lint: no paths given and no madsim_tpu repo above cwd",
                file=sys.stderr,
            )
            return 2
        paths = [os.path.join(root, "madsim_tpu")]
        repo_root = repo_root or root

    rules = args.rules.split(",") if args.rules else None
    notes: List[str] = []

    try:
        files_exist = _collect_files(paths)
    except FileNotFoundError as exc:
        print(f"lint: no such path: {exc}", file=sys.stderr)
        return 2
    del files_exist

    if args.fix:
        from .fixes import fix_source

        fixed_total = 0
        for path in _collect_files(paths):
            with open(path, "r", encoding="utf-8") as fh:
                src = fh.read()
            try:
                new_src, n = fix_source(src, path)
            except SyntaxError:
                continue
            if n:
                with open(path, "w", encoding="utf-8") as fh:
                    fh.write(new_src)
                fixed_total += n
                if not args.json:
                    print(f"fixed {n} finding(s) in {path}")
        if fixed_total and not args.json:
            print(f"--fix applied {fixed_total} edit(s); re-linting")

    changed = None
    if getattr(args, "changed", False):
        git_root = repo_root or grules.find_repo_root(
            paths[0] if paths else os.getcwd()
        )
        changed = git_changed_files(git_root) if git_root else None
        if changed is None:
            notes.append("--changed: git unavailable here; full run")
        else:
            # lint-relevant inputs: package sources plus the repo-pass
            # cross-check files (golden/gate test pins, the RNG manifest)
            changed = [
                r for r in changed
                if (r.startswith("madsim_tpu/") and r.endswith(".py"))
                or r in (grules.GATES_TEST, grules.GOLDEN_TEST, grules.MANIFEST)
            ]
            if not changed:
                if not args.json and not args.github:
                    print("lint: --changed: no lint-relevant files changed")
                return 0

    try:
        findings, sources = run_lint(
            paths,
            rules=rules,
            import_check=not args.no_import_check,
            repo_root=repo_root,
            verbose=args.verbose,
            notes=notes,
            use_cache=getattr(args, "cache", False),
            changed=changed,
        )
    except FileNotFoundError as exc:
        print(f"lint: no such path: {exc}", file=sys.stderr)
        return 2

    findings = filter_suppressed(findings, sources)

    baseline_path = args.baseline
    if baseline_path is None:
        root = repo_root or grules.find_repo_root(
            paths[0] if paths else os.getcwd()
        )
        if root is not None:
            candidate = os.path.join(root, DEFAULT_BASELINE_NAME)
            if os.path.exists(candidate):
                baseline_path = candidate

    if args.update_baseline:
        target = baseline_path or os.path.join(
            repo_root or os.getcwd(), DEFAULT_BASELINE_NAME
        )
        # the ratchet: a baseline may SHRINK freely (debt paid down) but
        # refuses to grow — new findings are new debt, and absorbing
        # them silently is how a strict baseline rots into a landfill
        if os.path.exists(target) and not getattr(args, "force", False):
            try:
                old_entries = load_baseline(target)
            except (OSError, ValueError, KeyError) as exc:
                print(f"lint: bad baseline {target}: {exc}", file=sys.stderr)
                return 2
            grown = baseline_growth(old_entries, findings)
            if grown:
                print(
                    f"lint: refusing to GROW the baseline ({len(grown)} "
                    f"new finding(s) not in {target}) — the ratchet is "
                    f"shrink-only. Fix or inline-suppress them, or pass "
                    f"--force to grandfather deliberately:",
                    file=sys.stderr,
                )
                for f in grown:
                    print(f"  + {f.text()}", file=sys.stderr)
                return 2
        save_baseline(target, findings)
        print(f"baseline: wrote {len(findings)} finding(s) to {target}")
        return 0

    baselined = 0
    if baseline_path:
        try:
            entries = load_baseline(baseline_path)
        except (OSError, ValueError, KeyError) as exc:
            print(f"lint: bad baseline {baseline_path}: {exc}", file=sys.stderr)
            return 2
        findings, consumed = apply_baseline(findings, entries)
        baselined = len(consumed)

    if args.verbose:
        for note in notes:
            print(f"note: {note}", file=sys.stderr)

    if getattr(args, "sarif", None):
        from .lintcache import RULES_VERSION

        doc = sarif_doc(findings, RULES_VERSION)
        with open(args.sarif, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, indent=2, sort_keys=True)
            fh.write("\n")
        if not args.json:
            print(f"sarif: wrote {len(findings)} result(s) to {args.sarif}")

    if args.json:
        doc = {
            "version": JSON_SCHEMA_VERSION,
            "findings": [f.json_dict() for f in findings],
            "counts": {
                "error": sum(1 for f in findings if f.severity == "error"),
                "warning": sum(1 for f in findings if f.severity == "warning"),
                "baselined": baselined,
            },
        }
        print(json.dumps(doc, indent=2, sort_keys=True))
    elif args.github:
        for f in findings:
            print(f.github())
    else:
        for f in findings:
            print(f.text())

    if not args.json and not args.github:
        if findings:
            n_err = sum(1 for f in findings if f.severity == "error")
            tail = f", {baselined} baselined" if baselined else ""
            print(f"lint: {n_err} error(s), {len(findings) - n_err} "
                  f"warning(s){tail}")
        else:
            tail = f" ({baselined} baselined)" if baselined else ""
            print(f"lint: clean{tail}")

    return 1 if findings else 0
