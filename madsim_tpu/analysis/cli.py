"""`python -m madsim_tpu lint` — driver, output formats, exit codes.

Exit codes (pre-commit friendly):
  0  clean (or everything suppressed/baselined)
  1  findings
  2  usage / internal error (bad paths, unparseable baseline)

The D/C-AST/G passes are stdlib-only; the C import half (model
contracts) imports jax and runs by default when any linted file defines
a Machine subclass — `--no-import-check` keeps a pre-commit hook
jax-free and fast.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Sequence

from . import crules, drules, grules
from .findings import (
    DEFAULT_BASELINE_NAME,
    Finding,
    apply_baseline,
    filter_suppressed,
    load_baseline,
    save_baseline,
)

JSON_SCHEMA_VERSION = 1

# directories never worth descending into
_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules", ".claude"}


def add_lint_args(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "paths", nargs="*", default=None,
        help="files/directories to lint (default: the madsim_tpu package "
             "of the enclosing repo)",
    )
    p.add_argument(
        "--rules", default=None,
        help="comma list of rule families or IDs to run (e.g. D,G or "
             "D003,C001); default all",
    )
    p.add_argument("--json", action="store_true", help="machine-readable output")
    p.add_argument(
        "--github", action="store_true",
        help="GitHub workflow-command annotations (::error file=...)",
    )
    p.add_argument(
        "--fix", action="store_true",
        help="apply the mechanical fixes (sorted() set iteration, "
             "ordered=True callbacks) in place, then re-lint",
    )
    p.add_argument(
        "--baseline", default=None,
        help=f"baseline file (default: {DEFAULT_BASELINE_NAME} at the "
             f"repo root when present)",
    )
    p.add_argument(
        "--update-baseline", action="store_true",
        help="write all current findings to the baseline file and exit 0",
    )
    p.add_argument(
        "--no-import-check", action="store_true",
        help="skip the C-rule import half (no jax import; AST-only run)",
    )
    p.add_argument(
        "--repo-root", default=None,
        help="repo root for the G-pass cross-checks (default: walk up "
             "from the first path)",
    )
    p.add_argument("-v", "--verbose", action="store_true")


def _collect_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(
                    d for d in dirnames if d not in _SKIP_DIRS
                )
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.append(os.path.join(dirpath, fn))
        else:
            raise FileNotFoundError(p)
    return out


def _rule_selected(rule: str, selector: Optional[Sequence[str]]) -> bool:
    if not selector:
        return True
    return any(rule == s or rule.startswith(s) for s in selector)


def run_lint(
    paths: Sequence[str],
    *,
    rules: Optional[Sequence[str]] = None,
    import_check: bool = True,
    repo_root: Optional[str] = None,
    verbose: bool = False,
    notes: Optional[List[str]] = None,
) -> tuple:
    """Run the passes. Returns (findings, source_by_path) BEFORE
    suppression/baseline filtering — the caller owns policy."""
    import ast as _ast

    files = _collect_files(paths)
    findings: List[Finding] = []
    source_by_path: Dict[str, str] = {}
    notes = notes if notes is not None else []

    for path in files:
        try:
            with open(path, "r", encoding="utf-8") as fh:
                source = fh.read()
        except (OSError, UnicodeDecodeError) as exc:
            notes.append(f"{path}: unreadable ({exc!r})")
            continue
        source_by_path[path] = source
        try:
            tree = _ast.parse(source, filename=path)
        except SyntaxError as exc:
            findings.append(Finding(
                rule="D000", severity="error", path=path,
                line=exc.lineno or 0, col=(exc.offset or 1) - 1,
                message=f"syntax error: {exc.msg}",
            ))
            continue
        findings.extend(drules.check_module(tree, source, path))
        findings.extend(crules.check_module(tree, source, path))
        if import_check:
            from .astutils import machine_classes

            if machine_classes(tree):
                c_findings, skipped = crules.check_module_contracts(
                    tree, source, path
                )
                findings.extend(c_findings)
                notes.extend(skipped)

    root = repo_root or (grules.find_repo_root(files[0]) if files else None)
    if root is None and files:
        notes.append(
            "no madsim_tpu repo root found above the linted paths; "
            "G-pass (mirror cross-checks) skipped"
        )
    elif root is not None:
        g = grules.check_repo(root)
        # G findings report repo-relative paths; qualify with the root
        # when linting from elsewhere so editors can open them
        if os.path.abspath(root) != os.path.abspath(os.getcwd()):
            g = [
                Finding(
                    rule=f.rule, severity=f.severity,
                    path=os.path.join(root, f.path), line=f.line,
                    col=f.col, message=f.message, fixable=f.fixable,
                )
                for f in g
            ]
        findings.extend(g)

    selector = [s.strip() for s in rules] if rules else None
    findings = [f for f in findings if _rule_selected(f.rule, selector)]

    # dedup (the taint pass can flag one expression through two node
    # shapes) and order stably for output + baseline
    seen = set()
    unique: List[Finding] = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule)):
        # positional dedup for source findings (the taint pass can flag
        # one expression through two node shapes); repo-level findings
        # all sit at line 0, so their identity is the message
        key = (
            (f.rule, f.path, f.line, f.col) if f.line
            else (f.rule, f.path, f.message)
        )
        if key in seen:
            continue
        seen.add(key)
        unique.append(f)
    return unique, source_by_path


def main(args: argparse.Namespace) -> int:
    paths = list(args.paths or [])
    repo_root = args.repo_root
    if not paths:
        root = grules.find_repo_root(os.getcwd())
        if root is None:
            print(
                "lint: no paths given and no madsim_tpu repo above cwd",
                file=sys.stderr,
            )
            return 2
        paths = [os.path.join(root, "madsim_tpu")]
        repo_root = repo_root or root

    rules = args.rules.split(",") if args.rules else None
    notes: List[str] = []

    try:
        files_exist = _collect_files(paths)
    except FileNotFoundError as exc:
        print(f"lint: no such path: {exc}", file=sys.stderr)
        return 2
    del files_exist

    if args.fix:
        from .fixes import fix_source

        fixed_total = 0
        for path in _collect_files(paths):
            with open(path, "r", encoding="utf-8") as fh:
                src = fh.read()
            try:
                new_src, n = fix_source(src, path)
            except SyntaxError:
                continue
            if n:
                with open(path, "w", encoding="utf-8") as fh:
                    fh.write(new_src)
                fixed_total += n
                if not args.json:
                    print(f"fixed {n} finding(s) in {path}")
        if fixed_total and not args.json:
            print(f"--fix applied {fixed_total} edit(s); re-linting")

    try:
        findings, sources = run_lint(
            paths,
            rules=rules,
            import_check=not args.no_import_check,
            repo_root=repo_root,
            verbose=args.verbose,
            notes=notes,
        )
    except FileNotFoundError as exc:
        print(f"lint: no such path: {exc}", file=sys.stderr)
        return 2

    findings = filter_suppressed(findings, sources)

    baseline_path = args.baseline
    if baseline_path is None:
        root = repo_root or grules.find_repo_root(
            paths[0] if paths else os.getcwd()
        )
        if root is not None:
            candidate = os.path.join(root, DEFAULT_BASELINE_NAME)
            if os.path.exists(candidate):
                baseline_path = candidate

    if args.update_baseline:
        target = baseline_path or os.path.join(
            repo_root or os.getcwd(), DEFAULT_BASELINE_NAME
        )
        save_baseline(target, findings)
        print(f"baseline: wrote {len(findings)} finding(s) to {target}")
        return 0

    baselined = 0
    if baseline_path:
        try:
            entries = load_baseline(baseline_path)
        except (OSError, ValueError, KeyError) as exc:
            print(f"lint: bad baseline {baseline_path}: {exc}", file=sys.stderr)
            return 2
        findings, consumed = apply_baseline(findings, entries)
        baselined = len(consumed)

    if args.verbose:
        for note in notes:
            print(f"note: {note}", file=sys.stderr)

    if args.json:
        doc = {
            "version": JSON_SCHEMA_VERSION,
            "findings": [f.json_dict() for f in findings],
            "counts": {
                "error": sum(1 for f in findings if f.severity == "error"),
                "warning": sum(1 for f in findings if f.severity == "warning"),
                "baselined": baselined,
            },
        }
        print(json.dumps(doc, indent=2, sort_keys=True))
    elif args.github:
        for f in findings:
            print(f.github())
    else:
        for f in findings:
            print(f.text())

    if not args.json and not args.github:
        if findings:
            n_err = sum(1 for f in findings if f.severity == "error")
            tail = f", {baselined} baselined" if baselined else ""
            print(f"lint: {n_err} error(s), {len(findings) - n_err} "
                  f"warning(s){tail}")
        else:
            tail = f" ({baselined} baselined)" if baselined else ""
            print(f"lint: clean{tail}")

    return 1 if findings else 0
