"""S-rules: sharding readiness — the lane-axis contract, machine-checked.

The mesh rebuild (`NamedSharding(mesh, P('batch'))` over the lane axis
of `StreamCarry`, ROADMAP [scale]) is only cheap if per-lane state
never crosses chips except at a few designed collectives. Until now
that claim was prose; these rules make it a blocking, ENUMERATED
contract over the `axes.py` lane-axis dataflow:

S001  a cross-lane reduction/gather/scan/reshape (an `axis=0` sum,
      `jnp.any` over lanes, a `bitwise_or.reduce`, a lane-indexed
      gather, a lane-axis cumsum, a reshape that drops the lane axis)
      outside the declared whitelist. Every designed collective carries
      an inline ``# madsim: collective(<name>, reduce=...)`` annotation
      naming an entry in `COLLECTIVES` below — the registry IS the
      all-reduce plan the sharding PR implements. Also S001: an
      annotation naming an unregistered collective, an annotation whose
      `reduce=` disagrees with the registry or with the op the analysis
      sees, a registry entry no annotation references (stale plan), and
      an annotation on a line where the analysis finds nothing
      cross-lane (dead annotation).
S002  `StreamCarry` axis discipline: every leaf of the carry (and of
      `LaneState`/`BatchResult`) is declared lane-leading or global in
      `CARRY_AXES`; a new leaf without a declaration, a declaration
      without a leaf, or a rebuild site (`StreamCarry(...)`,
      `carry.replace(...)`) that feeds a LANE-carrying value into a
      global-declared leaf (smuggling per-lane data into what the mesh
      will replicate = an implicit gather) all fail. The zero-length
      gate-off specializations (`fr_metrics`, `cov_map`, `fail_provs`)
      are global by design — a `[0]`-shaped leaf shards trivially.
S003  lane-axis-dependent Python control flow (if/while/assert/ternary,
      `len()`, iteration) in the step path — under a mesh every such
      read forces a cross-chip gather to one host; the designed pattern
      is to fold through a registered collective first.
S004  collective placement: a cross-lane op in the per-event inner loop
      (the `step` region — `step_batch` / `run_segment` bodies) rather
      than at segment/poll boundaries, or an annotated collective used
      in a region its registry entry does not allow. This is the perf
      half of the contract: near-linear 8-chip scaling is plausible
      only if collectives fire per SEGMENT, not per event. (The one
      designed exception, the while-cond done-mask, is registered with
      placement "step": a 1-bit all-reduce per event step is the
      early-exit check's irreducible cost.)

Same two-pass shape as `trules`: the interpreter (`axes.py`) walks the
entry contexts below over the `projectmodel` call graph; this module
owns policy — the registry, the carry axis tables, the entrypoints —
and turns the interpreter's events into findings. `jax.vmap` bodies
are per-lane code and exempt by construction (a cross-lane op cannot
be expressed inside them).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from . import axes
from .axes import CARRY, FREE, LANE, AxisEngine, EntryPoint, laneish
from .findings import Finding, Severity
from .projectmodel import ProjectModel

# -- the collective registry --------------------------------------------------
#
# One entry per designed cross-lane op. `reduce` is the combining op the
# mesh implements it with (jnp.any -> 1-bit or-all-reduce, sums ->
# psum, gathers -> the host-side ring drain / all_gather of failing
# lanes only); `placement` is where in the executor the op is allowed
# to fire (S004); `note` is the sharding plan, reviewed in this diff.

REDUCE_KINDS = ("or", "sum", "any", "max", "min", "gather", "scan")
REGIONS = ("step", "segment", "init", "final")


@dataclasses.dataclass(frozen=True)
class Collective:
    reduce: str  # one of REDUCE_KINDS
    placement: Tuple[str, ...]  # allowed regions
    note: str  # the all-reduce plan for the mesh rebuild


COLLECTIVES: Dict[str, Collective] = {
    "segment-done-any": Collective(
        "any", ("step",),
        "while-cond early-exit mask: becomes a 1-bit or-all-reduce per "
        "event step; keep — it is what lets a finished shard stop "
        "burning flops",
    ),
    "refill-count": Collective(
        "sum", ("segment",),
        "harvested-lane count for the refill: psum of a [L] bool at "
        "segment start",
    ),
    "refill-ranks": Collective(
        "scan", ("segment",),
        "gapless seed assignment ranks: a cross-shard exclusive scan "
        "over the done mask (or per-shard scan + psum of shard counts, "
        "the cheaper plan)",
    ),
    "harvest-completed": Collective(
        "sum", ("segment",),
        "completed-lane fold into the device-resident counter: psum "
        "per segment",
    ),
    "ring-append-ranks": Collective(
        "scan", ("segment",),
        "failing/abandoned-lane ring ranks: same exclusive-scan plan "
        "as refill-ranks",
    ),
    "ring-append-gather": Collective(
        "gather", ("segment",),
        "append failing lanes into the result ring: gathers ONLY "
        "masked lanes (the ring drain contract — never a full [L] "
        "all-gather)",
    ),
    "fr-fold": Collective(
        "sum", ("segment",),
        "flight-recorder totals of lanes finishing this segment: psum "
        "of small int32 vectors",
    ),
    "fr-hwm": Collective(
        "max", ("segment",),
        "flight-recorder high-water marks: pmax per segment",
    ),
    "cov-map-or": Collective(
        "or", ("segment",),
        "global coverage map fold: bitwise-or all-reduce of the packed "
        "[W] words per segment (the 'tiny all-reduces' the ROADMAP "
        "names). Executed as ops/coverage.cov_fold_words: shard-local "
        "or-reduce, then a bit-unpacked bool-any cross-device combine "
        "— integer or-all-reduce is unimplemented on the CPU collective "
        "runtime the mesh path is CI-proven on; exact either way",
    ),
    "cov-buffer-fold": Collective(
        "or", ("step",),
        "buffered-coverage segment-exit flush guard: a 1-bit "
        "or-all-reduce over the lanes' pending-slot counts, once per "
        "SEGMENT EXIT (run_segment's body region classifies as step, "
        "but the op sits after the while_loop — never per event); the "
        "flush it guards is per-lane (vmap/Pallas, no cross-lane "
        "traffic)",
    ),
    "seed-counter-init": Collective(
        "gather", ("init",),
        "next_seed = last seed + 1 at stream start: one scalar gather "
        "from the last lane, once per stream",
    ),
    "final-fail-gather": Collective(
        "gather", ("final",),
        "failing-lane (seed, code) harvest after the run: gathers only "
        "failing lanes to the host",
    ),
    "final-abandoned-gather": Collective(
        "gather", ("final",),
        "abandoned-lane seed harvest after the run (host-side)",
    ),
    "final-prov-gather": Collective(
        "gather", ("final",),
        "violation-provenance words of failing lanes, same drain as "
        "final-fail-gather",
    ),
    "final-cov-or": Collective(
        "or", ("final",),
        "host-side OR of per-lane coverage maps in the fixed-batch "
        "path: becomes the same or-all-reduce as cov-map-or",
    ),
    "multihost-completed-sum": Collective(
        "sum", ("final",),
        "replicated completion count across hosts (already a psum "
        "under jit with replicated out_shardings)",
    ),
    "multihost-fail-ranks": Collective(
        "scan", ("final",),
        "multihost failing-lane ring ranks (replicated scan)",
    ),
    "multihost-fail-ring": Collective(
        "gather", ("final",),
        "multihost failing-lane gather into the replicated "
        "fixed-capacity ring",
    ),
}

# -- carry axis tables (S002) -------------------------------------------------
#
# Every leaf of the streaming structs, declared: "lane" = lane-leading
# [L, ...] (shards under P('batch')), "global" = replicated device
# state (scalars, rings, the OR-folded coverage map). The class-def
# audit refuses a new leaf without a row here, and a row without a
# leaf — adding carry state FORCES an axis decision in this diff.

CARRY_AXES: Dict[str, Dict[str, str]] = {
    "StreamCarry": {
        "state": "lane",
        "seeds": "lane",
        "done": "lane",
        "next_seed": "global",
        "completed": "global",
        "segments": "global",
        "fail_seeds": "global",
        "fail_codes": "global",
        "fail_provs": "global",
        "fail_count": "global",
        "ab_seeds": "global",
        "ab_count": "global",
        "counters": "global",
        "fr_metrics": "global",
        "cov_map": "global",
    },
    "LaneState": {
        **{
            f: "lane"
            for f in (
                "now_us", "next_seq", "step", "rng_key", "done", "failed",
                "fail_code", "horizon_hit", "msg_count", "storm_loss",
                "delay_spike", "eq_time", "eq_seq", "eq_kind", "eq_node",
                "eq_src", "eq_payload", "eq_valid", "clogged", "killed",
                "paused_until", "skew_q10", "node_prov", "eq_prov",
                "fail_prov", "nodes", "ring", "fr", "cov",
            )
        },
        # dotted rows: documented sub-leaves of a dict-typed leaf (the
        # parent field must exist; the class-def audit skips them, see
        # check_model). The buffered-coverage slot ring and its count
        # are per-lane [L, C]/[L] state — they shard with the lane axis
        # like the map they flush into.
        "cov.map": "lane",
        "cov.buf": "lane",
        "cov.buf_n": "lane",
    },
    "BatchResult": {
        f: "lane"
        for f in (
            "seeds", "done", "failed", "fail_code", "fail_prov", "now_us",
            "steps", "msg_count", "summary", "ring", "fr", "cov",
        )
    },
}

# classes whose class-def field list is audited against CARRY_AXES
AUDITED_CLASSES: Tuple[Tuple[str, str], ...] = (
    ("madsim_tpu.engine.core", "StreamCarry"),
    ("madsim_tpu.engine.core", "LaneState"),
    ("madsim_tpu.engine.core", "BatchResult"),
)

# field -> axis lookup tables for the interpreter (derived from the
# axis tables; "state" is itself a classified struct)
CARRY_FIELDS: Set[str] = {"state"}


def _field_tables() -> Tuple[Set[str], Set[str]]:
    lane: Set[str] = set()
    free: Set[str] = set()
    for table in CARRY_AXES.values():
        for field, axis in table.items():
            # dotted sub-leaf rows document dict internals; the
            # interpreter's field lookup is by attribute name only
            if field in CARRY_FIELDS or "." in field:
                continue
            (lane if axis == "lane" else free).add(field)
    return lane, free


LANE_FIELDS, FREE_FIELDS = _field_tables()

# -- entry contexts -----------------------------------------------------------
#
# The streaming step path, plus the fixed-batch and multihost harvest
# paths the acceptance criteria name. `jax.vmap` bodies (the per-lane
# step, init_lane) are exempt by construction.

STREAM_ENTRYPOINTS: Tuple[EntryPoint, ...] = (
    EntryPoint("madsim_tpu.engine.core", "Engine.step_batch",
               "step", {"state": CARRY}),
    EntryPoint("madsim_tpu.engine.core", "Engine.run_segment",
               "step", {"state": CARRY}),
    EntryPoint("madsim_tpu.engine.core",
               "Engine._stream_fns.<locals>.init_carry",
               "init", {"seeds": LANE}),
    EntryPoint("madsim_tpu.engine.core",
               "Engine._stream_fns.<locals>._segment_impl",
               "segment", {"c": CARRY}),
    EntryPoint("madsim_tpu.engine.core",
               "Engine._stream_fns.<locals>.supersegment",
               "segment", {"c": CARRY, "need": FREE}),
    EntryPoint("madsim_tpu.engine.core",
               "Engine._stream_fns.<locals>.reset_rings",
               "segment", {"c": CARRY}),
    EntryPoint("madsim_tpu.engine.core", "Engine.run_batch",
               "final", {"seeds": LANE}),
    EntryPoint("madsim_tpu.engine.core", "Engine.run_seed_batch",
               "final", {}, pinned={"res": CARRY}),
    EntryPoint("madsim_tpu.engine.core", "Engine.failing_seeds",
               "final", {"result": CARRY}),
    EntryPoint("madsim_tpu.parallel.multihost",
               "run_batch_global.<locals>.stats",
               "final", {"r": CARRY}),
)

# functions whose bodies ARE the per-event inner loop, whatever region
# the caller walked in from (S004's "step" scope)
REGION_OVERRIDES: Dict[Tuple[str, str], str] = {
    ("madsim_tpu.engine.core", "Engine.step_batch"): "step",
    ("madsim_tpu.engine.core", "Engine.run_segment"): "step",
}

CARRY_CLASSES: Set[str] = {"StreamCarry", "LaneState", "BatchResult"}


# -- policy: events -> findings ----------------------------------------------


def _chain(chain: Tuple[str, ...]) -> str:
    return " -> ".join(chain)


def check_model(
    model: ProjectModel,
    *,
    entrypoints: Optional[Sequence[EntryPoint]] = None,
    collectives: Optional[Dict[str, Collective]] = None,
    carry_axes: Optional[Dict[str, Dict[str, str]]] = None,
    audited_classes: Optional[Sequence[Tuple[str, str]]] = None,
    carry_classes: Optional[Set[str]] = None,
    carry_fields: Optional[Set[str]] = None,
    region_overrides: Optional[Dict[Tuple[str, str], str]] = None,
    audit_registry: bool = True,
) -> List[Finding]:
    entrypoints = tuple(entrypoints if entrypoints is not None
                        else STREAM_ENTRYPOINTS)
    collectives = collectives if collectives is not None else COLLECTIVES
    carry_axes = carry_axes if carry_axes is not None else CARRY_AXES
    audited = tuple(audited_classes if audited_classes is not None
                    else AUDITED_CLASSES)
    carry_classes = carry_classes if carry_classes is not None else set(carry_axes)
    carry_fields = carry_fields if carry_fields is not None else CARRY_FIELDS

    lane_fields: Set[str] = set()
    free_fields: Set[str] = set()
    for table in carry_axes.values():
        for field, axis in table.items():
            if field in carry_fields:
                continue
            (lane_fields if axis == "lane" else free_fields).add(field)

    engine = AxisEngine(
        model,
        lane_fields=lane_fields,
        free_fields=free_fields,
        carry_fields=carry_fields,
        carry_classes=carry_classes,
        region_overrides=(region_overrides if region_overrides is not None
                          else REGION_OVERRIDES),
    )
    engine.run(entrypoints)

    findings: List[Finding] = []
    seen_names: Set[str] = set()

    # S001 / S004: cross-lane ops vs the registry
    for op in engine.cross_ops:
        ann = op.annotation
        if ann is None:
            findings.append(Finding(
                rule="S001", severity=Severity.ERROR, path=op.rel,
                line=op.line, col=op.col,
                message=(
                    f"cross-lane {op.kind}: {op.detail} — under "
                    f"P('batch') this is a cross-chip collective; "
                    f"either make it lane-parallel or declare it with "
                    f"`# madsim: collective(<name>, reduce={op.reduce})` "
                    f"and a registry entry (the mesh plan) "
                    f"[chain: {_chain(op.chain)}]"
                ),
            ))
            if op.region == "step":
                findings.append(Finding(
                    rule="S004", severity=Severity.WARNING, path=op.rel,
                    line=op.line, col=op.col,
                    message=(
                        f"cross-lane {op.kind} in the per-event inner "
                        f"loop (`step` region) — collectives belong at "
                        f"segment/poll boundaries; per-event cross-chip "
                        f"traffic sinks the near-linear scaling target "
                        f"[chain: {_chain(op.chain)}]"
                    ),
                ))
            continue
        entry = collectives.get(ann.name)
        if entry is None:
            findings.append(Finding(
                rule="S001", severity=Severity.ERROR, path=op.rel,
                line=op.line, col=op.col,
                message=(
                    f"collective annotation `{ann.name}` names no entry "
                    f"in the registry (analysis/srules.py COLLECTIVES) — "
                    f"the registry is the reviewed all-reduce plan; add "
                    f"the entry or fix the name"
                ),
            ))
            continue
        seen_names.add(ann.name)
        if ann.reduce != entry.reduce:
            findings.append(Finding(
                rule="S001", severity=Severity.ERROR, path=op.rel,
                line=op.line, col=op.col,
                message=(
                    f"collective `{ann.name}` annotated reduce="
                    f"{ann.reduce} but the registry declares "
                    f"{entry.reduce} — the annotation and the plan "
                    f"disagree"
                ),
            ))
        elif op.reduce not in ("?", ann.reduce) and not (
            # or/any are the same 1-bit fold family, and gather/scan
            # events are legitimate parts of composite collectives (a
            # ring append is a scan + a gather under one name)
            {op.reduce, ann.reduce} <= {"or", "any"}
            or op.reduce in ("gather", "scan")
        ):
            findings.append(Finding(
                rule="S001", severity=Severity.ERROR, path=op.rel,
                line=op.line, col=op.col,
                message=(
                    f"collective `{ann.name}` annotated reduce="
                    f"{ann.reduce} but the op the analysis sees is a "
                    f"{op.reduce} — annotation drift"
                ),
            ))
        if op.region not in entry.placement:
            findings.append(Finding(
                rule="S004", severity=Severity.WARNING, path=op.rel,
                line=op.line, col=op.col,
                message=(
                    f"collective `{ann.name}` fires in the `{op.region}` "
                    f"region but the registry allows "
                    f"{'/'.join(entry.placement)} — a collective drifting "
                    f"into a tighter loop is a silent scaling regression "
                    f"[chain: {_chain(op.chain)}]"
                ),
            ))

    # S001: stale registry entries (plan rows nothing implements)
    if audit_registry:
        for name in sorted(set(collectives) - seen_names):
            findings.append(Finding(
                rule="S001", severity=Severity.ERROR,
                path="madsim_tpu/analysis/srules.py", line=0, col=0,
                message=(
                    f"registry entry `{name}` is referenced by no "
                    f"collective annotation the analysis reaches — a "
                    f"stale all-reduce plan row; delete it or fix the "
                    f"annotation"
                ),
            ))
        # dead annotations: a collective(...) comment the analysis never
        # consumed claims a cross-lane op that does not exist (or moved)
        for mod in sorted(engine.walked_modules):
            mi = model.modules.get(mod)
            if mi is None:
                continue
            for ann in engine.annotations_of(mi).all:
                if (mi.rel, ann.lineno) not in engine.consumed_annotations:
                    findings.append(Finding(
                        rule="S001", severity=Severity.WARNING,
                        path=mi.rel, line=ann.lineno, col=0,
                        message=(
                            f"collective annotation `{ann.name}` is not "
                            f"anchored to any cross-lane op the analysis "
                            f"sees — dead annotation (the op moved, or "
                            f"the line placement is wrong)"
                        ),
                    ))

    # S002: rebuild sites — a LANE value into a global-declared leaf
    for rb in engine.rebuilds:
        table = carry_axes.get(rb.cls)
        if table is None:
            continue  # replace() on an unresolved receiver: skip
        declared = table.get(rb.field)
        if declared is None:
            findings.append(Finding(
                rule="S002", severity=Severity.ERROR, path=rb.rel,
                line=rb.line, col=rb.col,
                message=(
                    f"`{rb.cls}.{rb.field}` has no axis declaration in "
                    f"analysis/srules.py CARRY_AXES — every carry leaf "
                    f"must be declared lane-leading or global before "
                    f"the mesh rebuild can shard it "
                    f"[chain: {_chain(rb.chain)}]"
                ),
            ))
        elif declared == "global" and laneish(rb.axis):
            findings.append(Finding(
                rule="S002", severity=Severity.ERROR, path=rb.rel,
                line=rb.line, col=rb.col,
                message=(
                    f"`{rb.cls}.{rb.field}` is declared global "
                    f"(replicated under the mesh) but this rebuild "
                    f"feeds it a lane-axis value — smuggling per-lane "
                    f"state into a replicated leaf is an implicit "
                    f"gather; fold through a registered collective "
                    f"first [chain: {_chain(rb.chain)}]"
                ),
            ))

    # S002: class-def audit — leaves vs the declared table
    for module, cls_name in audited:
        mi = model.modules.get(module)
        if mi is None:
            continue
        cls = mi.classes.get(cls_name)
        if cls is None:
            continue
        table = carry_axes.get(cls_name, {})
        fields = [
            item.target.id
            for item in cls.body
            if isinstance(item, ast.AnnAssign)
            and isinstance(item.target, ast.Name)
        ]
        for field in fields:
            if field not in table:
                findings.append(Finding(
                    rule="S002", severity=Severity.ERROR, path=mi.rel,
                    line=cls.lineno, col=0,
                    message=(
                        f"`{cls_name}.{field}` is a new carry leaf with "
                        f"no axis declaration in analysis/srules.py "
                        f"CARRY_AXES — declare it lane-leading or "
                        f"global (the sharding contract is per-leaf)"
                    ),
                ))
        for field in sorted(set(table) - set(fields)):
            if "." in field and field.split(".", 1)[0] in fields:
                # documented sub-leaf of a dict-typed leaf (e.g.
                # LaneState.cov.buf): the parent leaf exists as an
                # AnnAssign; the inner dict's keys have no class-level
                # declaration to match, so the row is documentation,
                # not a ghost
                continue
            findings.append(Finding(
                rule="S002", severity=Severity.ERROR, path=mi.rel,
                line=cls.lineno, col=0,
                message=(
                    f"CARRY_AXES declares `{cls_name}.{field}` but the "
                    f"class has no such leaf — ghost axis declaration"
                ),
            ))

    # S003: lane-dependent python control flow / iteration
    for sink in engine.host_sinks:
        findings.append(Finding(
            rule="S003", severity=Severity.ERROR, path=sink.rel,
            line=sink.line, col=sink.col,
            message=(
                f"{sink.what} in the step path — under a mesh this "
                f"forces a cross-chip gather to one host per read; "
                f"fold through a registered collective (counters) "
                f"instead [chain: {_chain(sink.chain)}]"
            ),
        ))

    # stable order + dedup: positional for line-anchored findings (the
    # same op reached from several entry contexts reports once — the
    # shortest chain sorts first), message-keyed for repo-level rows
    seen = set()
    out: List[Finding] = []
    for f in sorted(
        findings,
        key=lambda f: (f.path, f.line, f.col, f.rule, len(f.message)),
    ):
        key = (
            (f.rule, f.path, f.line, f.col) if f.line
            else (f.rule, f.path, f.message)
        )
        if key in seen:
            continue
        seen.add(key)
        out.append(f)
    return out
