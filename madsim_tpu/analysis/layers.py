"""L-rules: jax-free layer enforcement over the import graph.

The hunt farm's control plane (`fleet serve` / `submit` / `status`),
the guided-search bias math, the bench-history renderer and this
analysis package all ship a hard promise: **importing them never
imports jax**. Until now that promise lived in docstring sentences
("Pure host-side stdlib — no jax import anywhere in this module",
`fleet/store.py`) and one subprocess test; a single careless
`from ..engine import shrink` at the top of a fleet module would break
`fleet serve`'s startup cost, the chaos harness's 0.3 s synthetic
workers, and every jax-less deployment — and nothing static would say
so. These rules make the layer map declarative and the check
whole-program:

L001  a jax-free module *directly* imports a closed module at module
      level (jax/jaxlib themselves, `engine.core`, or anything under
      `ops/` — the two jax-hosting subsystems the zone must never see)
L002  a jax-free module eagerly imports a PROJECT module whose eager
      transitive closure reaches jax — the finding names the full
      chain, including package `__init__` hops (`from .guided import
      ...` in `search/__init__.py` would drag jax into `search.bias`
      through the parent-package edge)
L003  gated-import discipline: a *function-local* (lazy) import of a
      jax-reaching module from a jax-free module is only legal through
      a recorded gate — either a `try:/except ImportError` optional-
      dependency probe (`perf/history.py`'s version stamp) or an
      inline justified allowance; and any call from the zone to an
      `import_jax`-gated helper (`compile_cache.cache_subkey`) must
      pass the literal `import_jax=False` (the idiom
      `fleet/store.job_subkey` records)

The zone below is the layer map. Adding a module to the zone is a
claim reviewers can hold you to; removing one is a visible contract
change in this file's diff, not a silent drift.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from .findings import Finding, Severity
from .projectmodel import (
    FunctionInfo,
    ProjectModel,
    is_jax_module,
    iter_calls,
    resolve_callee,
)

# -- the layer map -----------------------------------------------------------

# Modules (exact dotted name) or whole subpackages (prefix) that must be
# importable without jax. Keep sorted; every entry is a public contract.
JAX_FREE_ZONE = (
    "madsim_tpu.analysis",  # the linter lints itself jax-free (C import half is gated)
    "madsim_tpu.fleet.allocator",
    "madsim_tpu.fleet.api",
    "madsim_tpu.fleet.chaos",
    "madsim_tpu.fleet.client",
    "madsim_tpu.fleet.events",
    "madsim_tpu.fleet.fsck",
    "madsim_tpu.fleet.httpd",
    "madsim_tpu.fleet.scheduler",
    "madsim_tpu.fleet.store",
    "madsim_tpu.kinds",
    "madsim_tpu.perf.history",
    "madsim_tpu.search.bias",
)

# Closed modules: importing these from the zone is an L001 even before
# the transitive closure is consulted (they are jax by definition).
CLOSED_PREFIXES = (
    "jax",
    "jaxlib",
    "madsim_tpu.engine.core",
    "madsim_tpu.ops",
)

# The gate keyword: a project function carrying this parameter promises
# to stay jax-free when it is passed False (compile_cache.cache_subkey).
GATE_KWARG = "import_jax"


def in_zone(module: str) -> bool:
    return any(
        module == z or module.startswith(z + ".") for z in JAX_FREE_ZONE
    )


def _is_closed(target: str) -> bool:
    return any(
        target == p or target.startswith(p + ".") for p in CLOSED_PREFIXES
    )


def _finding(rule: str, mi, lineno: int, message: str) -> Finding:
    return Finding(
        rule=rule, severity=Severity.ERROR, path=mi.rel, line=lineno,
        col=0, message=message,
    )


def _jax_reaching(model: ProjectModel, target: str) -> Optional[List[str]]:
    """Does importing `target` (an absolute dotted edge target) execute
    a jax import?  Returns the module chain to jax, or None."""
    if is_jax_module(target) or _is_closed(target):
        return [target]
    for mod in model._project_targets(target):
        chain = model.eager_jax_chain(mod)
        if chain is not None:
            return chain
    return None


def _gated_functions(model: ProjectModel) -> set:
    """(module, qualname) of every project function with an
    `import_jax` parameter — the recorded gates."""
    out = set()
    for mi in model.modules.values():
        for fn in mi.functions.values():
            if GATE_KWARG in fn.params:
                out.add((fn.module, fn.qualname))
    return out


def check_model(model: ProjectModel) -> List[Finding]:
    findings: List[Finding] = []
    gates = _gated_functions(model)

    for name in sorted(model.modules):
        if not in_zone(name):
            continue
        mi = model.modules[name]

        # importing a.b.c executes a/__init__ and a/b/__init__ first:
        # the zone module's own package ancestors must be jax-free too
        # (`from .guided import ...` in search/__init__.py would poison
        # search.bias without bias.py changing a byte)
        parts = name.split(".")
        for cut in range(1, len(parts)):
            anc = ".".join(parts[:cut])
            if anc not in model.modules or in_zone(anc):
                continue  # zone ancestors report their own findings
            chain = model.eager_jax_chain(anc)
            if chain is not None:
                findings.append(_finding(
                    "L002", mi, 1,
                    f"jax-free module {name} cannot be imported without "
                    f"jax: its package ancestor executes "
                    f"{' -> '.join(chain)} at import time — break the "
                    f"chain in {chain[0]}'s __init__ or amend the "
                    f"layer map",
                ))
                break

        for edge in mi.imports:
            if edge.lazy:
                if edge.guarded:
                    # try/except ImportError: the optional-dependency
                    # probe idiom — legal, the module works without jax
                    continue
                chain = _jax_reaching(model, edge.target)
                if chain is not None:
                    via = (
                        f" (imports jax via {' -> '.join(chain)})"
                        if len(chain) > 1 or not is_jax_module(chain[0])
                        else ""
                    )
                    findings.append(_finding(
                        "L003", mi, edge.lineno,
                        f"jax-free module {name} lazily imports "
                        f"`{edge.target}`{via} inside "
                        f"`{edge.func or '?'}` without a gate — wrap in "
                        f"try/except ImportError if jax is optional "
                        f"here, or carry a justified inline allowance "
                        f"if this function IS the gate",
                    ))
                continue
            # eager edges
            if _is_closed(edge.target):
                findings.append(_finding(
                    "L001", mi, edge.lineno,
                    f"jax-free module {name} imports closed module "
                    f"`{edge.target}` at module level — the layer map "
                    f"(analysis/layers.py JAX_FREE_ZONE) pins this "
                    f"module jax-free; move the import behind a "
                    f"function gate or move the code out of the zone",
                ))
                continue
            chain = _jax_reaching(model, edge.target)
            if chain is not None:
                findings.append(_finding(
                    "L002", mi, edge.lineno,
                    f"jax-free module {name} transitively imports jax: "
                    f"{name} -> {' -> '.join(chain)} — every module on "
                    f"that chain executes at import time, so "
                    f"`import {name}` now pays (and requires) jax; "
                    f"break the chain or amend the layer map",
                ))

        # L003 half two: calls to import_jax-gated helpers must close
        # the gate with the literal False
        for fn in mi.functions.values():
            for call in iter_calls(fn):
                kind, target = resolve_callee(call, fn, model)
                if kind != "project":
                    continue
                assert isinstance(target, FunctionInfo)
                if (target.module, target.qualname) not in gates:
                    continue
                if target.module == mi.name:
                    continue  # the gate's own module may use it freely
                ok = any(
                    kw.arg == GATE_KWARG
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value is False
                    for kw in call.keywords
                )
                if not ok:
                    findings.append(_finding(
                        "L003", mi, call.lineno,
                        f"jax-free module {name} calls gated helper "
                        f"`{target.module}.{target.qualname}` without "
                        f"`{GATE_KWARG}=False` — the gate defaults to "
                        f"importing jax; the zone must close it "
                        f"explicitly (the `job_subkey` idiom)",
                    ))
    return findings
