"""The AST/model cache — `.madsim-lint-cache/` under the repo root.

The v2 analyzer parses every package file twice (per-file passes + the
program model) and the C import half instantiates models under jax;
cold that is tens of seconds on the 1-core reference box, which is too
slow for a pre-commit hook. The cache stores RAW findings (before
suppression/baseline policy — policy is cheap and must always run
fresh, so an edited `# madsim: allow(...)` comment takes effect even
on a full cache hit) at two granularities:

* per-file: the D/C findings of one source file, keyed by
  (sha256(source), import_check). Sound because those passes read
  nothing but the file. The C import half additionally reads the
  engine contract, so the rules-version salt below MUST be bumped when
  contract semantics change — that is what `RULES_VERSION` is for.
* whole-program: the G/L/T/R findings, keyed by the sha256 of every
  input the repo passes read (the package file set plus the G-pass's
  named test files and the RNG manifest). Any changed byte anywhere
  re-runs the whole-program half; only a byte-identical repo replays.

A no-change whole-package re-run is therefore a hash walk plus a JSON
read — the `make lint-fast` / pre-commit path. The cache is opt-in
(`--cache`); CI stays cold on purpose. Version skew (a new rules
version, a corrupt file) degrades to a cold run, never to stale
findings.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

from .findings import Finding

# Bump whenever any rule's behavior changes — the cache must never
# serve findings computed by older rule semantics.
RULES_VERSION = "lint-v2.1"  # v2.1: the S family (sharding readiness)

CACHE_DIR = ".madsim-lint-cache"
CACHE_FILE = "cache.json"


def _finding_to_dict(f: Finding) -> dict:
    return {
        "rule": f.rule, "severity": f.severity, "path": f.path,
        "line": f.line, "col": f.col, "message": f.message,
        "fixable": f.fixable,
    }


def _finding_from_dict(d: dict) -> Finding:
    return Finding(
        rule=d["rule"], severity=d["severity"], path=d["path"],
        line=d["line"], col=d["col"], message=d["message"],
        fixable=bool(d.get("fixable", False)),
    )


def sha256_text(text: str) -> str:
    return hashlib.sha256(text.encode("utf-8")).hexdigest()


def sha256_file(path: str) -> Optional[str]:
    try:
        with open(path, "rb") as fh:
            return hashlib.sha256(fh.read()).hexdigest()
    except OSError:
        return None


class LintCache:
    def __init__(self, root: str):
        self.root = root
        self.path = os.path.join(root, CACHE_DIR, CACHE_FILE)
        self.doc: dict = {"version": RULES_VERSION, "files": {}, "repo": None}
        self.dirty = False
        self.hits = 0
        self.misses = 0
        self._load()

    def _load(self) -> None:
        try:
            with open(self.path, "r", encoding="utf-8") as fh:
                doc = json.load(fh)
            if doc.get("version") == RULES_VERSION:
                self.doc = doc
        except (OSError, ValueError):
            pass  # cold start

    def save(self) -> None:
        if not self.dirty:
            return
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(self.doc, fh, sort_keys=True)
        os.replace(tmp, self.path)

    # -- per-file ------------------------------------------------------------

    def file_key(self, source: str, import_check: bool) -> str:
        return f"{sha256_text(source)}:{int(import_check)}"

    def get_file(self, path: str, key: str) -> Optional[List[Finding]]:
        entry = self.doc["files"].get(path)
        if entry is None or entry.get("key") != key:
            self.misses += 1
            return None
        self.hits += 1
        return [_finding_from_dict(d) for d in entry["findings"]]

    def put_file(self, path: str, key: str, findings: Sequence[Finding]) -> None:
        self.doc["files"][path] = {
            "key": key,
            "findings": [_finding_to_dict(f) for f in findings],
        }
        self.dirty = True

    # -- whole-program -------------------------------------------------------

    def repo_fileset_key(self, files: Sequence[str]) -> str:
        """sha over (relpath, sha256) of every whole-program input, in
        sorted order."""
        h = hashlib.sha256()
        for path in sorted(set(files)):
            rel = os.path.relpath(path, self.root)
            h.update(rel.encode())
            h.update(b"\0")
            digest = sha256_file(path)
            h.update((digest or "missing").encode())
            h.update(b"\0")
        return h.hexdigest()

    def get_repo(self, key: str) -> Optional[List[Finding]]:
        entry = self.doc.get("repo")
        if entry is None or entry.get("key") != key:
            return None
        return [_finding_from_dict(d) for d in entry["findings"]]

    def put_repo(self, key: str, findings: Sequence[Finding]) -> None:
        self.doc["repo"] = {
            "key": key,
            "findings": [_finding_to_dict(f) for f in findings],
        }
        self.dirty = True


def repo_input_files(root: str) -> List[str]:
    """Every file the whole-program (G/L/T/R) passes read: the package
    tree plus the G-pass's named test files and the RNG manifest."""
    from . import grules

    out: List[str] = []
    pkg = os.path.join(root, "madsim_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = sorted(
            d for d in dirnames
            if d not in ("__pycache__", ".git", ".venv", "node_modules")
        )
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                out.append(os.path.join(dirpath, fn))
    for rel in (grules.GATES_TEST, grules.GOLDEN_TEST, grules.MANIFEST):
        out.append(os.path.join(root, rel))
    return out
