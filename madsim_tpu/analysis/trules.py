"""T-rules: interprocedural traced-value taint (v2 of D006's pass).

D006 asks one file-local question — "is there python truthiness on a
traced value inside a Machine handler?" — and stops at the handler's
edge. The properties the streaming executor actually depends on are
interprocedural: `run_stream`'s steady state must have ZERO blocking
host syncs between segments (ROADMAP's coverage-tax and <5 s
warm-start items both die by a thousand hidden `.item()` cuts), and a
donated `StreamCarry` is CONSUMED by the dispatch that takes it — the
exact hazard the lane-axis sharding rebuild will multiply across
chips. This pass builds per-function taint summaries over the project
call graph (pass 1's model) and walks entry contexts with real
propagation chains:

T001  a sync-forcing sink on a traced value — python truthiness
      (`if`/`while`/`assert`/ternary/`bool()`/`and`/`or`), `int()`,
      `float()`, `.item()`, `np.asarray()`/`np.array()` — reachable
      from `run_stream`'s executor loop or from a Machine handler
      *through helper calls* (the scope D006's file-local taint
      misses). Each finding names the propagation chain.
T002  `block_until_ready` / `jax.device_get` inside the per-segment
      dispatch region (the executor's while-loops and the helpers they
      call). The two designed syncs — the counters poll and the ring
      drain — carry justified inline allowances; anything else is a
      hidden sync the A/B harness would only find after it shipped.
T003  use of a donated argument after the donating call site. Donation
      is resolved statically: `jax.jit(f, donate_argnums=...)` (also
      through `**kw` dicts and tuple-returning factories like
      `_stream_fns`), including the wrapper idiom where the donating
      fn is passed through a dispatch helper (`_dispatch(what, fn,
      *args)` — the args after `fn` are the donated ones).

Taint model (documented because findings are only as good as it):
*sources* are `jnp.*`/`lax.*`/`jax.random.*` expressions, calls to
jitted/donating fns, and (in handler contexts) the handler's params;
`jax.device_get` is the *sanitizer* — its result is host memory — and
a call that receives `jax.device_get` itself as an argument is treated
as sanitized too (the retry/span wrapper idiom); `int()`/`float()`/
`bool()`/`np.asarray()` sanitize their result while SINKING their
argument. Everything else propagates conservatively. Heuristic, like
D006 — T001/T002 report as warnings; T003 (a correctness bug, not a
perf bug) as error.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from .astutils import TRACED_METHODS, dotted_name, machine_classes
from .findings import Finding, Severity
from .projectmodel import (
    FunctionInfo,
    ProjectModel,
    own_body_nodes,
    resolve_callee,
    resolve_dotted,
)

# Entry points whose bodies ARE the per-segment dispatch region. Walks
# start here with intrinsic sources only (no tainted params).
EXECUTOR_ENTRYPOINTS = (
    ("madsim_tpu.engine.core", "Engine._run_stream_impl"),
)

# namespaces whose calls produce traced (device) values
_TRACED_PREFIXES = (
    "jnp.", "jax.numpy.", "lax.", "jax.lax.", "jax.random.", "jax.nn.",
    "jax.tree_util.", "jax.tree.",
)
# references that turn a function into a traced-value producer
_TRACED_FN_MAKERS = {"jax.jit", "jax.vmap", "jax.pmap", "jax.checkpoint"}
# the sanitizer: an explicit, designed device->host transfer
_SANITIZERS = {"jax.device_get"}
# host-returning builtins that are ALSO T001 sinks when their arg is traced
_SINK_CASTS = {"int", "float", "bool"}
_SINK_NP = {"np.asarray", "numpy.asarray", "np.array", "numpy.array"}
# host-returning, never sinks
_HOST_CALLS = {
    "len", "range", "isinstance", "type", "getattr", "hasattr", "repr",
    "str", "print", "enumerate", "id", "format",
}
# attribute reads that return static python off a traced value
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "at"}

_INTRINSIC = "*"  # origin marker for "a traced source in this body"


# -- donation registry -------------------------------------------------------


def _donate_positions(call: ast.Call, mi) -> Optional[Tuple[int, ...]]:
    """`jax.jit(f, ...)` -> donated argnums, or None if not a jit call /
    no donation. `**kw` dicts resolve through one module/local
    assignment (`donate_kw = {"donate_argnums": (0,)} if donate else
    {}` counts as donating — the static pass must assume the donating
    configuration)."""
    name = dotted_name(call.func)
    if name is None:
        return None
    resolved = mi.importmap.resolve(name)
    if resolved not in ("jax.jit", "jit", "pjit", "jax.experimental.pjit.pjit"):
        return None
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            return _tuple_of_ints(kw.value) or (0,)
        if kw.arg is None and _mentions_donate(kw.value, mi):
            return (0,)
    return None


def _tuple_of_ints(node: ast.expr) -> Optional[Tuple[int, ...]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, ast.Tuple) and all(
        isinstance(e, ast.Constant) and isinstance(e.value, int)
        for e in node.elts
    ):
        return tuple(e.value for e in node.elts)
    return None


def _mentions_donate(node: ast.expr, mi) -> bool:
    """A `**kwargs` operand donates when its expression — or the
    assignment of the Name it references, anywhere in the module —
    contains a 'donate_argnums' key."""
    def has_key(n) -> bool:
        return any(
            isinstance(x, ast.Constant) and x.value == "donate_argnums"
            for x in ast.walk(n)
        )

    if has_key(node):
        return True
    if isinstance(node, ast.Name):
        for n in ast.walk(mi.tree):
            if isinstance(n, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == node.id
                for t in n.targets
            ):
                if has_key(n.value):
                    return True
    return False


@dataclasses.dataclass
class Summary:
    """What a function does with taint, independent of call site."""
    prop: Set[str] = dataclasses.field(default_factory=set)  # params -> return
    always: bool = False  # returns a traced value regardless of args
    donates: Set[str] = dataclasses.field(default_factory=set)  # params it donates
    # return positions (tuple returns) that are donating jitted fns;
    # None key = "the return value itself is a donating fn"
    returns_donating: Dict[Optional[int], Tuple[int, ...]] = dataclasses.field(
        default_factory=dict
    )
    returns_traced_fn: bool = False  # returns a jitted fn (calls of it are traced)


class TaintEngine:
    def __init__(self, model: ProjectModel):
        self.model = model
        self.summaries: Dict[Tuple[str, str], Summary] = {}
        self.findings: List[Finding] = []
        self._context_memo: Set[Tuple[str, str, FrozenSet[str]]] = set()
        self._context_budget = 800

    def summary(self, fn: FunctionInfo) -> Summary:
        return self.summaries.setdefault((fn.module, fn.qualname), Summary())

    # -- fixed-point summaries ----------------------------------------------

    def compute_summaries(self) -> None:
        fns = [
            f for mi in self.model.modules.values()
            for f in mi.functions.values()
        ]
        for _ in range(4):  # call-graph cycles converge fast in practice
            changed = False
            for fn in fns:
                s = self._summarize(fn)
                old = self.summary(fn)
                if (
                    s.prop != old.prop or s.always != old.always
                    or s.donates != old.donates
                    or s.returns_donating != old.returns_donating
                    or s.returns_traced_fn != old.returns_traced_fn
                ):
                    self.summaries[(fn.module, fn.qualname)] = s
                    changed = True
            if not changed:
                break

    def _summarize(self, fn: FunctionInfo) -> Summary:
        walk = _BodyWalk(self, fn, tainted_params=set(fn.params),
                         symbolic=True, report=None)
        walk.run()
        s = Summary(
            prop={p for p in walk.return_origins if p != _INTRINSIC},
            always=_INTRINSIC in walk.return_origins,
            donates=walk.donated_params,
        )
        s.returns_donating = walk.returns_donating
        s.returns_traced_fn = walk.returns_traced_fn
        return s

    # -- entry walks ---------------------------------------------------------

    def run(
        self,
        executor_entrypoints: Sequence[Tuple[str, str]] = EXECUTOR_ENTRYPOINTS,
        handler_files: Optional[Set[str]] = None,
    ) -> List[Finding]:
        self.compute_summaries()

        # (a) executor contexts: no tainted params, intrinsic sources,
        # all sink kinds, T002 dispatch-region scope, T003 donation
        for mod, qual in executor_entrypoints:
            fn = self.model.function(mod, qual)
            if fn is None:
                continue
            self._walk_context(
                fn, tainted_params=frozenset(), chain=(),
                truthiness=True, executor=True,
            )

        # (b) Machine handler contexts: params tainted; depth-0
        # truthiness stays D006's (file-local, fixture-pinned) — this
        # pass takes the helpers D006 cannot see plus the cast/item
        # sinks D006 never covered
        for mi in self.model.modules.values():
            if handler_files is not None and mi.rel not in handler_files:
                continue
            for cls_name, cls in machine_classes(mi.tree).items():
                for item in cls.body:
                    if not isinstance(item, ast.FunctionDef):
                        continue
                    if item.name not in TRACED_METHODS:
                        continue
                    fn = mi.functions.get(f"{cls_name}.{item.name}")
                    if fn is None:
                        continue
                    params = frozenset(p for p in fn.params if p != "self")
                    self._walk_context(
                        fn, tainted_params=params, chain=(),
                        truthiness=False, executor=False,
                    )
        return self.findings

    def _walk_context(
        self,
        fn: FunctionInfo,
        tainted_params: FrozenSet[str],
        chain: Tuple[str, ...],
        truthiness: bool,
        executor: bool,
    ) -> None:
        key = (fn.module, fn.qualname, tainted_params)
        if key in self._context_memo or len(chain) > 6:
            return
        if self._context_budget <= 0:
            return
        self._context_budget -= 1
        self._context_memo.add(key)
        walk = _BodyWalk(
            self, fn, tainted_params=set(tainted_params), symbolic=False,
            report=_Reporter(self, fn, chain + (fn.qualname,),
                             truthiness=truthiness, executor=executor),
        )
        walk.run()


@dataclasses.dataclass
class _Reporter:
    engine: TaintEngine
    fn: FunctionInfo
    chain: Tuple[str, ...]
    truthiness: bool  # flag truthiness sinks at this depth
    executor: bool  # T002/T003 scope + all-sinks-on

    def rel(self) -> str:
        return self.engine.model.modules[self.fn.module].rel

    def emit(self, rule: str, sev: str, node: ast.AST, message: str) -> None:
        via = " -> ".join(self.chain)
        self.engine.findings.append(Finding(
            rule=rule, severity=sev, path=self.rel(),
            line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0),
            message=f"{message} [chain: {via}]",
        ))

    def descend(self, callee: FunctionInfo, tainted_params: FrozenSet[str]) -> None:
        self.engine._walk_context(
            callee, tainted_params, self.chain,
            truthiness=True,  # helpers get the full sink set (the D006 gap)
            executor=self.executor,
        )


class _BodyWalk:
    """One pass over a function body in document order, twice (the
    second round approximates loop-carried flows). Tracks, per local
    name, the set of taint origins (param names and/or the intrinsic
    marker) plus donation state."""

    def __init__(self, engine: TaintEngine, fn: FunctionInfo,
                 tainted_params: Set[str], symbolic: bool, report):
        self.engine = engine
        self.fn = fn
        self.mi = engine.model.modules[fn.module]
        self.symbolic = symbolic  # summary mode: origins are param names
        self.report: Optional[_Reporter] = report
        self.env: Dict[str, Set[str]] = {
            p: {p} for p in tainted_params
        }
        # names bound to donating jitted fns: name -> donated positions
        self.donating_fns: Dict[str, Tuple[int, ...]] = {}
        # names bound to (plain) jitted fns — calls of them are traced
        self.traced_fns: Set[str] = set()
        self.return_origins: Set[str] = set()
        self.donated_params: Set[str] = set()
        self.returns_donating: Dict[Optional[int], Tuple[int, ...]] = {}
        self.returns_traced_fn: bool = False
        # name -> lineno where it was donated (T003 state)
        self.donated_at: Dict[str, int] = {}
        self._reported: Set[Tuple[str, int, int]] = set()
        # While-loop spans of THIS body: the dispatch region for T002
        self._loop_spans: List[Tuple[int, int]] = [
            (n.lineno, n.end_lineno or n.lineno)
            for n in own_body_nodes(fn)
            if isinstance(n, (ast.While, ast.For))
        ]

    # -- driver --------------------------------------------------------------

    def run(self) -> None:
        body = list(self.fn.node.body)
        for _round in (1, 2):
            self._stmts(body)

    def _stmts(self, stmts) -> None:
        for stmt in stmts:
            self._stmt(stmt)

    def _stmt(self, node: ast.stmt) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # separate FunctionInfo
        if isinstance(node, ast.Return):
            if node.value is not None:
                o = self._origins(node.value)
                self.return_origins |= o
                self._note_return_shape(node.value)
            return
        if isinstance(node, ast.Assign):
            o = self._origins(node.value)
            self._bind_fns(node.targets, node.value)
            for tgt in node.targets:
                self._assign_target(tgt, o, node.value)
            return
        if isinstance(node, ast.AugAssign):
            o = self._origins(node.value) | self._origins(node.target)
            self._assign_target(node.target, o, node.value)
            return
        if isinstance(node, ast.AnnAssign):
            if node.value is not None:
                o = self._origins(node.value)
                self._assign_target(node.target, o, node.value)
            return
        if isinstance(node, ast.For):
            o = self._origins(node.iter)
            self._assign_target(node.target, o, node.iter)
            self._stmts(node.body)
            self._stmts(node.orelse)
            return
        if isinstance(node, ast.While):
            self._truthiness_sink(node.test, "while")
            self._origins(node.test)
            self._stmts(node.body)
            self._stmts(node.orelse)
            return
        if isinstance(node, ast.If):
            self._truthiness_sink(node.test, "if")
            self._origins(node.test)
            self._stmts(node.body)
            self._stmts(node.orelse)
            return
        if isinstance(node, ast.Assert):
            self._truthiness_sink(node.test, "assert")
            self._origins(node.test)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                self._origins(item.context_expr)
                if item.optional_vars is not None:
                    self._assign_target(
                        item.optional_vars,
                        self._origins(item.context_expr),
                        item.context_expr,
                    )
            self._stmts(node.body)
            return
        if isinstance(node, ast.Try):
            self._stmts(node.body)
            for h in node.handlers:
                self._stmts(h.body)
            self._stmts(node.orelse)
            self._stmts(node.finalbody)
            return
        if isinstance(node, ast.Expr):
            self._origins(node.value)
            return
        # fallthrough (Raise, Delete, Global, ...): evaluate contained
        # expressions for sinks
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._origins(child)

    def _assign_target(self, tgt: ast.expr, origins: Set[str], value) -> None:
        if isinstance(tgt, ast.Name):
            self.env[tgt.id] = set(origins)
            self.donated_at.pop(tgt.id, None)  # rebind clears donation
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for e in tgt.elts:
                self._assign_target(e, origins, value)
        elif isinstance(tgt, (ast.Attribute, ast.Subscript)):
            self._origins(tgt.value)

    def _bind_fns(self, targets, value) -> None:
        """Track names bound to jitted/donating fns: direct jax.jit
        assignment, or tuple-unpack of a factory whose summary records
        donating return positions (`self._stream_fns(...)`)."""
        names: List[Optional[str]] = []
        if len(targets) == 1 and isinstance(targets[0], ast.Name):
            names = [targets[0].id]
        elif len(targets) == 1 and isinstance(targets[0], (ast.Tuple, ast.List)):
            names = [
                e.id if isinstance(e, ast.Name) else None
                for e in targets[0].elts
            ]
        if not names:
            return

        if isinstance(value, ast.Call):
            pos = _donate_positions(value, self.mi)
            resolved = None
            name = dotted_name(value.func)
            if name is not None:
                resolved = self.mi.importmap.resolve(name)
            if pos is not None and len(names) == 1 and names[0]:
                self.donating_fns[names[0]] = pos
                self.traced_fns.add(names[0])
                return
            if resolved in _TRACED_FN_MAKERS and len(names) == 1 and names[0]:
                self.traced_fns.add(names[0])
                return
            # factory unpack: summaries know which tuple slots donate
            kind, target = resolve_callee(value, self.fn, self.engine.model)
            if kind == "project":
                s = self.engine.summary(target)
                if s.returns_traced_fn:
                    for n in names:
                        if n:
                            self.traced_fns.add(n)
                for slot, dpos in s.returns_donating.items():
                    if slot is None and len(names) == 1 and names[0]:
                        self.donating_fns[names[0]] = dpos
                        self.traced_fns.add(names[0])
                    elif slot is not None and slot < len(names) and names[slot]:
                        self.donating_fns[names[slot]] = dpos
                        self.traced_fns.add(names[slot])
        elif isinstance(value, ast.Name):
            if value.id in self.donating_fns and len(names) == 1 and names[0]:
                self.donating_fns[names[0]] = self.donating_fns[value.id]
            if value.id in self.traced_fns and len(names) == 1 and names[0]:
                self.traced_fns.add(names[0])

    def _note_return_shape(self, value: ast.expr) -> None:
        """Record donating/jitted fns escaping through the return value
        (the `_stream_fns` factory shape)."""
        def jit_info(e: ast.expr) -> Optional[Tuple[int, ...]]:
            if isinstance(e, ast.Call):
                pos = _donate_positions(e, self.mi)
                if pos is not None:
                    return pos
                name = dotted_name(e.func)
                if name and self.mi.importmap.resolve(name) in _TRACED_FN_MAKERS:
                    return ()
            if isinstance(e, ast.Name):
                if e.id in self.donating_fns:
                    return self.donating_fns[e.id]
                if e.id in self.traced_fns:
                    return ()
                # one Name hop: `fns = (...); return fns`
                for n in ast.walk(self.fn.node):
                    if (
                        isinstance(n, ast.Assign)
                        and len(n.targets) == 1
                        and isinstance(n.targets[0], ast.Name)
                        and n.targets[0].id == e.id
                        and isinstance(n.value, ast.Tuple)
                    ):
                        return None  # handled by the tuple branch below
            return None

        expr: ast.expr = value
        if isinstance(expr, ast.Name):
            for n in ast.walk(self.fn.node):
                if (
                    isinstance(n, ast.Assign)
                    and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Name)
                    and n.targets[0].id == expr.id
                    and isinstance(n.value, (ast.Tuple, ast.Call))
                ):
                    expr = n.value
                    break
        if isinstance(expr, ast.Tuple):
            for i, e in enumerate(expr.elts):
                info = jit_info(e)
                if info is not None:
                    self.returns_traced_fn = True
                    if info:
                        self.returns_donating[i] = info
        else:
            info = jit_info(expr)
            if info is not None:
                self.returns_traced_fn = True
                if info:
                    self.returns_donating[None] = info

    # -- expression origins (and sinks) --------------------------------------

    def _origins(self, node: ast.expr) -> Set[str]:
        if isinstance(node, ast.Name):
            self._check_donated_use(node)
            if node.id in self.traced_fns:
                return set()  # the fn object itself is host
            return set(self.env.get(node.id, ()))
        if isinstance(node, ast.Constant):
            return set()
        if isinstance(node, ast.Attribute):
            base = self._origins(node.value)
            if node.attr in _STATIC_ATTRS:
                return set()
            if isinstance(node.value, ast.Name) and node.value.id == "self":
                return set()  # static config, matches D006
            return base
        if isinstance(node, ast.Subscript):
            self._origins(node.slice)
            return self._origins(node.value)
        if isinstance(node, ast.Call):
            return self._call_origins(node)
        if isinstance(node, ast.BinOp):
            return self._origins(node.left) | self._origins(node.right)
        if isinstance(node, ast.UnaryOp):
            return self._origins(node.operand)
        if isinstance(node, ast.Compare):
            out = self._origins(node.left)
            for c in node.comparators:
                out |= self._origins(c)
            return out
        if isinstance(node, ast.BoolOp):
            out: Set[str] = set()
            for v in node.values:
                out |= self._origins(v)
            return out
        if isinstance(node, ast.IfExp):
            self._truthiness_sink(node.test, "conditional expression")
            self._origins(node.test)
            return self._origins(node.body) | self._origins(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            out = set()
            for e in node.elts:
                out |= self._origins(e)
            return out
        if isinstance(node, ast.Dict):
            out = set()
            for k in node.keys:
                if k is not None:
                    out |= self._origins(k)
            for v in node.values:
                out |= self._origins(v)
            return out
        if isinstance(node, ast.Starred):
            return self._origins(node.value)
        if isinstance(node, ast.Lambda):
            return self._origins(node.body)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            out = set()
            for gen in node.generators:
                o = self._origins(gen.iter)
                self._assign_target(gen.target, o, gen.iter)
                out |= o
            out |= self._origins(node.elt)
            return out
        if isinstance(node, ast.DictComp):
            out = set()
            for gen in node.generators:
                o = self._origins(gen.iter)
                self._assign_target(gen.target, o, gen.iter)
                out |= o
            return out | self._origins(node.key) | self._origins(node.value)
        if isinstance(node, ast.JoinedStr):
            for v in node.values:
                if isinstance(v, ast.FormattedValue):
                    self._origins(v.value)
            return set()
        if isinstance(node, (ast.Slice,)):
            for part in (node.lower, node.upper, node.step):
                if part is not None:
                    self._origins(part)
            return set()
        if isinstance(node, (ast.NamedExpr,)):
            o = self._origins(node.value)
            self._assign_target(node.target, o, node.value)
            return o
        if isinstance(node, ast.Await):
            return self._origins(node.value)
        return set()

    def _call_origins(self, node: ast.Call) -> Set[str]:
        name = dotted_name(node.func)
        resolved = self.mi.importmap.resolve(name) if name else None
        arg_origins: Set[str] = set()
        for a in node.args:
            arg_origins |= self._origins(a)
        for kw in node.keywords:
            arg_origins |= self._origins(kw.value)

        # the wrapper idiom: a call handed jax.device_get itself is a
        # designed transfer — host result, and a T002 device fetch
        sanitizer_arg = any(
            self._is_sanitizer_ref(a) for a in node.args
        )

        # sinks first (they fire whether or not the result is used)
        if self.report is not None:
            self._call_sinks(node, resolved, arg_origins, sanitizer_arg)

        if resolved in _SANITIZERS or sanitizer_arg:
            return set()
        if resolved is not None:
            if resolved in _SINK_CASTS:
                return set()
            if resolved in _SINK_NP:
                return set()
            if resolved in _HOST_CALLS or (
                "." not in resolved and resolved in _HOST_CALLS
            ):
                return set()
            if any(resolved.startswith(p) for p in _TRACED_PREFIXES):
                return {_INTRINSIC}
            if resolved in _TRACED_FN_MAKERS:
                return {_INTRINSIC}
        # .item() returns host (and sank above)
        if isinstance(node.func, ast.Attribute) and node.func.attr == "item":
            self._origins(node.func.value)
            return set()
        if isinstance(node.func, ast.Attribute) and node.func.attr == "block_until_ready":
            return self._origins(node.func.value)

        # call of a name bound to a jitted/donating fn -> traced; the
        # donated positional args are consumed
        if isinstance(node.func, ast.Name):
            nm = node.func.id
            if nm in self.donating_fns:
                self._mark_donated(node, node.args, self.donating_fns[nm])
                return {_INTRINSIC}
            if nm in self.traced_fns:
                return {_INTRINSIC}
            if self.env.get(nm):
                # call of a value that may be a traced fn
                return {_INTRINSIC} if not self.symbolic else set(self.env[nm])

        # the dispatch-wrapper idiom: a donating fn passed BY NAME as an
        # argument — the args after it ride through to the donated call,
        # and the wrapper's result is the jitted call's result (traced)
        wrapper_traced = False
        for i, a in enumerate(node.args):
            if isinstance(a, ast.Name) and a.id in self.donating_fns:
                tail_args = node.args[i + 1:]
                self._mark_donated(node, tail_args, self.donating_fns[a.id])
                arg_origins |= {_INTRINSIC}
                wrapper_traced = True
            elif isinstance(a, ast.Name) and a.id in self.traced_fns:
                arg_origins |= {_INTRINSIC}
                wrapper_traced = True

        kind, target = resolve_callee(node, self.fn, self.engine.model)
        if kind == "project":
            s = self.engine.summary(target)
            mapped = self._map_args(node, target)
            out: Set[str] = set()
            if s.always or wrapper_traced:
                out |= {_INTRINSIC}
            for pname, origins in mapped.items():
                if pname in s.prop:
                    out |= origins
                if pname in s.donates:
                    # interprocedural donation: args bound to donating
                    # params are consumed at this call site
                    for anode, pn in self._arg_nodes(node, target):
                        if pn == pname and isinstance(anode, ast.Name):
                            self._donate_name(anode.id, node.lineno)
            # descend for sink detection inside the callee with this
            # call's taint (context-sensitive, memoized)
            if self.report is not None:
                tainted = frozenset(
                    p for p, o in mapped.items() if o
                )
                if tainted:
                    self.report.descend(target, tainted)
            return out

        # extern / opaque: conservative propagation
        return arg_origins

    def _is_sanitizer_ref(self, node: ast.expr) -> bool:
        name = dotted_name(node)
        if name is None:
            return False
        return self.mi.importmap.resolve(name) in _SANITIZERS

    def _map_args(self, call: ast.Call, target: FunctionInfo) -> Dict[str, Set[str]]:
        mapped: Dict[str, Set[str]] = {}
        for anode, pname in self._arg_nodes(call, target):
            if pname is None:
                continue
            mapped.setdefault(pname, set()).update(self._origins_quiet(anode))
        return mapped

    def _origins_quiet(self, node: ast.expr) -> Set[str]:
        """Origins without re-firing sinks (args were already walked)."""
        report, self.report = self.report, None
        try:
            return self._origins(node)
        finally:
            self.report = report

    def _arg_nodes(self, call: ast.Call, target: FunctionInfo):
        params = [p for p in target.params if p != "self"]
        out = []
        for i, a in enumerate(call.args):
            if isinstance(a, ast.Starred):
                # map the starred bundle onto every remaining param
                for p in params[i:]:
                    out.append((a.value, p))
                break
            out.append((a, params[i] if i < len(params) else None))
        for kw in call.keywords:
            if kw.arg is not None and kw.arg in params:
                out.append((kw.value, kw.arg))
        return out

    # -- sinks ---------------------------------------------------------------

    def _truthiness_sink(self, test: ast.expr, what: str) -> None:
        if self.report is None or not self.report.truthiness:
            return
        if self._origins_quiet(test):
            self._emit(
                "T001", Severity.WARNING, test,
                f"python truthiness on a traced value ({what}) in "
                f"`{self.fn.qualname}` — under jit a trace error, on the "
                f"host an implicit blocking device sync",
            )

    def _call_sinks(self, node: ast.Call, resolved, arg_origins, sanitizer_arg) -> None:
        assert self.report is not None
        tainted = bool(arg_origins)
        if resolved in _SINK_CASTS and tainted and self.report.truthiness:
            self._emit(
                "T001", Severity.WARNING, node,
                f"`{resolved}()` on a traced value in `{self.fn.qualname}` "
                f"— forces a blocking device->host sync (or a trace "
                f"error under jit); fetch via the designed "
                f"jax.device_get sync points instead",
            )
        if resolved in _SINK_NP and tainted:
            self._emit(
                "T001", Severity.WARNING, node,
                f"`{resolved}()` on a traced value in `{self.fn.qualname}` "
                f"— an implicit device->host transfer outside the "
                f"designed sync points",
            )
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "item"
            and self._origins_quiet(node.func.value)
        ):
            self._emit(
                "T001", Severity.WARNING, node,
                f"`.item()` on a traced value in `{self.fn.qualname}` — "
                f"one hidden blocking sync per call; batch the read "
                f"through the counters poll",
            )
        # T002: device fetches in the dispatch region
        if self.report.executor and self._in_loop_span(node):
            if resolved in _SANITIZERS or sanitizer_arg or (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "block_until_ready"
            ):
                what = (
                    "block_until_ready" if isinstance(node.func, ast.Attribute)
                    and node.func.attr == "block_until_ready"
                    else "device fetch (jax.device_get)"
                )
                self._emit(
                    "T002", Severity.WARNING, node,
                    f"{what} inside the per-segment dispatch region of "
                    f"`{self.fn.qualname}` — the pipelined executor's "
                    f"contract is zero blocking syncs between segments; "
                    f"if this IS a designed sync point, say so with an "
                    f"inline allowance",
                )

    def _in_loop_span(self, node: ast.AST) -> bool:
        # nested helper bodies (poll/drain) count as dispatch region in
        # their entirety: they exist to be called from the loop
        if self.fn.qualname.count("<locals>"):
            return True
        line = getattr(node, "lineno", 0)
        return any(lo <= line <= hi for lo, hi in self._loop_spans)

    # -- donation (T003) -----------------------------------------------------

    def _mark_donated(self, call: ast.Call, args, positions: Tuple[int, ...]) -> None:
        for p in positions:
            if p < len(args) and isinstance(args[p], ast.Name):
                self._donate_name(args[p].id, call.lineno)

    def _donate_name(self, name: str, lineno: int) -> None:
        if self.symbolic and name in self.fn.params:
            self.donated_params.add(name)
        self.donated_at[name] = lineno

    def _check_donated_use(self, node: ast.Name) -> None:
        if self.report is None:
            return
        at = self.donated_at.get(node.id)
        if at is None or node.lineno <= at:
            return
        self._emit(
            "T003", Severity.ERROR, node,
            f"`{node.id}` is used after being donated at line {at} of "
            f"`{self.fn.qualname}` — a donated buffer is CONSUMED by "
            f"the call that takes it (XLA aliases it in place); read "
            f"counters/rings BEFORE donating, or rebind the name to "
            f"the call's result",
        )

    def _emit(self, rule: str, sev: str, node: ast.AST, message: str) -> None:
        key = (rule, getattr(node, "lineno", 0), getattr(node, "col_offset", 0))
        if key in self._reported:
            return
        self._reported.add(key)
        assert self.report is not None
        self.report.emit(rule, sev, node, message)


def check_model(
    model: ProjectModel,
    executor_entrypoints: Sequence[Tuple[str, str]] = EXECUTOR_ENTRYPOINTS,
    handler_files: Optional[Set[str]] = None,
) -> List[Finding]:
    """`handler_files` (repo-relative paths) restricts the Machine
    handler context walks — the `lint --changed` scope; None = all."""
    engine = TaintEngine(model)
    findings = engine.run(
        executor_entrypoints=executor_entrypoints,
        handler_files=handler_files,
    )
    # stable order + dedup across the two-round body walks
    seen = set()
    out: List[Finding] = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule)):
        key = (f.rule, f.path, f.line, f.col)
        if key in seen:
            continue
        seen.add(key)
        out.append(f)
    return out
