"""R-rules: the static RNG-ledger auditor.

G008 checks the manifest's *shape* — `StepRngLayout`'s field order
against `ops/rng_layout.manifest`. That catches a reordered dataclass,
but the dataclass is only the ledger's cover page: the actual word
budget lives in `layout_for`'s cursor arithmetic (which section starts
where, how wide it is) and in the consumption sites that slice the
step block (`step_words[layout.drop_off : layout.drop_off + M]`). A
drifted *consumer* — a site reading past its section into the next
one, or a cursor walk that hands out sections in a different order
than the manifest records — shifts every recorded stream while G008
stays green. These rules check the CODE against the manifest:

R001  every word-block section the code materializes or consumes has a
      manifest row (an unrecorded section is unreviewable growth), and
      every manifest row still exists in the code (a ghost row means
      the ledger describes a stream nobody derives)
R002  no consumption site reads past its section: for each slice
      `words[X_off + a : X_off + b]` (or scalar read `words[X_off]`),
      `b` must fit inside section X's width as derived from the
      `layout_for` cursor walk — symbolically, in units of
      (max_msgs, words), so `spike_off + 2*M` vs width `2*M` checks
      without knowing M
R003  the v3 cursor walk hands out sections in exactly the manifest
      order — tail growth is append-only in the CODE, not just in the
      dataclass declaration (the same corpus contract G008 words:
      moving an existing offset is a corpus-breaking event that must
      ship as a new rng_stream version)

Sections are audited in `ops/step_rng.py` (the layout + the v3
restart-tail read) and `engine/core.py` (the step-block consumers).
The `lat` section has no cursor statement — it is the fixed head at
offset `h` with width `max_msgs`, recovered from the walk's seed
statement `cursor = h + m`. All stdlib-`ast`; widths that cannot be
resolved symbolically are skipped, not guessed.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Tuple

from .findings import Finding, Severity

STEP_RNG_PY = "madsim_tpu/ops/step_rng.py"
CORE_PY = "madsim_tpu/engine/core.py"
MANIFEST = "madsim_tpu/ops/rng_layout.manifest"

# a symbolic word count: (coefficient on max_msgs, constant words)
Width = Tuple[int, int]


def _finding(rule: str, path: str, line: int, message: str) -> Finding:
    return Finding(
        rule=rule, severity=Severity.ERROR, path=path, line=line, col=0,
        message=message,
    )


def _is_msgs_unit(node: ast.expr) -> bool:
    """`m` / `max_msgs` / `<anything>.MAX_MSGS` — the per-step message
    slot count, the one symbolic unit in the block layout."""
    if isinstance(node, ast.Name):
        return node.id in ("m", "max_msgs")
    if isinstance(node, ast.Attribute):
        return node.attr in ("MAX_MSGS", "max_msgs")
    return False


def _width_of(node: ast.expr) -> Optional[Width]:
    """Resolve an expression to a symbolic width a*M + b, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (0, node.value)
    if _is_msgs_unit(node):
        return (1, 0)
    if isinstance(node, ast.BinOp):
        if isinstance(node.op, ast.Mult):
            left, right = node.left, node.right
            if isinstance(left, ast.Constant) and _is_msgs_unit(right):
                return (left.value, 0)
            if isinstance(right, ast.Constant) and _is_msgs_unit(left):
                return (right.value, 0)
        if isinstance(node.op, ast.Add):
            a = _width_of(node.left)
            b = _width_of(node.right)
            if a is not None and b is not None:
                return (a[0] + b[0], a[1] + b[1])
    return None


def _fits(read: Width, width: Width) -> bool:
    """read <= width for all max_msgs >= 1 (coefficient-wise; a read
    trading a constant for an M coefficient is out of budget)."""
    return read[0] <= width[0] and read[1] <= width[1] + (width[0] - read[0])


# -- the cursor walk ---------------------------------------------------------


def _cursor_walk(tree: ast.Module) -> Tuple[List[Tuple[str, Width, int]], Optional[int]]:
    """Ordered (section, width, lineno) from `layout_for`'s v3 cursor
    arithmetic, with `lat` recovered from the seed statement. Returns
    ([], None) when layout_for is missing."""
    fn = None
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == "layout_for":
            fn = node
            break
    if fn is None:
        return [], None

    sections: List[Tuple[str, Width, int]] = []
    pending: Optional[Tuple[str, int]] = None  # (section, lineno) awaiting width

    def doc_order(n):
        # ast.walk is breadth-first; the cursor idiom is sequential
        for child in ast.iter_child_nodes(n):
            yield child
            yield from doc_order(child)

    for node in doc_order(fn):
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
        ):
            tgt = node.targets[0].id
            if tgt == "cursor" and not sections and pending is None:
                # seed statement `cursor = h + m`: the implicit handler
                # head (h) plus the lat section (m)
                w = _width_of_tail(node.value)
                if w is not None:
                    sections.append(("lat", w, node.lineno))
                continue
            if (
                tgt.endswith("_off")
                and isinstance(node.value, ast.Name)
                and node.value.id == "cursor"
            ):
                pending = (tgt[: -len("_off")], node.lineno)
        elif (
            isinstance(node, ast.AugAssign)
            and isinstance(node.target, ast.Name)
            and node.target.id == "cursor"
            and isinstance(node.op, ast.Add)
            and pending is not None
        ):
            w = _width_of(node.value)
            sections.append((pending[0], w if w is not None else (0, 0), pending[1]))
            pending = None
    return sections, fn.lineno


def _width_of_tail(node: ast.expr) -> Optional[Width]:
    """`h + m` -> the lat width (m); the handler head is not a layout
    section (it has no offset field and no manifest row)."""
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        if isinstance(node.left, ast.Name) and node.left.id in ("h", "handler_words"):
            return _width_of(node.right)
    return None


# -- consumption sites -------------------------------------------------------


def _off_section(node: ast.expr) -> Optional[Tuple[str, bool]]:
    """(section, is_layout_attr) when `node` is `<...>.X_off` or
    `X_off`. Attribute form (`layout.drop_off`) is the strong signal;
    a bare local Name ending in `_off` may be unrelated arithmetic
    (`b_off`, `slot_off` in the fault scheduler), so unknown sections
    are only reported for the attribute form."""
    if isinstance(node, ast.Attribute) and node.attr.endswith("_off"):
        return node.attr[: -len("_off")], True
    if isinstance(node, ast.Name) and node.id.endswith("_off"):
        return node.id[: -len("_off")], False
    return None


def _bound_relative(node: ast.expr) -> Optional[Tuple[str, Width, bool]]:
    """`X_off` -> (X, (0,0), attr?); `X_off + E` -> (X, width(E), attr?)."""
    sec = _off_section(node)
    if sec is not None:
        return sec[0], (0, 0), sec[1]
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        sec = _off_section(node.left)
        if sec is not None:
            w = _width_of(node.right)
            if w is not None:
                return sec[0], w, sec[1]
    return None


def _consumption_sites(tree: ast.Module) -> List[Tuple[str, Width, int, bool]]:
    """(section, read-extent-past-offset, lineno, is_layout_attr) for
    every subscript that indexes a word block by a layout offset."""
    out: List[Tuple[str, Width, int, bool]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Subscript):
            continue
        sl = node.slice
        if isinstance(sl, ast.Slice):
            lo = _bound_relative(sl.lower) if sl.lower is not None else None
            hi = _bound_relative(sl.upper) if sl.upper is not None else None
            if hi is None:
                continue
            sec, extent, attr = hi
            if lo is not None and lo[0] != sec:
                continue  # cross-section slice: not this rule's shape
            out.append((sec, extent, node.lineno, attr))
        else:
            direct = _off_section(sl)
            if direct is None and isinstance(sl, ast.BinOp):
                b = _bound_relative(sl)
                if b is not None:
                    out.append((b[0], (b[1][0], b[1][1] + 1), node.lineno, b[2]))
                continue
            if direct is not None:
                out.append((direct[0], (0, 1), node.lineno, direct[1]))
    return out


# -- the audit ---------------------------------------------------------------


def check_repo(root: str) -> List[Finding]:
    findings: List[Finding] = []

    def read(rel: str) -> Optional[str]:
        path = os.path.join(root, rel)
        if not os.path.isfile(path):
            return None
        with open(path, "r", encoding="utf-8") as fh:
            return fh.read()

    manifest_src = read(MANIFEST)
    rng_src = read(STEP_RNG_PY)
    if manifest_src is None or rng_src is None:
        # G008 already reports the missing ledger/layout loudly
        return findings
    manifest = [
        line.strip() for line in manifest_src.splitlines()
        if line.strip() and not line.strip().startswith("#")
    ]
    try:
        rng_tree = ast.parse(rng_src, filename=STEP_RNG_PY)
    except SyntaxError:
        return findings  # D000 owns it

    sections, anchor = _cursor_walk(rng_tree)
    if anchor is None or not sections:
        return [_finding(
            "R001", STEP_RNG_PY, anchor or 0,
            "cannot statically resolve layout_for's v3 cursor walk — the "
            "RNG-ledger audit needs the `X_off = cursor; cursor += W` "
            "idiom to reconstruct section widths",
        )]
    widths: Dict[str, Width] = {name: w for name, w, _ln in sections}
    code_order = [name for name, _w, _ln in sections]

    # R001 half one: every code section has a manifest row
    for name, _w, ln in sections:
        if name not in manifest:
            findings.append(_finding(
                "R001", STEP_RNG_PY, ln,
                f"layout_for materializes section `{name}` with no row in "
                f"{MANIFEST} — appending the row is the ritual that makes "
                f"word-budget growth reviewable",
            ))
    # R001 half two: every manifest row still derived by the code
    for name in manifest:
        if name not in widths:
            findings.append(_finding(
                "R001", MANIFEST, 0,
                f"manifest row `{name}` has no section in layout_for's "
                f"cursor walk — the ledger describes a stream the code no "
                f"longer derives; removing a section is corpus-breaking "
                f"and must ship as a new rng_stream version",
            ))

    # R003: append-only order — the code's walk must equal the manifest
    # restricted to recorded rows, in manifest order
    recorded_in_code = [n for n in code_order if n in manifest]
    manifest_in_code = [n for n in manifest if n in widths]
    if recorded_in_code != manifest_in_code:
        findings.append(_finding(
            "R003", STEP_RNG_PY, sections[0][2],
            f"layout_for's cursor walk hands out sections in order "
            f"{code_order}, but {MANIFEST} records {manifest} — a "
            f"reordered section moves every later offset (recorded "
            f"streams replay under the wrong words); restore the order "
            f"or ship a new rng_stream version",
        ))

    # R002: consumption sites across the layout module and the engine
    for rel, src in ((STEP_RNG_PY, rng_src), (CORE_PY, read(CORE_PY))):
        if src is None:
            continue
        try:
            tree = ast.parse(src, filename=rel)
        except SyntaxError:
            continue
        for sec, extent, ln, attr in _consumption_sites(tree):
            if sec not in widths:
                if sec in manifest or not attr:
                    continue
                findings.append(_finding(
                    "R001", rel, ln,
                    f"consumption site reads section `{sec}` which neither "
                    f"the layout_for cursor walk nor {MANIFEST} knows — "
                    f"every consumed word needs a manifest row",
                ))
                continue
            if not _fits(extent, widths[sec]):
                findings.append(_finding(
                    "R002", rel, ln,
                    f"read of {extent[0]}*max_msgs+{extent[1]} words past "
                    f"`{sec}_off` exceeds the `{sec}` section's width "
                    f"{widths[sec][0]}*max_msgs+{widths[sec][1]} — the "
                    f"site reads into the NEXT section's words (silent "
                    f"stream corruption with the next flag on)",
                ))
    return findings
