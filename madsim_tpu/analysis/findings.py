"""Finding model, inline suppressions, and the checked-in baseline.

A `Finding` is one rule violation at one source location. Three ways to
silence one, in decreasing order of preference:

* fix it;
* an inline ``# madsim: allow(D003)`` on the flagged line (or a
  comment-only line directly above it) — for deliberate, justified
  exceptions; always pair it with a human reason in the comment;
* a file-level ``# madsim: allow-file(D001,D002)`` comment line — for
  modules whose whole *contract* is the exception (the real-mode
  shims: wall clocks and OS entropy are their job);
* the baseline file — for grandfathered findings when the linter is
  introduced to an existing codebase. This repo ships an EMPTY baseline
  (.madsim-lint-baseline.json) on purpose: CI starts strict.

Baseline entries match on (rule, path, message) rather than line
numbers, so unrelated edits above a grandfathered finding don't
resurrect it; duplicate findings consume duplicate entries.
"""

from __future__ import annotations

import dataclasses
import json
import re
from typing import Dict, List, Optional, Sequence, Tuple


class Severity:
    ERROR = "error"
    WARNING = "warning"


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str  # stable ID: D001..., C001..., G001...
    severity: str  # Severity.*
    path: str  # as given to the linter (repo-relative in CI)
    line: int  # 1-based; 0 = whole-file/repo finding
    col: int  # 0-based
    message: str
    fixable: bool = False  # `lint --fix` knows a mechanical rewrite

    def text(self) -> str:
        tag = " [fixable]" if self.fixable else ""
        return (
            f"{self.path}:{self.line}:{self.col + 1}: "
            f"{self.rule} [{self.severity}]{tag} {self.message}"
        )

    def github(self) -> str:
        # GitHub workflow-command annotation; error/warning map directly
        kind = "error" if self.severity == Severity.ERROR else "warning"
        return (
            f"::{kind} file={self.path},line={max(self.line, 1)},"
            f"col={self.col + 1},title={self.rule}::{self.message}"
        )

    def json_dict(self) -> dict:
        return {
            "rule": self.rule,
            "severity": self.severity,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "fixable": self.fixable,
        }


# -- inline suppressions -----------------------------------------------------

_ALLOW_RE = re.compile(r"#\s*madsim:\s*allow\(([A-Z]\d{3}(?:\s*,\s*[A-Z]\d{3})*)\)")
_ALLOW_FILE_RE = re.compile(
    r"#\s*madsim:\s*allow-file\(([A-Z]\d{3}(?:\s*,\s*[A-Z]\d{3})*)\)"
)


def _ids(match: re.Match) -> set:
    return {part.strip() for part in match.group(1).split(",")}


class Suppressions:
    """Per-file suppression map parsed from comments.

    `line_allows[n]` holds rule IDs allowed on line n (1-based). A
    comment-only line's allowance also covers the next line, so long
    flagged expressions can carry the justification above them.
    """

    def __init__(self, source: str):
        self.file_allows: set = set()
        self.line_allows: Dict[int, set] = {}
        lines = source.splitlines()
        for lineno, text in enumerate(lines, start=1):
            m = _ALLOW_FILE_RE.search(text)
            if m:
                self.file_allows |= _ids(m)
                continue
            m = _ALLOW_RE.search(text)
            if not m:
                continue
            ids = _ids(m)
            self.line_allows.setdefault(lineno, set()).update(ids)
            if text.lstrip().startswith("#"):
                # comment-only: the allowance extends through the rest
                # of the comment block to the first code line below it
                target = lineno + 1
                while target <= len(lines) and lines[target - 1].lstrip().startswith("#"):
                    target += 1
                self.line_allows.setdefault(target, set()).update(ids)

    def allows(self, finding: Finding) -> bool:
        if finding.rule in self.file_allows:
            return True
        return finding.rule in self.line_allows.get(finding.line, set())


def filter_suppressed(
    findings: Sequence[Finding], source_by_path: Dict[str, str]
) -> List[Finding]:
    """Drop findings an inline/file suppression in their source allows.
    Repo-level findings (G-rules, line 0) have no inline channel — the
    mirrors they guard span files, so only the baseline can grandfather
    them."""
    out: List[Finding] = []
    cache: Dict[str, Suppressions] = {}
    for f in findings:
        src = source_by_path.get(f.path)
        if src is not None and f.line > 0:
            sup = cache.get(f.path)
            if sup is None:
                sup = cache[f.path] = Suppressions(src)
            if sup.allows(f):
                continue
        out.append(f)
    return out


# -- baseline ----------------------------------------------------------------

BASELINE_VERSION = 1
DEFAULT_BASELINE_NAME = ".madsim-lint-baseline.json"


def _key(entry: dict) -> Tuple[str, str, str]:
    return (entry["rule"], entry["path"], entry["message"])


def load_baseline(path: str) -> List[dict]:
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    if doc.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"{path}: unsupported baseline version {doc.get('version')!r}"
        )
    return list(doc.get("findings", []))


def save_baseline(path: str, findings: Sequence[Finding]) -> None:
    doc = {
        "version": BASELINE_VERSION,
        "findings": [
            {"rule": f.rule, "path": f.path, "message": f.message}
            for f in sorted(findings, key=lambda f: (f.path, f.rule, f.line))
        ],
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")


def apply_baseline(
    findings: Sequence[Finding], baseline: Sequence[dict]
) -> Tuple[List[Finding], List[dict]]:
    """Split findings into (fresh, grandfathered-entries-consumed).
    Matching is by (rule, path, message), count-aware: two identical
    findings need two baseline entries."""
    budget: Dict[Tuple[str, str, str], int] = {}
    for entry in baseline:
        budget[_key(entry)] = budget.get(_key(entry), 0) + 1
    fresh: List[Finding] = []
    consumed: List[dict] = []
    for f in findings:
        k = (f.rule, f.path, f.message)
        if budget.get(k, 0) > 0:
            budget[k] -= 1
            consumed.append({"rule": f.rule, "path": f.path, "message": f.message})
        else:
            fresh.append(f)
    return fresh, consumed
