"""Finding model, inline suppressions, and the checked-in baseline.

A `Finding` is one rule violation at one source location. Three ways to
silence one, in decreasing order of preference:

* fix it;
* an inline ``# madsim: allow(D003)`` on the flagged line (or a
  comment-only line directly above it) — for deliberate, justified
  exceptions; always pair it with a human reason in the comment;
* a file-level ``# madsim: allow-file(D001,D002)`` comment line — for
  modules whose whole *contract* is the exception (the real-mode
  shims: wall clocks and OS entropy are their job);
* the baseline file — for grandfathered findings when the linter is
  introduced to an existing codebase. This repo ships an EMPTY baseline
  (.madsim-lint-baseline.json) on purpose: CI starts strict.

Baseline entries match on (rule, path, message) rather than line
numbers, so unrelated edits above a grandfathered finding don't
resurrect it; duplicate findings consume duplicate entries.
"""

from __future__ import annotations

import dataclasses
import json
import re
from typing import Dict, List, Optional, Sequence, Tuple


class Severity:
    ERROR = "error"
    WARNING = "warning"


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str  # stable ID: D001..., C001..., G001...
    severity: str  # Severity.*
    path: str  # as given to the linter (repo-relative in CI)
    line: int  # 1-based; 0 = whole-file/repo finding
    col: int  # 0-based
    message: str
    fixable: bool = False  # `lint --fix` knows a mechanical rewrite

    def text(self) -> str:
        tag = " [fixable]" if self.fixable else ""
        return (
            f"{self.path}:{self.line}:{self.col + 1}: "
            f"{self.rule} [{self.severity}]{tag} {self.message}"
        )

    def github(self) -> str:
        # GitHub workflow-command annotation; error/warning map directly
        kind = "error" if self.severity == Severity.ERROR else "warning"
        return (
            f"::{kind} file={self.path},line={max(self.line, 1)},"
            f"col={self.col + 1},title={self.rule}::{self.message}"
        )

    def json_dict(self) -> dict:
        return {
            "rule": self.rule,
            "severity": self.severity,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "fixable": self.fixable,
        }


# -- inline suppressions -----------------------------------------------------

_ALLOW_RE = re.compile(r"#\s*madsim:\s*allow\(([A-Z]\d{3}(?:\s*,\s*[A-Z]\d{3})*)\)")
_ALLOW_FILE_RE = re.compile(
    r"#\s*madsim:\s*allow-file\(([A-Z]\d{3}(?:\s*,\s*[A-Z]\d{3})*)\)"
)


def _ids(match: re.Match) -> set:
    return {part.strip() for part in match.group(1).split(",")}


class Suppressions:
    """Per-file suppression map parsed from comments.

    `line_allows[n]` holds rule IDs allowed on line n (1-based). A
    comment-only line's allowance also covers the next line, so long
    flagged expressions can carry the justification above them.
    """

    def __init__(self, source: str):
        self.file_allows: set = set()
        self.line_allows: Dict[int, set] = {}
        lines = source.splitlines()
        for lineno, text in enumerate(lines, start=1):
            m = _ALLOW_FILE_RE.search(text)
            if m:
                self.file_allows |= _ids(m)
                continue
            m = _ALLOW_RE.search(text)
            if not m:
                continue
            ids = _ids(m)
            self.line_allows.setdefault(lineno, set()).update(ids)
            if text.lstrip().startswith("#"):
                # comment-only: the allowance extends through the rest
                # of the comment block to the first code line below it
                target = lineno + 1
                while target <= len(lines) and lines[target - 1].lstrip().startswith("#"):
                    target += 1
                self.line_allows.setdefault(target, set()).update(ids)

    def allows(self, finding: Finding) -> bool:
        if finding.rule in self.file_allows:
            return True
        return finding.rule in self.line_allows.get(finding.line, set())


def filter_suppressed(
    findings: Sequence[Finding], source_by_path: Dict[str, str]
) -> List[Finding]:
    """Drop findings an inline/file suppression in their source allows.
    Repo-level findings (G-rules, line 0) have no inline channel — the
    mirrors they guard span files, so only the baseline can grandfather
    them."""
    out: List[Finding] = []
    cache: Dict[str, Suppressions] = {}
    for f in findings:
        src = source_by_path.get(f.path)
        if src is not None and f.line > 0:
            sup = cache.get(f.path)
            if sup is None:
                sup = cache[f.path] = Suppressions(src)
            if sup.allows(f):
                continue
        out.append(f)
    return out


# -- SARIF -------------------------------------------------------------------

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)

# one-line descriptions for the SARIF rules table (and future docs);
# family entries cover IDs without a specific row
RULE_CATALOG: Dict[str, str] = {
    "D001": "wall-clock read in sim code",
    "D002": "OS/global entropy draw",
    "D003": "iteration over a set (hash-order leak)",
    "D004": "id()/builtin hash() (process-varying value)",
    "D005": "unordered host callback",
    "D006": "python truthiness on a traced value in a Machine handler",
    "C001": "self.* mutation inside a pure handler",
    "C002": "durable_spec() not congruent with init()",
    "C003": "torn_spec() not a legal refinement of durable_spec()",
    "C004": "coverage_projection must return one scalar integer word",
    "C005": "voter/ack bitmask without the 31-node cap",
    "G001": "flight-recorder counter mirror drift",
    "G002": "coverage band mirror drift",
    "G003": "shrink ablation table drift",
    "G004": "CLI fault-kind vocabulary drift",
    "G005": "chaos flag missing from the gate-off matrix",
    "G006": "chaos flag missing from the golden-stream pins",
    "G007": "K_* index / FaultPlan flag / enabled_kinds ladder drift",
    "G008": "RNG-layout manifest order violation",
    "G009": "guided-search escalation ladder drift",
    "L001": "jax-free module imports a closed module directly",
    "L002": "jax-free module transitively imports jax",
    "L003": "ungated lazy jax import / open import_jax gate",
    "T001": "sync-forcing sink on a traced value (with chain)",
    "T002": "device fetch inside the per-segment dispatch region",
    "T003": "use of a donated argument after the donating call",
    "R001": "RNG word section without a manifest row (or ghost row)",
    "R002": "consumption site reads past its RNG section",
    "R003": "RNG cursor walk out of manifest order",
    "S001": "cross-lane op outside the collective registry (or registry drift)",
    "S002": "carry leaf without a lane-axis declaration / lane data into a global leaf",
    "S003": "lane-axis-dependent python control flow in the step path",
    "S004": "collective placed in the per-event inner loop",
}


def sarif_doc(findings: Sequence[Finding], tool_version: str) -> dict:
    """Minimal-but-valid SARIF 2.1.0 for CI artifact upload and editor
    ingestion. Paths pass through as given (repo-relative in CI)."""
    rule_ids = sorted({f.rule for f in findings} | set(RULE_CATALOG))
    rules = [
        {
            "id": rid,
            "shortDescription": {
                "text": RULE_CATALOG.get(rid, f"madsim lint rule {rid}")
            },
        }
        for rid in rule_ids
    ]
    results = []
    for f in findings:
        results.append({
            "ruleId": f.rule,
            "ruleIndex": rule_ids.index(f.rule),
            "level": "error" if f.severity == Severity.ERROR else "warning",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path.replace("\\", "/")},
                    "region": {
                        "startLine": max(f.line, 1),
                        "startColumn": f.col + 1,
                    },
                },
            }],
        })
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {
                "driver": {
                    "name": "madsim-tpu-lint",
                    "informationUri": "https://github.com/madsim-rs/madsim",
                    "version": tool_version,
                    "rules": rules,
                },
            },
            "results": results,
        }],
    }


# -- baseline ----------------------------------------------------------------

BASELINE_VERSION = 1
DEFAULT_BASELINE_NAME = ".madsim-lint-baseline.json"


def _key(entry: dict) -> Tuple[str, str, str]:
    return (entry["rule"], entry["path"], entry["message"])


def load_baseline(path: str) -> List[dict]:
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    if doc.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"{path}: unsupported baseline version {doc.get('version')!r}"
        )
    return list(doc.get("findings", []))


def save_baseline(path: str, findings: Sequence[Finding]) -> None:
    doc = {
        "version": BASELINE_VERSION,
        "findings": [
            {"rule": f.rule, "path": f.path, "message": f.message}
            for f in sorted(findings, key=lambda f: (f.path, f.rule, f.line))
        ],
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")


def baseline_growth(
    baseline: Sequence[dict], findings: Sequence[Finding]
) -> List[Finding]:
    """Findings NOT already covered by the baseline — the entries a
    `--update-baseline` would ADD. The ratchet is shrink-only: a
    baseline exists to grandfather the past, never to absorb new debt,
    so growth refuses without `--force` (count-aware, like
    apply_baseline: a second identical finding is growth)."""
    budget: Dict[Tuple[str, str, str], int] = {}
    for entry in baseline:
        budget[_key(entry)] = budget.get(_key(entry), 0) + 1
    grown: List[Finding] = []
    for f in findings:
        k = (f.rule, f.path, f.message)
        if budget.get(k, 0) > 0:
            budget[k] -= 1
        else:
            grown.append(f)
    return grown


def apply_baseline(
    findings: Sequence[Finding], baseline: Sequence[dict]
) -> Tuple[List[Finding], List[dict]]:
    """Split findings into (fresh, grandfathered-entries-consumed).
    Matching is by (rule, path, message), count-aware: two identical
    findings need two baseline entries."""
    budget: Dict[Tuple[str, str, str], int] = {}
    for entry in baseline:
        budget[_key(entry)] = budget.get(_key(entry), 0) + 1
    fresh: List[Finding] = []
    consumed: List[dict] = []
    for f in findings:
        k = (f.rule, f.path, f.message)
        if budget.get(k, 0) > 0:
            budget[k] -= 1
            consumed.append({"rule": f.rule, "path": f.path, "message": f.message})
        else:
            fresh.append(f)
    return fresh, consumed
