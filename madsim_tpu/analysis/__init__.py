"""Static analysis for determinism and engine contracts.

The Rust reference enforces determinism *at runtime*: under
``cfg(madsim)`` every nondeterminism source (libc entropy, clocks,
thread scheduling) is intercepted and replaced with the seeded
simulator. Python/JAX offers no such interception point — a stray
`time.time()` or an unordered `jax.debug.callback` compiles fine and
only surfaces months later as corpus rot. This package is the
static-analysis analogue of madsim's interception layer: it refuses the
hazard at review time instead of replaying it at debug time.

Three rule families (stable IDs, `# madsim: allow(...)` suppressions,
checked-in baseline — see findings.py):

* **D-rules** (`drules.py`) — determinism hazards, pure stdlib-`ast`
  over any python source: wall clocks, entropy, unordered set
  iteration, `id()`/`hash()`, unordered host callbacks, python
  truthiness on traced values inside Machine handlers.
* **C-rules** (`crules.py`) — `Machine` authoring-contract checks: an
  AST half (handler purity, the voter-bitmask cap) plus an import half
  that instantiates each model and verifies `durable_spec()` /
  `torn_spec()` congruence and the `coverage_projection` scalar
  contract without running a simulation.
* **G-rules** (`grules.py`) — whole-repo gate-discipline cross-checks:
  every fault kind/flag present in every host mirror, the shrink
  ablation table, the CLI vocabulary, the gate-off bit-identity matrix
  and the golden-stream pins; plus the RNG-layout manifest audit
  (tail-only growth, `ops/rng_layout.manifest`).

Entry point: ``python -m madsim_tpu lint [paths]`` (cli.py). The D/C-AST
and G passes never import jax; the C import half does (models are jax
programs) and can be disabled with ``--no-import-check``.
"""

from .findings import Finding, Severity  # noqa: F401
