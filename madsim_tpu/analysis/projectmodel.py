"""Pass 1 of the v2 analyzer: the whole-program model (stdlib-only).

The D/C rule packs are per-file and syntactic; the properties the
ROADMAP now leans on — "the fleet control plane is jax-free", "the hot
streaming path has no hidden host syncs", "nobody touches a donated
carry" — are whole-program, flow-sensitive claims. This module builds
the shared substrate the L/T passes spend:

* a **module import graph** over the package, with each edge classified
  *eager* (module/class level — executed at import time) vs *lazy*
  (function-local — executed at call time) and *guarded* (directly
  inside a ``try`` whose handler catches ImportError — the
  optional-dependency idiom, e.g. `perf/history.py`'s version probe).
  Importing `a.b.c` also executes `a/__init__.py` and `a/b/__init__.py`,
  so every edge to a project module fans out to its package ancestors —
  the exact channel through which an innocent-looking
  ``from .guided import ...`` in `search/__init__.py` would drag jax
  into the "jax-free" `search.bias`.
* a **per-module symbol table** — module-level functions, classes and
  their methods, plus nested function defs (run_stream's `poll`/`drain`
  helpers are nested, and the taint pass must see through them).
* **call resolution** from a call site to a project FunctionInfo where
  the target is syntactically evident (import-alias chains, `self.`
  methods, same-module names, nested defs). Runtime indirection
  (getattr strings, callables in dicts) stays out of scope, same
  honesty bar as `astutils`.

The model is built once per lint run from the repo root (the same root
the G-pass uses) and handed to `layers.check_model` / `trules` /
`rrules`. Nothing here imports jax.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, List, Optional, Sequence, Tuple

from .astutils import ImportMap, dotted_name

PACKAGE = "madsim_tpu"

_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules", ".claude"}


@dataclasses.dataclass
class ImportEdge:
    target: str  # absolute dotted target ("jax.numpy", "madsim_tpu.ops")
    lineno: int
    lazy: bool  # inside a function body (deferred to call time)
    guarded: bool  # directly under a try: catching ImportError/Exception
    func: Optional[str] = None  # enclosing function qualname when lazy


@dataclasses.dataclass
class FunctionInfo:
    qualname: str  # "foo" / "Cls.meth" / "outer.<locals>.inner"
    module: str  # dotted module name
    node: ast.AST  # FunctionDef / AsyncFunctionDef
    class_name: Optional[str]
    params: List[str]
    lineno: int
    # nested defs visible from this function's body: local name -> qualname
    locals_fns: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ModuleInfo:
    name: str  # dotted
    path: str  # absolute
    rel: str  # repo-relative (finding path)
    tree: ast.Module
    source: str
    imports: List[ImportEdge]
    functions: Dict[str, FunctionInfo]
    classes: Dict[str, ast.ClassDef]
    importmap: ImportMap


class ProjectModel:
    def __init__(self, root: str):
        self.root = root
        self.modules: Dict[str, ModuleInfo] = {}
        self.broken: List[Tuple[str, str]] = []  # (rel, error) — unparseable

    # -- queries -------------------------------------------------------------

    def module_of_path(self, path: str) -> Optional[ModuleInfo]:
        ap = os.path.abspath(path)
        for m in self.modules.values():
            if m.path == ap:
                return m
        return None

    def split_function(self, dotted: str) -> Optional[Tuple[str, str]]:
        """Longest-module-prefix split of an absolute dotted name into
        (module, symbol) — "madsim_tpu.fleet.store.job_subkey" ->
        ("madsim_tpu.fleet.store", "job_subkey")."""
        parts = dotted.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            mod = ".".join(parts[:cut])
            if mod in self.modules:
                return mod, ".".join(parts[cut:])
        return None

    def function(self, module: str, qualname: str) -> Optional[FunctionInfo]:
        mi = self.modules.get(module)
        return mi.functions.get(qualname) if mi else None

    def eager_targets(self, name: str) -> List[ImportEdge]:
        mi = self.modules.get(name)
        if mi is None:
            return []
        return [e for e in mi.imports if not e.lazy]

    def eager_jax_chain(self, start: str) -> Optional[List[str]]:
        """BFS over eager project edges from `start`; returns the module
        chain ending at the first direct jax import, or None when the
        eager closure is jax-free. The chain includes the jax module
        itself as its last element."""
        seen = {start}
        queue: List[str] = [start]
        parent: Dict[str, str] = {}
        while queue:
            cur = queue.pop(0)
            for edge in self.eager_targets(cur):
                if is_jax_module(edge.target):
                    chain = [edge.target, cur]
                    while cur != start:
                        cur = parent[cur]
                        chain.append(cur)
                    return list(reversed(chain))
                for nxt in self._project_targets(edge.target):
                    if nxt not in seen:
                        seen.add(nxt)
                        parent[nxt] = cur
                        queue.append(nxt)
        return None

    def _project_targets(self, target: str) -> List[str]:
        """A resolved import edge target, expanded to every project
        module it executes: the module itself (or the package when a
        `from pkg import name` edge points at a non-module symbol) plus
        all package ancestors present in the model."""
        out: List[str] = []
        probe = target
        while probe and probe not in self.modules:
            probe = probe.rpartition(".")[0]
        if not probe:
            return out
        anc = probe.split(".")
        for cut in range(1, len(anc) + 1):
            name = ".".join(anc[:cut])
            if name in self.modules:
                out.append(name)
        return out


def is_jax_module(dotted: str) -> bool:
    head = dotted.split(".")[0]
    return head in ("jax", "jaxlib")


# -- construction ------------------------------------------------------------


def _module_name(root: str, path: str) -> str:
    rel = os.path.relpath(path, root).replace(os.sep, "/")
    parts = rel[:-3].split("/")  # strip .py
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _resolve_relative(module: str, is_pkg_init: bool, level: int, target: str) -> str:
    """Absolute dotted name of a level-`level` relative import from
    `module` (`from ..runtime import atomicio` in madsim_tpu.fleet.store
    -> madsim_tpu.runtime[.atomicio])."""
    parts = module.split(".")
    # a package __init__'s own package counts as the first level
    base = parts if is_pkg_init else parts[:-1]
    if level > 1:
        base = base[: len(base) - (level - 1)]
    return ".".join(base + ([target] if target else [])).strip(".")


class _ImportCollector(ast.NodeVisitor):
    def __init__(self, module: str, is_pkg_init: bool, module_names: set):
        self.module = module
        self.is_pkg_init = is_pkg_init
        self.module_names = module_names
        self.edges: List[ImportEdge] = []
        self._fn_stack: List[str] = []
        self._try_guard = 0

    def _add(self, target: str, lineno: int) -> None:
        self.edges.append(ImportEdge(
            target=target, lineno=lineno,
            lazy=bool(self._fn_stack),
            guarded=self._try_guard > 0,
            func=".".join(self._fn_stack) if self._fn_stack else None,
        ))

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            self._add(alias.name, node.lineno)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.level:
            base = _resolve_relative(
                self.module, self.is_pkg_init, node.level, node.module or ""
            )
        else:
            base = node.module or ""
        # `from X import a`: an edge to X.a when X.a is a module in the
        # project (importing a submodule), else to X itself
        for alias in node.names:
            if alias.name != "*" and f"{base}.{alias.name}" in self.module_names:
                self._add(f"{base}.{alias.name}", node.lineno)
            elif base:
                self._add(base, node.lineno)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._fn_stack.append(node.name)
        self.generic_visit(node)
        self._fn_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_Try(self, node: ast.Try) -> None:
        catches_import = any(
            h.type is None
            or any(
                n in ("ImportError", "ModuleNotFoundError", "Exception")
                for n in _handler_names(h)
            )
            for h in node.handlers
        )
        if catches_import:
            self._try_guard += 1
        for stmt in node.body:
            self.visit(stmt)
        if catches_import:
            self._try_guard -= 1
        for part in (node.handlers, node.orelse, node.finalbody):
            for stmt in part:
                self.visit(stmt)


def _handler_names(handler: ast.ExceptHandler) -> List[str]:
    t = handler.type
    nodes = t.elts if isinstance(t, ast.Tuple) else [t]
    out = []
    for n in nodes:
        name = dotted_name(n) if n is not None else None
        if name:
            out.append(name.split(".")[-1])
    return out


def _collect_functions(tree: ast.Module, module: str) -> Tuple[Dict[str, FunctionInfo], Dict[str, ast.ClassDef]]:
    functions: Dict[str, FunctionInfo] = {}
    classes: Dict[str, ast.ClassDef] = {}

    def params_of(fn) -> List[str]:
        a = fn.args
        out = [p.arg for p in a.posonlyargs + a.args]
        if a.vararg:
            out.append(a.vararg.arg)
        out.extend(p.arg for p in a.kwonlyargs)
        if a.kwarg:
            out.append(a.kwarg.arg)
        return out

    def add_fn(fn, qual: str, cls: Optional[str]) -> FunctionInfo:
        info = FunctionInfo(
            qualname=qual, module=module, node=fn, class_name=cls,
            params=params_of(fn), lineno=fn.lineno,
        )
        functions[qual] = info
        # nested defs (run_stream's poll/drain/_dispatch): registered as
        # their own analyzable units, resolvable by local name from the
        # enclosing body
        for child in ast.walk(fn):
            if child is fn:
                continue
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # only register DIRECTLY nested defs here; deeper ones
                # register when their enclosing def is processed
                if _encloses_directly(fn, child):
                    nested_q = f"{qual}.<locals>.{child.name}"
                    info.locals_fns[child.name] = nested_q
                    nested = add_fn(child, nested_q, cls)
                    # a nested fn sees its siblings too
                    nested.locals_fns.setdefault(child.name, nested_q)
        # siblings resolve each other (drain calls reset via closure)
        for child_name, child_q in list(info.locals_fns.items()):
            child_info = functions[child_q]
            for sib, sib_q in info.locals_fns.items():
                child_info.locals_fns.setdefault(sib, sib_q)
        return info

    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            add_fn(node, node.name, None)
        elif isinstance(node, ast.ClassDef):
            classes[node.name] = node
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    add_fn(item, f"{node.name}.{item.name}", node.name)
    return functions, classes


def _encloses_directly(outer, inner) -> bool:
    """inner is nested in outer with no intermediate FunctionDef."""
    for node in ast.walk(outer):
        if node in (outer, inner):
            continue
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(n is inner for n in ast.walk(node)):
                return False
    return True


def build_model(root: str, package_dir: Optional[str] = None) -> ProjectModel:
    """Parse every .py under `<root>/madsim_tpu` (or `package_dir`) into
    the project model. Unreadable/unparseable files are recorded in
    `model.broken` and skipped — the per-file D-pass already reports
    the syntax error."""
    model = ProjectModel(root)
    pkg = package_dir or os.path.join(root, PACKAGE)
    paths: List[str] = []
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                paths.append(os.path.join(dirpath, fn))

    names = {_module_name(root, p) for p in paths}
    for path in paths:
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        name = _module_name(root, path)
        try:
            with open(path, "r", encoding="utf-8") as fh:
                source = fh.read()
            tree = ast.parse(source, filename=path)
        except (OSError, UnicodeDecodeError, SyntaxError) as exc:
            model.broken.append((rel, repr(exc)))
            continue
        is_pkg_init = os.path.basename(path) == "__init__.py"
        coll = _ImportCollector(name, is_pkg_init, names)
        coll.visit(tree)
        functions, classes = _collect_functions(tree, name)
        model.modules[name] = ModuleInfo(
            name=name, path=os.path.abspath(path), rel=rel, tree=tree,
            source=source, imports=coll.edges, functions=functions,
            classes=classes, importmap=ImportMap(tree),
        )
    return model


# -- call resolution ---------------------------------------------------------


def resolve_dotted(dotted: str, mi: ModuleInfo) -> str:
    """Absolute form of a dotted reference inside module `mi`, following
    the file's import aliases; relative origins (".store.Job") resolve
    against the module's package."""
    resolved = mi.importmap.resolve(dotted)
    if resolved.startswith("."):
        level = len(resolved) - len(resolved.lstrip("."))
        is_pkg_init = mi.rel.endswith("__init__.py")
        return _resolve_relative(
            mi.name, is_pkg_init, level, resolved.lstrip(".")
        )
    return resolved


def resolve_callee(
    call: ast.Call, fn: FunctionInfo, model: ProjectModel
) -> Tuple[str, object]:
    """Resolve a call site to one of:
    ("project", FunctionInfo) — a function/method in the model;
    ("extern", dotted) — a syntactically-known external name;
    ("opaque", None) — not resolvable (call of a call, subscript, ...).
    """
    mi = model.modules[fn.module]
    name = dotted_name(call.func)
    if name is None:
        return "opaque", None
    parts = name.split(".")

    # nested def in the enclosing function chain
    if len(parts) == 1 and parts[0] in fn.locals_fns:
        target = mi.functions.get(fn.locals_fns[parts[0]])
        if target is not None:
            return "project", target

    # self.method -> same class (single-file hierarchies only)
    if parts[0] == "self" and fn.class_name and len(parts) == 2:
        target = mi.functions.get(f"{fn.class_name}.{parts[1]}")
        if target is not None:
            return "project", target
        return "extern", f"self.{parts[1]}"

    # same-module function / Class.method
    if len(parts) == 1 and parts[0] in mi.functions:
        return "project", mi.functions[parts[0]]
    if len(parts) == 2 and f"{parts[0]}.{parts[1]}" in mi.functions:
        return "project", mi.functions[f"{parts[0]}.{parts[1]}"]

    absolute = resolve_dotted(name, mi)
    split = model.split_function(absolute)
    if split is not None:
        mod, sym = split
        target = model.function(mod, sym)
        if target is not None:
            return "project", target
        # `Cls()` constructor or attr of a project module we can't see
        return "extern", absolute
    return "extern", absolute


def own_body_nodes(fn: FunctionInfo):
    """Nodes in `fn`'s own body, excluding nested function defs (those
    are separate FunctionInfos)."""
    nested_ids = set()
    for n in ast.walk(fn.node):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) and n is not fn.node:
            for x in ast.walk(n):
                # madsim: allow(D004) — AST node identity within ONE
                # lint process (membership test only); nothing derived
                # from the address reaches findings or sim state
                nested_ids.add(id(x))
    for node in ast.walk(fn.node):
        if id(node) not in nested_ids or node is fn.node:  # madsim: allow(D004) — same membership test
            yield node


def iter_calls(fn: FunctionInfo):
    for node in own_body_nodes(fn):
        if isinstance(node, ast.Call):
            yield node


def functions_with_param(model: ProjectModel, param: str) -> List[FunctionInfo]:
    return [
        f
        for mi in model.modules.values()
        for f in mi.functions.values()
        if param in f.params
    ]
