"""Shared stdlib-`ast` helpers for the lint passes (no jax here).

The resolvers are deliberately *syntactic*: they track import aliases
(`import time as wall` → `wall.perf_counter` resolves to
`time.perf_counter`) and nothing else. A hazard reachable only through
runtime indirection (getattr strings, callables in dicts) is out of
scope — the runtime interception madsim has and Python lacks is exactly
what this layer cannot rebuild, so it aims at the honest 95%: direct
calls, direct iteration, direct truthiness.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Tuple


def parse_source(source: str, path: str) -> ast.Module:
    return ast.parse(source, filename=path)


class ImportMap:
    """local name -> dotted origin ("wall" -> "time",
    "io_callback" -> "jax.experimental.io_callback"). Relative imports
    resolve to ".<module>" so they can never collide with stdlib
    names (the package's own `time`/`rand` modules are the point)."""

    def __init__(self, tree: ast.Module):
        self.names: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    local = alias.asname or alias.name.split(".")[0]
                    # `import a.b` binds `a`; `import a.b as c` binds a.b
                    self.names[local] = alias.name if alias.asname else alias.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom):
                mod = ("." * node.level) + (node.module or "")
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    local = alias.asname or alias.name
                    self.names[local] = f"{mod}.{alias.name}" if mod else alias.name

    def resolve(self, dotted: str) -> str:
        head, _, rest = dotted.partition(".")
        origin = self.names.get(head)
        if origin is None:
            return dotted
        return f"{origin}.{rest}" if rest else origin


def dotted_name(node: ast.AST) -> Optional[str]:
    """`a.b.c` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def resolve_call(node: ast.Call, imports: ImportMap) -> Optional[str]:
    name = dotted_name(node.func)
    return imports.resolve(name) if name else None


# -- Machine subclass detection ----------------------------------------------

# Handler methods the authoring contract requires to be pure traced
# functions of their inputs (state lives in the `nodes` pytree).
PURE_HANDLERS = (
    "on_message", "on_timer", "invariant", "is_done", "summary",
    "coverage_projection",
)
# All methods whose parameters are traced jax values when the engine
# calls them (the D006 truthiness scope).
TRACED_METHODS = PURE_HANDLERS + (
    "init", "init_node", "restart_if", "amnesia_restart_if",
    "torn_restart_if",
)


def machine_classes(tree: ast.Module) -> Dict[str, ast.ClassDef]:
    """Classes that look like Machine subclasses: a base named
    `Machine`, `*Machine`, or another machine-like class defined in the
    same file (fixed point, so local hierarchies resolve)."""
    classes = {
        n.name: n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)
    }
    machine_like: Dict[str, ast.ClassDef] = {}

    def base_names(cls: ast.ClassDef) -> List[str]:
        out = []
        for b in cls.bases:
            name = dotted_name(b)
            if name:
                out.append(name.split(".")[-1])
        return out

    changed = True
    while changed:
        changed = False
        for name, cls in classes.items():
            if name in machine_like:
                continue
            for base in base_names(cls):
                if base == "Machine" or base.endswith("Machine") or base in machine_like:
                    machine_like[name] = cls
                    changed = True
                    break
    return machine_like


def class_methods(cls: ast.ClassDef) -> Iterator[ast.FunctionDef]:
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def walk_with_parents(
    tree: ast.AST,
) -> Iterator[Tuple[ast.AST, List[ast.AST]]]:
    """(node, ancestor-stack) pairs, outermost ancestor first."""
    stack: List[ast.AST] = []

    def visit(node: ast.AST):
        yield node, list(stack)
        stack.append(node)
        for child in ast.iter_child_nodes(node):
            yield from visit(child)
        stack.pop()

    yield from visit(tree)
