"""Tracing spans — structured logging context per node/task.

Reference parity (§5.1): every node gets an `error_span!("node")` and
every task a child span entered on each poll (madsim/src/sim/task/
mod.rs:116-131, runtime/context.rs:59-66), so log lines carry which
simulated process emitted them. Here a logging.Filter injects
`%(sim)s` = "t=<virtual time> node=<name>/<id> task=<id>" into every
record emitted inside a simulation, plus an `@instrument` decorator for
span-like entry/exit logs.
"""

from __future__ import annotations

import functools
import inspect
import json
import logging
import time
from typing import Any, Callable, Optional

from . import _context


class SimContextFilter(logging.Filter):
    """Injects the current simulation context into log records."""

    def filter(self, record: logging.LogRecord) -> bool:
        ctx = _context.try_current()
        if ctx is None:
            record.sim = "-"
            return True
        t_ns = ctx.executor.time.now_ns()
        task = ctx.current_task
        if task is not None:
            node = task.node
            record.sim = f"t={t_ns / 1e9:.6f}s node={node.name}/{node.id} task={task.id}"
        else:
            record.sim = f"t={t_ns / 1e9:.6f}s node=main"
        return True


class JsonlHandler(logging.Handler):
    """Structured JSONL log sink: one JSON object per record —
    {"ts", "level", "logger", "sim", "msg"} — append-mode, grep/jq-able.
    The machine-readable counterpart of the human StreamHandler format
    (engine traces have their own serializer, engine/trace_export.py)."""

    def __init__(self, path: str):
        super().__init__()
        self._f = open(path, "a")

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self._f.write(
                json.dumps(
                    {
                        # madsim: allow(D001) — log-record wall stamp
                        "ts": round(time.time(), 6),
                        "level": record.levelname,
                        "logger": record.name,
                        "sim": getattr(record, "sim", "-"),
                        "msg": record.getMessage(),
                    }
                )
            )
            self._f.write("\n")
            self._f.flush()
        except Exception:  # never let logging take down the sim
            self.handleError(record)

    def close(self) -> None:
        try:
            self._f.close()
        finally:
            super().close()


def init_tracing(level: str = "INFO", jsonl_path: Optional[str] = None) -> None:
    """Install a handler whose format includes the sim span context
    (reference: init_logger, sim/runtime/mod.rs:445-449). With
    `jsonl_path`, a structured JSONL sink (JsonlHandler) is installed
    alongside the human-readable stream handler."""
    root = logging.getLogger()
    root.setLevel(getattr(logging, level.upper()))
    handler = logging.StreamHandler()
    handler.setFormatter(logging.Formatter("%(levelname)s [%(sim)s] %(name)s: %(message)s"))
    handler.addFilter(SimContextFilter())
    root.addHandler(handler)
    if jsonl_path:
        jh = JsonlHandler(jsonl_path)
        jh.addFilter(SimContextFilter())
        root.addHandler(jh)


class StatsEmitter:
    """Time-series run telemetry for long hunts/benches — observable
    from OUTSIDE the process, which a log stream is not:

      * `<base>.jsonl` — one JSON object per emitted record (append;
        the whole history, replotting-friendly);
      * `<base>.prom` — a Prometheus textfile-collector snapshot of the
        LATEST record's numeric leaves (node_exporter's textfile
        directory, or curl via `serve --service stats` /metrics);
      * `<base>.json` — the latest record verbatim (the `/stats`
        endpoint's payload; dashboards read one file, not a log).

    Snapshots are written atomically (tmp + rename) so a scraper never
    reads a torn file — the latest-snapshot JSON included, which is what
    lets the fleet control plane serve `/jobs/{id}` live feeds without
    ever observing a torn record. Records are plain dicts; nested dicts
    flatten to `a_b_c` gauge names, non-numeric leaves are JSONL-only.
    Emission must never take down a hunt: I/O errors are swallowed
    after the constructor proves the base path writable.

    `labels` namespaces the Prometheus textfile: every gauge renders as
    ``name{k="v",...} value``, so many emitters (one per fleet job) can
    be concatenated into one exposition — the fleet `/metrics` endpoint
    does exactly that with ``labels={"job": <id>}``."""

    def __init__(self, base: str, prefix: str = "madsim_tpu",
                 labels: Optional[dict] = None):
        self.base = base
        self.prefix = prefix
        self.labels = dict(labels) if labels else None
        self.seq = 0
        self._jsonl = open(base + ".jsonl", "a")

    @property
    def jsonl_path(self) -> str:
        return self.base + ".jsonl"

    @property
    def prom_path(self) -> str:
        return self.base + ".prom"

    @property
    def snapshot_path(self) -> str:
        return self.base + ".json"

    @staticmethod
    def _flatten(record: dict, prefix: str = "") -> dict:
        out: dict = {}
        for k, v in record.items():
            key = f"{prefix}_{k}" if prefix else str(k)
            if isinstance(v, dict):
                out.update(StatsEmitter._flatten(v, key))
            elif isinstance(v, bool):
                out[key] = int(v)
            elif isinstance(v, (int, float)):
                out[key] = v
        return out

    def _atomic_write(self, path: str, text: str) -> None:
        # the shared rename discipline, WITHOUT the fsync half: these
        # snapshots are rewritten every batch and are throwaway on
        # crash — a scraper must never see a torn file, but losing the
        # latest one to a power cut costs one poll interval
        from .runtime.atomicio import atomic_write_text

        atomic_write_text(path, text, fsync=False)

    def emit(self, record: dict) -> dict:
        """Emit one record (a plain dict of stats). Returns the record
        as written (with `ts`/`seq` stamped). The write rides the host
        timeline as a `stats_emit` span when a PerfRecorder is active
        (madsim_tpu/perf) — emitter I/O is part of the observability
        tax the timeline exists to expose."""
        from .perf.recorder import maybe_span

        self.seq += 1
        # madsim: allow(D001) — JSONL sink stamps host wall time
        row = {"ts": round(time.time(), 6), "seq": self.seq, **record}
        with maybe_span("stats_emit"):
            return self._emit_row(row)

    def _label_suffix(self) -> str:
        if not self.labels:
            return ""
        rendered = ",".join(
            '{}="{}"'.format(
                k, str(v).replace("\\", "\\\\").replace('"', '\\"')
            )
            for k, v in sorted(self.labels.items())
        )
        return "{" + rendered + "}"

    def _emit_row(self, row: dict) -> dict:
        try:
            self._jsonl.write(json.dumps(row, sort_keys=True) + "\n")
            self._jsonl.flush()
            lines = [f"# emitted by madsim_tpu StatsEmitter (seq {self.seq})"]
            suffix = self._label_suffix()
            for k, v in sorted(self._flatten(row).items()):
                name = f"{self.prefix}_{k}".replace("-", "_").replace(".", "_")
                lines.append(f"# TYPE {name} gauge")
                lines.append(f"{name}{suffix} {v}")
            self._atomic_write(self.prom_path, "\n".join(lines) + "\n")
            self._atomic_write(
                self.snapshot_path, json.dumps(row, sort_keys=True) + "\n"
            )
        except OSError:  # telemetry must never kill the run
            pass
        return row

    def close(self) -> None:
        try:
            self._jsonl.close()
        except OSError:
            pass


def instrument(fn: Callable[..., Any] = None, *, name: str = "", level: int = logging.DEBUG):
    """Span-style decorator: logs entry/exit of a sync or async fn with
    the sim context (reference: `#[instrument]` on net ops). An
    exception exits the span as `exit <span> raised <Type>: <msg>` (at
    the same level — spans are tracing, the exception itself still
    propagates to whoever handles it)."""

    def deco(f):
        span = name or f.__qualname__
        logger = logging.getLogger(f.__module__)

        def _exit_ok():
            logger.log(level, "exit %s", span)

        def _exit_exc(exc: BaseException):
            logger.log(
                level, "exit %s raised %s: %s", span, type(exc).__name__, exc
            )

        if inspect.iscoroutinefunction(f):

            @functools.wraps(f)
            async def wrapper(*args, **kwargs):
                logger.log(level, "enter %s", span)
                try:
                    result = await f(*args, **kwargs)
                except BaseException as exc:
                    _exit_exc(exc)
                    raise
                _exit_ok()
                return result

        else:

            @functools.wraps(f)
            def wrapper(*args, **kwargs):
                logger.log(level, "enter %s", span)
                try:
                    result = f(*args, **kwargs)
                except BaseException as exc:
                    _exit_exc(exc)
                    raise
                _exit_ok()
                return result

        return wrapper

    if fn is not None:
        return deco(fn)
    return deco
