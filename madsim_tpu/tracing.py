"""Tracing spans — structured logging context per node/task.

Reference parity (§5.1): every node gets an `error_span!("node")` and
every task a child span entered on each poll (madsim/src/sim/task/
mod.rs:116-131, runtime/context.rs:59-66), so log lines carry which
simulated process emitted them. Here a logging.Filter injects
`%(sim)s` = "t=<virtual time> node=<name>/<id> task=<id>" into every
record emitted inside a simulation, plus an `@instrument` decorator for
span-like entry/exit logs.
"""

from __future__ import annotations

import functools
import inspect
import json
import logging
import time
from typing import Any, Callable, Optional

from . import _context


class SimContextFilter(logging.Filter):
    """Injects the current simulation context into log records."""

    def filter(self, record: logging.LogRecord) -> bool:
        ctx = _context.try_current()
        if ctx is None:
            record.sim = "-"
            return True
        t_ns = ctx.executor.time.now_ns()
        task = ctx.current_task
        if task is not None:
            node = task.node
            record.sim = f"t={t_ns / 1e9:.6f}s node={node.name}/{node.id} task={task.id}"
        else:
            record.sim = f"t={t_ns / 1e9:.6f}s node=main"
        return True


class JsonlHandler(logging.Handler):
    """Structured JSONL log sink: one JSON object per record —
    {"ts", "level", "logger", "sim", "msg"} — append-mode, grep/jq-able.
    The machine-readable counterpart of the human StreamHandler format
    (engine traces have their own serializer, engine/trace_export.py)."""

    def __init__(self, path: str):
        super().__init__()
        self._f = open(path, "a")

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self._f.write(
                json.dumps(
                    {
                        "ts": round(time.time(), 6),
                        "level": record.levelname,
                        "logger": record.name,
                        "sim": getattr(record, "sim", "-"),
                        "msg": record.getMessage(),
                    }
                )
            )
            self._f.write("\n")
            self._f.flush()
        except Exception:  # never let logging take down the sim
            self.handleError(record)

    def close(self) -> None:
        try:
            self._f.close()
        finally:
            super().close()


def init_tracing(level: str = "INFO", jsonl_path: Optional[str] = None) -> None:
    """Install a handler whose format includes the sim span context
    (reference: init_logger, sim/runtime/mod.rs:445-449). With
    `jsonl_path`, a structured JSONL sink (JsonlHandler) is installed
    alongside the human-readable stream handler."""
    root = logging.getLogger()
    root.setLevel(getattr(logging, level.upper()))
    handler = logging.StreamHandler()
    handler.setFormatter(logging.Formatter("%(levelname)s [%(sim)s] %(name)s: %(message)s"))
    handler.addFilter(SimContextFilter())
    root.addHandler(handler)
    if jsonl_path:
        jh = JsonlHandler(jsonl_path)
        jh.addFilter(SimContextFilter())
        root.addHandler(jh)


def instrument(fn: Callable[..., Any] = None, *, name: str = "", level: int = logging.DEBUG):
    """Span-style decorator: logs entry/exit of a sync or async fn with
    the sim context (reference: `#[instrument]` on net ops). An
    exception exits the span as `exit <span> raised <Type>: <msg>` (at
    the same level — spans are tracing, the exception itself still
    propagates to whoever handles it)."""

    def deco(f):
        span = name or f.__qualname__
        logger = logging.getLogger(f.__module__)

        def _exit_ok():
            logger.log(level, "exit %s", span)

        def _exit_exc(exc: BaseException):
            logger.log(
                level, "exit %s raised %s: %s", span, type(exc).__name__, exc
            )

        if inspect.iscoroutinefunction(f):

            @functools.wraps(f)
            async def wrapper(*args, **kwargs):
                logger.log(level, "enter %s", span)
                try:
                    result = await f(*args, **kwargs)
                except BaseException as exc:
                    _exit_exc(exc)
                    raise
                _exit_ok()
                return result

        else:

            @functools.wraps(f)
            def wrapper(*args, **kwargs):
                logger.log(level, "enter %s", span)
                try:
                    result = f(*args, **kwargs)
                except BaseException as exc:
                    _exit_exc(exc)
                    raise
                _exit_ok()
                return result

        return wrapper

    if fn is not None:
        return deco(fn)
    return deco
