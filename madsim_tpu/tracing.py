"""Tracing spans — structured logging context per node/task.

Reference parity (§5.1): every node gets an `error_span!("node")` and
every task a child span entered on each poll (madsim/src/sim/task/
mod.rs:116-131, runtime/context.rs:59-66), so log lines carry which
simulated process emitted them. Here a logging.Filter injects
`%(sim)s` = "t=<virtual time> node=<name>/<id> task=<id>" into every
record emitted inside a simulation, plus an `@instrument` decorator for
span-like entry/exit logs.
"""

from __future__ import annotations

import functools
import logging
from typing import Any, Callable

from . import _context


class SimContextFilter(logging.Filter):
    """Injects the current simulation context into log records."""

    def filter(self, record: logging.LogRecord) -> bool:
        ctx = _context.try_current()
        if ctx is None:
            record.sim = "-"
            return True
        t_ns = ctx.executor.time.now_ns()
        task = ctx.current_task
        if task is not None:
            node = task.node
            record.sim = f"t={t_ns / 1e9:.6f}s node={node.name}/{node.id} task={task.id}"
        else:
            record.sim = f"t={t_ns / 1e9:.6f}s node=main"
        return True


def init_tracing(level: str = "INFO") -> None:
    """Install a handler whose format includes the sim span context
    (reference: init_logger, sim/runtime/mod.rs:445-449)."""
    root = logging.getLogger()
    root.setLevel(getattr(logging, level.upper()))
    handler = logging.StreamHandler()
    handler.setFormatter(logging.Formatter("%(levelname)s [%(sim)s] %(name)s: %(message)s"))
    handler.addFilter(SimContextFilter())
    root.addHandler(handler)


def instrument(fn: Callable[..., Any] = None, *, name: str = "", level: int = logging.DEBUG):
    """Span-style decorator: logs entry/exit of an async fn with the sim
    context (reference: `#[instrument]` on net ops)."""

    def deco(f):
        span = name or f.__qualname__
        logger = logging.getLogger(f.__module__)

        @functools.wraps(f)
        async def wrapper(*args, **kwargs):
            logger.log(level, "enter %s", span)
            try:
                return await f(*args, **kwargs)
            finally:
                logger.log(level, "exit %s", span)

        return wrapper

    if fn is not None:
        return deco(fn)
    return deco
