"""Env-driven multi-seed test harness (reference: madsim/src/sim/runtime/builder.rs).

Reads the same `MADSIM_TEST_*` environment variables as the reference
(:64-120) so existing madsim workflows translate directly:

  MADSIM_TEST_SEED                first seed (default 1... here: 1)
  MADSIM_TEST_NUM                 number of seeds to run (default 1)
  MADSIM_TEST_JOBS                seeds run concurrently (default 1)
  MADSIM_TEST_CONFIG              path to a TOML Config file
  MADSIM_TEST_TIME_LIMIT          virtual-seconds limit per run
  MADSIM_TEST_CHECK_DETERMINISM   run every seed twice + compare RNG logs

On failure it prints the reproduction hint, like the reference's
"MADSIM_TEST_SEED={seed}" message (sim/runtime/mod.rs:205-210).
"""

from __future__ import annotations

import concurrent.futures
import functools
import os
import sys
import threading
import warnings
from typing import Any, Callable, Coroutine, List, Optional

from ..config import Config
from . import Runtime


class Builder:
    """Reference: sim/runtime/builder.rs:7-22 `Builder`."""

    def __init__(
        self,
        seed: int = 1,
        count: int = 1,
        jobs: int = 1,
        config: Optional[Config] = None,
        time_limit: Optional[float] = None,
        check: bool = False,
    ):
        self.seed = seed
        self.count = count
        self.jobs = jobs
        self.config = config
        self.time_limit = time_limit
        self.check = check

    @staticmethod
    def from_env() -> "Builder":
        """Reference: builder.rs:64-120 `from_env`."""
        config = None
        config_path = os.environ.get("MADSIM_TEST_CONFIG")
        if config_path:
            with open(config_path, "r", encoding="utf-8") as f:
                config = Config.from_toml(f.read())
        time_limit_s = os.environ.get("MADSIM_TEST_TIME_LIMIT")
        return Builder(
            seed=int(os.environ.get("MADSIM_TEST_SEED", "1")),
            count=int(os.environ.get("MADSIM_TEST_NUM", "1")),
            jobs=int(os.environ.get("MADSIM_TEST_JOBS", "1")),
            config=config,
            time_limit=float(time_limit_s) if time_limit_s else None,
            check=os.environ.get("MADSIM_TEST_CHECK_DETERMINISM", "") not in ("", "0", "false"),
        )

    def _run_one(self, seed: int, factory: Callable[[], Coroutine]) -> Any:
        if self.check:
            return Runtime.check_determinism(
                seed, factory, self.config, time_limit=self.time_limit
            )
        rt = Runtime(seed, self.config)
        if self.time_limit is not None:
            rt.set_time_limit(self.time_limit)
        return rt.block_on(factory())

    def run(self, factory: Callable[[], Coroutine]) -> Any:
        """Run `count` seeds, `jobs` at a time. Returns the result of the
        last seed.

        Parallelism is real: each concurrent seed gets its own OS
        *process* (reference runs one runtime per OS thread,
        builder.rs:121-160 — genuinely parallel in Rust; Python threads
        would serialize CPU-bound sims on the GIL, so `fork` is the
        faithful equivalent). Falls back to threads where fork is
        unavailable."""
        seeds = list(range(self.seed, self.seed + self.count))
        result: Any = None
        if self.jobs <= 1:
            for seed in seeds:
                result = self._run_in_thread(seed, factory)
            return result
        # fork only on linux: macOS fork() is unsafe once threads/frameworks
        # are up (CPython's own default there is spawn for this reason)
        if sys.platform.startswith("linux"):
            return self._run_parallel_processes(seeds, factory)
        return self._run_parallel_threads(seeds, factory)

    def _run_parallel_processes(
        self, seeds: List[int], factory: Callable[[], Coroutine]
    ) -> Any:
        """fork one child per seed, at most `jobs` alive at once. The
        factory closure and `self` are inherited through fork (no
        pickling of the workload); only results/errors cross the pipe."""
        import multiprocessing as mp
        import pickle
        import traceback
        from queue import Empty

        ctx = mp.get_context("fork")
        queue: Any = ctx.Queue()

        def child(seed: int) -> None:
            code = 0
            try:
                value = self._run_one(seed, factory)
                try:
                    pickle.dumps(value)
                except Exception:  # unpicklable result: drop the value only
                    value = None
                queue.put((seed, None, value))
            except BaseException:  # noqa: BLE001
                queue.put((seed, traceback.format_exc(), None))
                code = 1
            # flush the queue's feeder thread BEFORE the hard exit, or the
            # result can die buffered in the child
            queue.close()
            queue.join_thread()
            # _exit skips atexit hooks (forked jax/XLA teardown can hang)
            os._exit(code)

        pending = list(seeds)
        procs: dict[int, Any] = {}
        last_result: List[Any] = [None]
        errors: dict[int, str] = {}

        def launch_up_to_jobs() -> None:
            while pending and len(procs) < self.jobs:
                seed = pending.pop(0)
                p = ctx.Process(target=child, args=(seed,), name=f"madsim-seed-{seed}")
                with warnings.catch_warnings():
                    # CPython warns that forking a multi-threaded process
                    # (jax's pools) can deadlock the child. Children here
                    # run only the pure-Python/C++ host sim — never jax —
                    # and leave via os._exit, so inherited jax locks are
                    # never acquired.
                    warnings.simplefilter("ignore", DeprecationWarning)
                    warnings.simplefilter("ignore", RuntimeWarning)
                    p.start()
                procs[seed] = p

        def record(seed: int, err: Any, value: Any) -> None:
            if err is None:
                if seed == seeds[-1]:
                    last_result[0] = value
            else:
                errors[seed] = err
            p = procs.pop(seed, None)
            if p is not None:
                p.join()

        launch_up_to_jobs()
        while procs:
            try:
                record(*queue.get(timeout=0.5))
            except Empty:
                # a message can still be in flight for a child that already
                # exited — drain everything available before declaring any
                # dead child result-less
                while True:
                    try:
                        record(*queue.get_nowait())
                    except Empty:
                        break
                for seed, p in list(procs.items()):
                    if not p.is_alive():
                        p.join()
                        errors[seed] = (
                            f"simulation process died (exit code {p.exitcode}) "
                            f"without reporting a result"
                        )
                        del procs[seed]
            launch_up_to_jobs()

        if errors:
            for seed in sorted(errors):
                print(
                    f"note: run with `MADSIM_TEST_SEED={seed}` environment "
                    f"variable to reproduce this failure",
                    file=sys.stderr,
                )
            first = min(errors)
            raise RuntimeError(
                f"seed {first} failed:\n{errors[first]}"
                + (f"\n({len(errors)} seeds failed in total)" if len(errors) > 1 else "")
            )
        return last_result[0]

    def _run_parallel_threads(
        self, seeds: List[int], factory: Callable[[], Coroutine]
    ) -> Any:
        """Thread fallback for platforms without safe fork (GIL-serialized)."""
        last_result: Any = None
        with concurrent.futures.ThreadPoolExecutor(max_workers=self.jobs) as pool:
            futs = {pool.submit(self._run_one, seed, factory): seed for seed in seeds}
            for fut in concurrent.futures.as_completed(futs):
                seed = futs[fut]
                try:
                    value = fut.result()
                    if seed == seeds[-1]:
                        last_result = value
                except BaseException:
                    print(
                        f"note: run with `MADSIM_TEST_SEED={seed}` environment "
                        f"variable to reproduce this failure",
                        file=sys.stderr,
                    )
                    raise
        return last_result

    def _run_in_thread(self, seed: int, factory: Callable[[], Coroutine]) -> Any:
        """One runtime per fresh thread, like the reference harness."""
        box: List[Any] = [None, None]

        def target() -> None:
            try:
                box[0] = self._run_one(seed, factory)
            except BaseException as exc:  # noqa: BLE001
                box[1] = exc

        t = threading.Thread(target=target, name=f"madsim-seed-{seed}")
        t.start()
        t.join()
        if box[1] is not None:
            print(
                f"note: run with `MADSIM_TEST_SEED={seed}` environment "
                f"variable to reproduce this failure",
                file=sys.stderr,
            )
            raise box[1]
        return box[0]


def main(fn: Callable[..., Coroutine]) -> Callable[..., Any]:
    """`#[madsim::main]` equivalent (reference: madsim-macros/src/lib.rs:115-152):
    decorate an async fn so calling it runs `Builder.from_env().run`."""

    @functools.wraps(fn)
    def wrapper(*args: Any, **kwargs: Any) -> Any:
        return Builder.from_env().run(lambda: fn(*args, **kwargs))

    return wrapper


# `#[madsim::test]` equivalent — usable directly under pytest.
test = main
