"""Host-side scenario-coverage decoding, plateau detection, persistence.

The device half (ops/coverage.py) folds popped events into per-lane hit
maps and OR-reduces them into one `bool[2^slots_log2]` vector at stream
harvest. Everything downstream of that vector lives here, numpy-only (no
jax import — the `madsim_tpu coverage` subcommand and the `serve`
stats endpoint must work on boxes with no accelerator stack warm):

  * `coverage_dict` — the summary run_stream stats embed (slots hit /
    fraction / per-band marginals);
  * `cell_table` / `top_uncovered` — the (band, phase) cell decode the
    CLI report ranks ("which fault kind x model phase has the fleet
    barely explored");
  * `PlateauDetector` — the `--stop-on-plateau` policy: N consecutive
    batches adding zero new slots means the hunt saturated its scenario
    space (FoundationDB's stop signal, made explicit);
  * `save_coverage_doc` / `load_coverage_doc` / `diff_maps` — the
    `hunt --coverage-out` artifact (base64 maps keyed by machine) and
    cross-run diffing.

Slot layout (mirrors ops/coverage.py as literals — keep in sync). Two
banded layout versions exist; maps and docs carry `band_bits` so every
historical 3-bit doc keeps rendering:

    v1 (band_bits=3, PR-4):  slot = [ band:3 | phase:3 | mix:(slots_log2-6) ]
    v2 (band_bits=4, PR-5):  slot = [ band:4 | phase:3 | mix:(slots_log2-7) ]

v2 is selected by the engine whenever a PR-5 chaos capability
(pause/skew/dup/strict_restart) can occur; it adds the pause/skew fault
bands plus the synthetic dup (a step that enqueued a Bernoulli
duplicate) and amnesia (a strict-restart wipe) bands.
"""

from __future__ import annotations

import base64
import json
from typing import Dict, Optional, Sequence

import numpy as np

COV_BAND_BITS = 3
COV_PHASE_BITS = 3
COV_BANDS = 1 << COV_BAND_BITS
COV_PHASES = 1 << COV_PHASE_BITS
# band 0/1: event class; 2..: fault kind — the shared table in
# madsim_tpu/kinds.py (pure literals, no jax import for this decoder)
from ..kinds import COV_BAND_NAMES, COV_BAND_NAMES_V2

# doc v1: band_bits implicitly 3; v2 carries an explicit band_bits field
COV_DOC_VERSION = 2
_ACCEPTED_DOC_VERSIONS = (1, 2)


def band_names(band_bits: int = COV_BAND_BITS) -> tuple:
    if band_bits == 3:
        return COV_BAND_NAMES
    if band_bits == 4:
        return COV_BAND_NAMES_V2
    raise ValueError(f"unknown coverage band layout: band_bits={band_bits}")


def _as_bool_map(map_arr) -> np.ndarray:
    m = np.asarray(map_arr)
    return m if m.dtype == bool else m > 0


def unpack_map(words, slots_log2: int) -> np.ndarray:
    """Decode the device's packed bit map (int32[..., 2^slots_log2/32],
    slot s in word s >> 5, bit s & 31) to bool[..., 2^slots_log2].
    Works on a single map or a [lanes, words] batch."""
    w = np.asarray(words).astype(np.uint32)
    if w.shape[-1] * 32 != 1 << slots_log2:
        raise ValueError(
            f"packed map has {w.shape[-1]} words, expected "
            f"{(1 << slots_log2) // 32} for 2^{slots_log2} slots"
        )
    bits = (w[..., :, None] >> np.arange(32, dtype=np.uint32)) & 1
    return bits.reshape(*w.shape[:-1], 1 << slots_log2).astype(bool)


def coverage_dict(map_arr, slots_log2: int, band_bits: int = COV_BAND_BITS) -> dict:
    """Summarize a global coverage vector: total slots hit, fraction,
    and the per-band marginals (how much of each event class / fault
    kind's slot space has been reached)."""
    m = _as_bool_map(map_arr)
    total = 1 << slots_log2
    if m.size != total:
        raise ValueError(f"map has {m.size} slots, expected {total}")
    per_band = m.reshape(1 << band_bits, -1).sum(axis=1)
    hit = int(m.sum())
    return {
        "slots_hit": hit,
        "slots_total": total,
        "fraction": round(hit / total, 6),
        "by_band": {
            name: int(n) for name, n in zip(band_names(band_bits), per_band)
        },
    }


def cell_table(map_arr, slots_log2: int, band_bits: int = COV_BAND_BITS) -> np.ndarray:
    """[bands, COV_PHASES] hit counts — the fault/event-class x
    model-phase cell grid. Each cell owns
    2^(slots_log2-band_bits-3) mix slots."""
    m = _as_bool_map(map_arr)
    return m.reshape(1 << band_bits, COV_PHASES, -1).sum(axis=2)


def top_uncovered(
    map_arr, slots_log2: int, top: int = 8, band_bits: int = COV_BAND_BITS
) -> list:
    """The `top` least-covered (band, phase) cells that have been
    TOUCHED at least once, plus every never-touched cell, ranked
    emptiest-first. A touched-but-thin cell is a reachable scenario
    class the hunt has barely explored — the steering signal a
    coverage-guided search would consume. Reserved v2 bands are
    skipped (nothing can ever land there)."""
    cells = cell_table(map_arr, slots_log2, band_bits=band_bits)
    cell_size = 1 << (slots_log2 - band_bits - COV_PHASE_BITS)
    names = band_names(band_bits)
    out = []
    for b in range(1 << band_bits):
        if names[b].startswith("reserved"):
            continue
        for p in range(COV_PHASES):
            out.append(
                {
                    "band": names[b],
                    "phase": p,
                    "hit": int(cells[b, p]),
                    "fraction": round(int(cells[b, p]) / cell_size, 4),
                }
            )
    out.sort(key=lambda c: (c["hit"], c["band"], c["phase"]))
    return out[:top]


class PlateauDetector:
    """Saturation policy for `--stop-on-plateau N`: fire after N
    consecutive observations that added zero new slots to the
    cumulative total. Feed it the RUNNING total (monotone), not deltas —
    it derives deltas itself, so a poll/batch boundary mismatch can't
    double-count."""

    def __init__(self, patience: int):
        if patience < 1:
            raise ValueError("plateau patience must be >= 1")
        self.patience = patience
        self.best = 0
        self.batches = 0
        self.streak = 0

    def update(self, slots_hit_total: int) -> bool:
        """Observe one batch's cumulative slots-hit; returns True when
        the plateau policy says stop."""
        self.batches += 1
        new = max(0, int(slots_hit_total) - self.best)
        self.best = max(self.best, int(slots_hit_total))
        self.streak = self.streak + 1 if new == 0 else 0
        return self.plateaued

    @property
    def plateaued(self) -> bool:
        return self.streak >= self.patience


# -- persistence (`hunt --coverage-out`) -------------------------------------


def encode_map(map_arr) -> str:
    """bool map -> base64 of packed bits (2^14 slots -> ~2.7 KiB)."""
    m = _as_bool_map(map_arr)
    return base64.b64encode(np.packbits(m).tobytes()).decode("ascii")


def decode_map(b64: str, slots_log2: int) -> np.ndarray:
    raw = np.frombuffer(base64.b64decode(b64), dtype=np.uint8)
    return np.unpackbits(raw)[: 1 << slots_log2].astype(bool)


def make_coverage_doc(
    maps: Dict[str, np.ndarray],
    slots_log2: int,
    meta: Optional[dict] = None,
    band_bits: int = COV_BAND_BITS,
) -> dict:
    """Build the JSON document `hunt --coverage-out` writes: one map per
    machine name (the per-model breakdown the report renders). 3-band-bit
    maps are written as version-1 docs (byte-compatible with every
    pre-existing consumer); the 4-bit layout bumps the doc version and
    records band_bits explicitly."""
    version = 1 if band_bits == COV_BAND_BITS else COV_DOC_VERSION
    doc = {
        "version": version,
        "slots_log2": slots_log2,
        "meta": dict(meta or {}),
        "maps": {
            name: {
                "map_b64": encode_map(m),
                **coverage_dict(m, slots_log2, band_bits=band_bits),
            }
            for name, m in sorted(maps.items())
        },
    }
    if version != 1:
        doc["band_bits"] = band_bits
    return doc


def save_coverage_doc(path: str, doc: dict) -> None:
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")


def load_coverage_doc(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    if doc.get("version") not in _ACCEPTED_DOC_VERSIONS:
        raise ValueError(
            f"{path}: coverage doc version {doc.get('version')!r}, "
            f"expected one of {_ACCEPTED_DOC_VERSIONS}"
        )
    return doc


def doc_band_bits(doc: dict) -> int:
    """The banded layout a doc was written under (v1 docs predate the
    field and are always 3-bit)."""
    return int(doc.get("band_bits", COV_BAND_BITS))


def doc_maps(doc: dict) -> Dict[str, np.ndarray]:
    L = doc["slots_log2"]
    return {
        name: decode_map(entry["map_b64"], L)
        for name, entry in doc["maps"].items()
    }


def diff_maps(a: np.ndarray, b: np.ndarray) -> dict:
    """Cross-run comparison: slots only run A reached, only run B,
    both. The "did 10k more seeds buy anything" answer in three ints."""
    a, b = _as_bool_map(a), _as_bool_map(b)
    return {
        "only_a": int((a & ~b).sum()),
        "only_b": int((~a & b).sum()),
        "both": int((a & b).sum()),
    }


def render_report(doc: dict, top: int = 8, diff_doc: Optional[dict] = None) -> str:
    """Human-readable coverage report for one (optionally two) docs."""
    L = doc["slots_log2"]
    bb = doc_band_bits(doc)
    lines = []
    other = doc_maps(diff_doc) if diff_doc is not None else {}
    for name, m in doc_maps(doc).items():
        d = coverage_dict(m, L, band_bits=bb)
        lines.append(
            f"{name}: {d['slots_hit']}/{d['slots_total']} slots "
            f"({100 * d['fraction']:.2f}%)"
        )
        band_txt = ", ".join(
            f"{k}={v}" for k, v in d["by_band"].items() if v
        )
        lines.append(f"  by band: {band_txt or 'none'}")
        cells = top_uncovered(m, L, top=top, band_bits=bb)
        worst = ", ".join(
            f"{c['band']}x{c['phase']}={c['hit']}" for c in cells
        )
        lines.append(f"  thinnest band x phase cells: {worst}")
        if name in other:
            dd = diff_maps(other[name], m)
            lines.append(
                f"  vs baseline: +{dd['only_b']} new slots, "
                f"-{dd['only_a']} lost, {dd['both']} shared"
            )
    if not lines:
        lines.append("(coverage doc has no maps)")
    return "\n".join(lines)
