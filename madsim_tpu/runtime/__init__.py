"""Runtime & supervisor (reference: madsim/src/sim/runtime/mod.rs).

`Runtime` owns the RNG, virtual clock, executor and simulators;
`Handle` is the supervisor API (kill / restart / pause / resume /
ctrl-c per node); `NodeBuilder` creates simulated processes.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Coroutine, Dict, List, Optional, Type, Union

from .. import _context
from ..config import Config
from ..errors import NonDeterminism
from ..plugin import Simulator
from ..rand import GlobalRng
from ..task.executor import Executor, NodeInfo, MAIN_NODE_ID
from ..task.join import JoinHandle
from ..time import TimeHandle
from .metrics import RuntimeMetrics

__all__ = ["Runtime", "Handle", "NodeBuilder", "NodeHandle", "hostname", "init_logger"]


def _default_simulators() -> List[Type[Simulator]]:
    sims: List[Type[Simulator]] = []
    try:
        from ..net import NetSim

        sims.append(NetSim)
    except ImportError:  # pragma: no cover - net not built yet
        pass
    try:
        from ..fs import FsSim

        sims.append(FsSim)
    except ImportError:  # pragma: no cover
        pass
    return sims


class Runtime:
    """The simulation runtime (reference: sim/runtime/mod.rs:34 `Runtime`).

    One seed => one bit-identical execution of `block_on`.
    """

    def __init__(self, seed: int = 0, config: Optional[Config] = None):
        self.seed = seed
        self.config = config or Config()
        self.rng = GlobalRng(seed)
        self.time = TimeHandle(self.rng)
        self.executor = Executor(self.rng, self.time)
        self.simulators: Dict[type, Simulator] = {}
        self.executor.simulators = self.simulators  # for plugin.simulator()
        self.handle = Handle(self)
        self.executor.runtime_handle = self.handle  # for Handle.current()
        for sim_cls in _default_simulators():
            self.add_simulator(sim_cls)

    @staticmethod
    def with_seed_and_config(seed: int, config: Config) -> "Runtime":
        """Reference: sim/runtime/mod.rs:53 `with_seed_and_config`."""
        return Runtime(seed, config)

    def add_simulator(self, sim_cls: Type[Simulator]) -> None:
        """Reference: sim/runtime/mod.rs:72 `add_simulator`."""
        sim = sim_cls(self.rng, self.time, self.config)
        self.simulators[sim_cls] = sim
        self.executor.create_hooks.append(sim.create_node)
        self.executor.reset_hooks.append(sim.reset_node)
        # Nodes created before this simulator was added (e.g. main).
        for node_id in self.executor.nodes:
            sim.create_node(node_id)

    def set_time_limit(self, duration: Union[int, float]) -> None:
        """Reference: sim/runtime/mod.rs:148."""
        self.executor.set_time_limit(duration)

    def create_node(self) -> "NodeBuilder":
        return NodeBuilder(self.handle)

    def block_on(self, coro: Coroutine) -> Any:
        """Run the simulation until `coro` completes
        (reference: sim/runtime/mod.rs:127-130)."""
        ctx = _context.SimContext(self.executor)
        _context.enter(ctx)
        try:
            return self.executor.block_on(coro)
        finally:
            _context.exit()

    def metrics(self) -> RuntimeMetrics:
        return RuntimeMetrics(self.executor)

    @staticmethod
    def check_determinism(
        seed: int,
        factory: Callable[[], Coroutine],
        config: Optional[Config] = None,
        time_limit: Optional[float] = None,
    ) -> Any:
        """Run a workload twice with the same seed and compare the RNG draw
        logs; raises `NonDeterminism` on divergence
        (reference: sim/runtime/mod.rs:178-203).

        Each run executes on a fresh thread for full isolation, like the
        reference.
        """
        results: List[Any] = [None, None]
        errors: List[Optional[BaseException]] = [None, None]
        log_box: List[Optional[List[int]]] = [None]

        def run(i: int) -> None:
            try:
                rt = Runtime(seed, config)
                if time_limit is not None:
                    rt.set_time_limit(time_limit)
                if i == 0:
                    rt.rng.enable_log()
                else:
                    rt.rng.enable_check(log_box[0])  # type: ignore[arg-type]
                results[i] = rt.block_on(factory())
                if i == 0:
                    log_box[0] = rt.rng.take_log()
                else:
                    rt.rng.finish_check()
            except BaseException as exc:  # noqa: BLE001
                errors[i] = exc

        for i in range(2):
            t = threading.Thread(target=run, args=(i,), name=f"madsim-check-{i}")
            t.start()
            t.join()
            if errors[i] is not None:
                raise errors[i]  # type: ignore[misc]
        return results[1]


class Handle:
    """Supervisor handle (reference: sim/runtime/mod.rs:214 `Handle`)."""

    def __init__(self, runtime: Runtime):
        self._runtime = runtime

    @staticmethod
    def current() -> "Handle":
        """Handle of the simulation running on this thread."""
        executor = _context.current().executor
        return executor.runtime_handle  # type: ignore[attr-defined]

    @property
    def seed(self) -> int:
        return self._runtime.seed

    @property
    def config(self) -> Config:
        return self._runtime.config

    @property
    def time(self) -> TimeHandle:
        return self._runtime.time

    @property
    def rng(self) -> GlobalRng:
        return self._runtime.rng

    def _node_id(self, node: Union[int, "NodeHandle"]) -> int:
        return node.id if isinstance(node, NodeHandle) else node

    def kill(self, node: Union[int, "NodeHandle"]) -> None:
        """Reference: sim/runtime/mod.rs:276."""
        self._runtime.executor.kill(self._node_id(node))

    def restart(self, node: Union[int, "NodeHandle"]) -> None:
        """Reference: sim/runtime/mod.rs:281."""
        self._runtime.executor.restart(self._node_id(node))

    def pause(self, node: Union[int, "NodeHandle"]) -> None:
        """Reference: sim/runtime/mod.rs:286."""
        self._runtime.executor.pause(self._node_id(node))

    def resume(self, node: Union[int, "NodeHandle"]) -> None:
        """Reference: sim/runtime/mod.rs:291."""
        self._runtime.executor.resume(self._node_id(node))

    def send_ctrl_c(self, node: Union[int, "NodeHandle"]) -> None:
        """Reference: sim/runtime/mod.rs:296."""
        self._runtime.executor.send_ctrl_c(self._node_id(node))

    def is_killed(self, node: Union[int, "NodeHandle"]) -> bool:
        return self._runtime.executor.nodes[self._node_id(node)].killed

    def create_node(self) -> "NodeBuilder":
        return NodeBuilder(self)


class NodeBuilder:
    """Builds a simulated process (reference: sim/runtime/mod.rs:325)."""

    def __init__(self, handle: Handle):
        self._handle = handle
        self._name = ""
        self._ip: Optional[str] = None
        self._cores = 1
        self._init: Optional[Callable[[], Coroutine]] = None
        self._restart_on_panic = False
        self._restart_on_panic_matching: Optional[Callable[[BaseException], bool]] = None

    def name(self, name: str) -> "NodeBuilder":
        self._name = name
        return self

    def ip(self, ip: str) -> "NodeBuilder":
        """Reference: sim/runtime/mod.rs:390."""
        self._ip = ip
        return self

    def cores(self, cores: int) -> "NodeBuilder":
        """Reference: sim/runtime/mod.rs:398."""
        self._cores = cores
        return self

    def init(self, factory: Callable[[], Coroutine]) -> "NodeBuilder":
        """Async closure run at node start and at every restart
        (reference: sim/runtime/mod.rs:359)."""
        self._init = factory
        return self

    def restart_on_panic(self) -> "NodeBuilder":
        """Reference: sim/runtime/mod.rs:377."""
        self._restart_on_panic = True
        return self

    def restart_on_panic_matching(self, pred: Callable[[BaseException], bool]) -> "NodeBuilder":
        self._restart_on_panic_matching = pred
        return self

    def build(self) -> "NodeHandle":
        executor = self._handle._runtime.executor
        node = executor.create_node(self._name)
        node.ip = self._ip
        node.cores = self._cores
        node.init = self._init
        node.restart_on_panic = self._restart_on_panic
        node.restart_on_panic_matching = self._restart_on_panic_matching
        if self._ip is not None:
            for sim in self._handle._runtime.simulators.values():
                hook = getattr(sim, "set_node_ip", None)
                if hook is not None:
                    hook(node.id, self._ip)
        if self._init is not None:
            executor.spawn(self._init(), node, location="<node-init>")
        return NodeHandle(self._handle, node)


class NodeHandle:
    """Handle to a simulated process (reference: sim/runtime/mod.rs NodeHandle)."""

    def __init__(self, handle: Handle, node: NodeInfo):
        self._handle = handle
        self._node = node

    @property
    def id(self) -> int:
        return self._node.id

    @property
    def name(self) -> str:
        return self._node.name

    @property
    def ip(self) -> Optional[str]:
        return self._node.ip

    def spawn(self, coro: Coroutine, *, name: str = "") -> JoinHandle:
        """Spawn a task onto this node."""
        import sys

        frame = sys._getframe(1)
        location = f"{frame.f_code.co_filename}:{frame.f_lineno}"
        executor = self._handle._runtime.executor
        task = executor.spawn(coro, self._node, location=location, name=name)
        return JoinHandle(task)


def hostname() -> str:
    """The current node's name (reference 0.2.34: the libc gethostname
    interposition returns the node's name, or `madsim-node-{id}` for
    unnamed nodes — here that default is baked in at node creation, so
    this is simply the name)."""
    from .. import _context

    return _context.current_task().node.name


def init_logger(level: str = "INFO") -> None:
    """Install a basic logging config (reference: sim/runtime/mod.rs:445
    `init_logger` installing tracing-subscriber)."""
    import logging

    logging.basicConfig(
        level=getattr(logging, level.upper()),
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
