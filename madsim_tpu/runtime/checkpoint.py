"""Hunt/explore checkpointing — resume an interrupted streaming run exactly.

`hunt --checkpoint PATH` persists per-batch progress from the chunked
streaming driver (`__main__._stream_batches`): the seed cursor, the
completed/failing/infra/abandoned aggregates, the cumulative coverage
map and the plateau-detector state. A process killed between batches
resumes from the last completed batch ("resumed at batch k/n") and the
final report is bit-identical to the uninterrupted run — batch i always
consumes the same seed range, so the only state that matters is the
cursor and the aggregates, both of which are recorded atomically
(tmp + rename) after every batch.

The checkpoint carries a FINGERPRINT of every argument that shapes the
seed schedule or the failure semantics; resuming with a mismatched
command line is refused rather than silently blending two different
hunts. Pure host-side JSON — no jax import.

Guided hunts (`--guided`, madsim_tpu/search) extend the document with
a "guided" record — the bias state, seed corpus, per-batch (seed
schedule, bias state) trail and per-find escalation steps — which is
the COMPLETE remaining-schedule state: a resumed (or
replacement-worker) guided hunt recomputes the identical seed
schedule from it, asserted byte-identical in tests/test_search.py.
"""

from __future__ import annotations

import json
import os
from typing import Optional

CKPT_VERSION = 1

#: keys every complete checkpoint carries. The strict loader below
#: only validates the version (a deliberate `--checkpoint PATH` should
#: fail loudly on anything unexpected); the fleet's lenient reader and
#: `fleet fsck` additionally treat a valid-JSON document missing any of
#: these as corrupt — quarantine to `*.corrupt` and restart the stream
#: — rather than letting a torn artifact crash the farm downstream.
CKPT_REQUIRED_KEYS = frozenset({
    "fingerprint", "batch", "planned", "cursor", "completed",
    "seeds_consumed", "failing", "infra", "abandoned", "done",
})

# args fields that must match for a resume to be sound: anything that
# changes which seeds run, in what order, or what they mean.
_FINGERPRINT_FIELDS = (
    "machine",
    "nodes",
    "seed",
    "seeds",
    "batch",
    "max_steps",
    "horizon",
    "loss",
    "faults",
    "fault_tmax",
    "fault_kinds",
    "rng_stream",
    "strict_restart",
    "coverage",
    "stop_on_plateau",
    # guided mode reshapes the whole seed schedule (corpus mutants +
    # bias-selected batches): resuming a guided checkpoint without
    # --guided (or vice versa) would blend two different hunts
    "guided",
)


def fingerprint_from_args(args) -> dict:
    return {f: getattr(args, f, None) for f in _FINGERPRINT_FIELDS}


def save_checkpoint(path: str, state: dict) -> None:
    """Atomic write (the shared `runtime/atomicio` discipline: tmp +
    fsync + rename + dir-fsync): a kill mid-write leaves the previous
    checkpoint intact, never a truncated JSON — on a real filesystem,
    not just against process death. Rides the host timeline as a
    `checkpoint_write` span when a PerfRecorder is active — per-batch
    persistence is part of the wall-clock budget."""
    from ..perf.recorder import maybe_span

    from .atomicio import atomic_write_json

    doc = {"version": CKPT_VERSION, **state}
    with maybe_span("checkpoint_write"):
        atomic_write_json(path, doc)


def load_checkpoint(path: str) -> Optional[dict]:
    """Load a checkpoint, or None when the file doesn't exist (a fresh
    run). A malformed or wrong-version file raises — silently starting
    over would throw away a long hunt's progress."""
    if not os.path.exists(path):
        return None
    with open(path) as f:
        doc = json.load(f)
    if doc.get("version") != CKPT_VERSION:
        raise ValueError(
            f"{path}: checkpoint version {doc.get('version')!r}, "
            f"expected {CKPT_VERSION}"
        )
    return doc


def check_fingerprint(ckpt: dict, args) -> Optional[str]:
    """None when the checkpoint belongs to this command line; otherwise
    a human-readable description naming EVERY field that differs
    (model, kinds, gates, lanes, ...) — a drifted resume usually drifts
    several fields at once, and the fleet worker surfaces this message
    verbatim as the job's `failed` reason, so it must diagnose in one
    shot rather than one refusal per rerun."""
    want = fingerprint_from_args(args)
    got = ckpt.get("fingerprint", {})
    diffs = [
        f"{field} (checkpoint {got.get(field)!r} != this run "
        f"{want.get(field)!r})"
        for field in _FINGERPRINT_FIELDS
        if got.get(field) != want.get(field)
    ]
    if not diffs:
        return None
    return (
        "checkpoint belongs to a different run — refusing to resume; "
        "differing: " + ", ".join(diffs)
    )
