"""Crash-safe atomic file writes — one discipline for every artifact.

Every durable JSON artifact in this repo (hunt checkpoints, fleet job
documents, StatsEmitter snapshots, the corpus, port files) historically
grew its own copy of the same four lines: write `<path>.tmp`, rename
over `<path>`. That is atomic against *process* death — `os.replace` is
all-or-nothing — but it is NOT atomic against power loss or a kernel
crash: the rename can be journaled while the tmp file's data blocks are
still in the page cache, leaving a zero-length or torn file behind a
rename that "succeeded". The full discipline, shared here so every
call site means the same thing by "atomic", is::

    write tmp -> flush -> fsync(tmp fd) -> rename -> fsync(directory)

The directory fsync persists the rename itself (the directory entry is
data too). `fsync=False` keeps the plain tmp+rename behavior for
artifacts that are throwaway-on-crash (e.g. per-batch stats snapshots
written many times a second).

Chaos hook (the fleet-chaos harness's injection point): when
``MADSIM_TPU_FLEET_CHAOS`` holds a JSON plan, writes whose absolute
path contains the plan's ``match`` substring are counted, and the
scheduled one dies deterministically:

* ``{"kill_at_write": K}`` — SIGKILL this process *instead of* the K-th
  write. Rename atomicity means the previous file version must survive.
* ``{"sigterm_at_write": K}`` — SIGTERM at the K-th write (once-only:
  the plan disarms itself before delivering, so the victim's handler —
  the worker's partial-span flush — can write through this same
  module on its way out). The claim under test is that a gracefully
  killed worker leaves a non-empty span dump behind.
* ``{"torn_at_write": [K, B]}`` — the kill lands mid-write: B bytes of
  the K-th payload reach the TMP file, the rename never runs, the
  process dies. The claim "atomic" makes is exactly that the final
  path still holds its previous version afterwards; `fleet fsck`
  sweeps the stale tmp.
* ``{"sigstop_at_write": K}`` — SIGSTOP self at the K-th write
  (once-only, like sigterm: the plan disarms itself first, so the
  resumed process writes on normally). This is the zombie fixture:
  the stopped worker's lease expires, a new holder takes the job,
  and when the harness SIGCONTs the zombie its writes must be
  fenced, never merged. The harness must only match paths written
  OUTSIDE the store's file locks (e.g. ``.ckpt``) — a process
  stopped while holding a flock would wedge every other worker.

The plan is parsed once per process (the harness sets the env var
before spawning the victim); `_reset_chaos_for_tests` re-arms it.

Pure stdlib, no jax, no wall-clock reads — safe to import from the
jax-free fleet control plane.
"""

from __future__ import annotations

import json
import os
import signal
from typing import Optional

_CHAOS: Optional[dict] = None
_WRITE_COUNT = 0


def _chaos_plan() -> dict:
    global _CHAOS
    if _CHAOS is None:
        raw = os.environ.get("MADSIM_TPU_FLEET_CHAOS")
        _CHAOS = json.loads(raw) if raw else {}
    return _CHAOS


def _reset_chaos_for_tests() -> None:
    global _CHAOS, _WRITE_COUNT
    _CHAOS, _WRITE_COUNT = None, 0


def _chaos_tick(path: str, text: str) -> None:
    """Count this write against the armed plan; die if it is the
    scheduled one. No-op (one dict read) when chaos is unarmed."""
    plan = _chaos_plan()
    if not plan:
        return
    match = plan.get("match")
    if match and match not in os.path.abspath(path):
        return
    global _WRITE_COUNT
    _WRITE_COUNT += 1
    n = _WRITE_COUNT
    if plan.get("kill_at_write") == n:
        os.kill(os.getpid(), signal.SIGKILL)
    if plan.get("sigterm_at_write") == n:
        # graceful-kill variant: fire ONCE and disarm before
        # delivering, because the victim's SIGTERM handler (the
        # worker's partial-span flush) appends through this same
        # writer and must go through
        plan.pop("sigterm_at_write", None)
        os.kill(os.getpid(), signal.SIGTERM)
    if plan.get("sigstop_at_write") == n:
        plan.pop("sigstop_at_write", None)  # once-only; see module doc
        os.kill(os.getpid(), signal.SIGSTOP)
    torn = plan.get("torn_at_write")
    if torn and int(torn[0]) == n:
        with open(f"{path}.tmp", "w") as f:
            f.write(text[: int(torn[1])])
        os.kill(os.getpid(), signal.SIGKILL)


def _chaos_tick_append(path: str, text: str) -> None:
    """The append-path twin of `_chaos_tick`, sharing the same write
    counter so one armed plan schedules across both disciplines. The
    torn variant differs on purpose: an append has no tmp file, so the
    partial payload lands in the REAL file — exactly the torn tail the
    jsonl readers and `fleet fsck` must tolerate."""
    plan = _chaos_plan()
    if not plan:
        return
    match = plan.get("match")
    if match and match not in os.path.abspath(path):
        return
    global _WRITE_COUNT
    _WRITE_COUNT += 1
    n = _WRITE_COUNT
    if plan.get("kill_at_write") == n:
        os.kill(os.getpid(), signal.SIGKILL)
    if plan.get("sigterm_at_write") == n:
        plan.pop("sigterm_at_write", None)  # once-only; see _chaos_tick
        os.kill(os.getpid(), signal.SIGTERM)
    if plan.get("sigstop_at_write") == n:
        plan.pop("sigstop_at_write", None)  # once-only; see _chaos_tick
        os.kill(os.getpid(), signal.SIGSTOP)
    torn = plan.get("torn_at_write")
    if torn and int(torn[0]) == n:
        with open(path, "a") as f:
            f.write(text[: int(torn[1])])
            f.flush()
            os.fsync(f.fileno())
        os.kill(os.getpid(), signal.SIGKILL)


def fsync_dir(dirpath: str) -> None:
    """Persist a just-performed rename in `dirpath`. Best-effort: some
    filesystems refuse O_RDONLY directory fsync — that degrades back to
    rename-without-dir-sync, never to an error on the write path."""
    try:
        fd = os.open(dirpath or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_text(path: str, text: str, *, fsync: bool = True) -> None:
    """Atomically replace `path` with `text` (tmp + fsync + rename +
    dir-fsync). A reader never observes a torn or partial file at
    `path`; a crash at any instant leaves either the old version or the
    new one."""
    _chaos_tick(path, text)
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        f.write(text)
        if fsync:
            f.flush()
            os.fsync(f.fileno())
    os.replace(tmp, path)
    if fsync:
        fsync_dir(os.path.dirname(os.path.abspath(path)))


def append_text(path: str, text: str, *, fsync: bool = True) -> None:
    """Append `text` to `path` (create if absent), fsync'd by default.

    Appends are NOT atomic — a crash mid-append leaves a torn tail in
    the real file, and that is a documented property, not a bug: the
    jsonl feeds written this way (event logs, span dumps) pair with
    readers that skip unparseable lines and an fsck verdict
    (`torn-tail`) that reports without quarantining. To keep one torn
    record from corrupting its successor, an append onto a file whose
    last byte is not a newline first heals the boundary with ``"\\n"``
    so the damage stays confined to its own line.

    Concurrent appenders (N workers sharing one queue/event log) rely
    on one more property: the heal byte and the record go down in a
    SINGLE ``os.write`` on an ``O_APPEND`` descriptor, so two processes
    appending at once interleave whole records, never bytes of one
    record inside another.
    """
    _chaos_tick_append(path, text)
    heal = False
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            if f.tell() > 0:
                f.seek(-1, os.SEEK_END)
                heal = f.read(1) != b"\n"
    except FileNotFoundError:
        pass
    data = (("\n" if heal else "") + text).encode()
    fd = os.open(path, os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o666)
    try:
        os.write(fd, data)
        if fsync:
            os.fsync(fd)
    finally:
        os.close(fd)


def create_exclusive(path: str, text: str, *, fsync: bool = True) -> bool:
    """Create `path` with `text` iff it does not already exist
    (``O_CREAT|O_EXCL``) — the kernel arbitrates, so exactly one of N
    racing processes wins. Returns True for the winner, False when the
    file already existed (the loser backs off; nothing is written).
    This is the fleet claim-file primitive: claim creates go through
    the chaos write counter like every other durable write, so a plan
    matched on ``.claim`` can kill a contender at its k-th claim."""
    _chaos_tick(path, text)
    try:
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o666)
    except FileExistsError:
        return False
    try:
        os.write(fd, text.encode())
        if fsync:
            os.fsync(fd)
    finally:
        os.close(fd)
    if fsync:
        fsync_dir(os.path.dirname(os.path.abspath(path)))
    return True


def atomic_write_json(path: str, doc, *, indent: int = 1,
                      sort_keys: bool = True, fsync: bool = True) -> None:
    text = json.dumps(doc, indent=indent, sort_keys=sort_keys) + "\n"
    atomic_write_text(path, text, fsync=fsync)
