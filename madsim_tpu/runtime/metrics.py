"""Runtime metrics (reference: madsim/src/sim/runtime/metrics.rs).

Also the host-side decoder for the TPU engine's flight-recorder metrics
vector (`StreamCarry.fr_metrics` / `LaneState.fr`): the device
accumulates per-fault-kind injection counters and occupancy high-water
marks in the step kernel; `fr_metrics_dict` turns the harvested int
vector into the labelled dict that run_stream stats, bench.py and the
hunt report print.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Dict, Sequence

if TYPE_CHECKING:
    from ..task.executor import Executor

# The same table engine/core.py's FAULT_KIND_NAMES / FR_EXTRA_NAMES
# bind — via madsim_tpu/kinds.py (pure literals, no jax import), so
# this host-side decoder can never drift from the device counters.
from ..kinds import FAULT_KIND_NAMES as FR_FAULT_KINDS
from ..kinds import FR_EXTRA_NAMES as FR_EXTRAS

# Causal-provenance word layout (mirrors engine/core.py PROV_*): bits
# [0, 30) = scheduled fault slots, bit 30 = crash-with-amnesia wipe,
# bit 31 = duplicate delivery. Kept as literals so host-side consumers
# (the `/stats` service, dashboards) can decode words without jax.
PROV_FAULT_BITS = 30
PROV_BIT_AMNESIA = 30
PROV_BIT_DUP = 31


def prov_word_bits(word: int) -> Dict[str, object]:
    """Split a violation provenance word into its raw channels:
    implicated scheduled-fault slot indices plus the two non-scheduled
    chaos flags. Kind names need the seed's fault schedule —
    engine/provenance.py decodes those; this is the schedule-free
    half."""
    w = int(word) & 0xFFFFFFFF
    return {
        "fault_slots": [i for i in range(PROV_FAULT_BITS) if (w >> i) & 1],
        "amnesia": bool((w >> PROV_BIT_AMNESIA) & 1),
        "dup": bool((w >> PROV_BIT_DUP) & 1),
    }


def fr_metrics_dict(vec: Sequence[int]) -> Dict[str, object]:
    """Decode a flight-recorder metrics vector: per-kind fault injection
    totals, the non-scheduled chaos counters (message duplicates pushed,
    crash-with-amnesia restarts applied), then queue / clogged-link /
    killed-node high-water marks."""
    v = [int(x) for x in vec]
    nk, ne = len(FR_FAULT_KINDS), len(FR_EXTRAS)
    if len(v) != nk + ne + 3:
        raise ValueError(f"expected {nk + ne + 3} metric words, got {len(v)}")
    return {
        "faults_injected": dict(zip(FR_FAULT_KINDS, v[:nk])),
        "dup_injected": v[nk],
        "amnesia_restarts": v[nk + 1],
        "queue_hwm": v[nk + ne],
        "clog_links_hwm": v[nk + ne + 1],
        "killed_hwm": v[nk + ne + 2],
    }


class RuntimeMetrics:
    """Live task census (reference: metrics.rs:6-40)."""

    def __init__(self, executor: "Executor"):
        self._executor = executor

    def num_nodes(self) -> int:
        return len(self._executor.nodes)

    def num_tasks(self) -> int:
        return sum(len(n.tasks) for n in self._executor.nodes.values())

    def num_tasks_by_node(self) -> Dict[str, int]:
        return {
            n.name: len(n.tasks)
            for n in self._executor.nodes.values()
            if n.tasks
        }

    def num_tasks_by_node_by_spawn(self) -> Dict[str, Dict[str, int]]:
        out: Dict[str, Dict[str, int]] = {}
        for n in self._executor.nodes.values():
            if not n.tasks:
                continue
            per: Dict[str, int] = {}
            for t in n.tasks:
                loc = t.location
                if isinstance(loc, tuple):  # (filename, lineno) spawn key
                    loc = f"{loc[0]}:{loc[1]}"
                per[loc] = per.get(loc, 0) + 1
            out[n.name] = per
        return out
