"""Runtime metrics (reference: madsim/src/sim/runtime/metrics.rs)."""

from __future__ import annotations

from typing import TYPE_CHECKING, Dict

if TYPE_CHECKING:
    from ..task.executor import Executor


class RuntimeMetrics:
    """Live task census (reference: metrics.rs:6-40)."""

    def __init__(self, executor: "Executor"):
        self._executor = executor

    def num_nodes(self) -> int:
        return len(self._executor.nodes)

    def num_tasks(self) -> int:
        return sum(len(n.tasks) for n in self._executor.nodes.values())

    def num_tasks_by_node(self) -> Dict[str, int]:
        return {
            n.name: len(n.tasks)
            for n in self._executor.nodes.values()
            if n.tasks
        }

    def num_tasks_by_node_by_spawn(self) -> Dict[str, Dict[str, int]]:
        out: Dict[str, Dict[str, int]] = {}
        for n in self._executor.nodes.values():
            if not n.tasks:
                continue
            per: Dict[str, int] = {}
            for t in n.tasks:
                loc = t.location
                if isinstance(loc, tuple):  # (filename, lineno) spawn key
                    loc = f"{loc[0]}:{loc[1]}"
                per[loc] = per.get(loc, 0) + 1
            out[n.name] = per
        return out
