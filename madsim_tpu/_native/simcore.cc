// simcore — native hot-path core for the host engine.
//
// The reference's runtime is native Rust end-to-end; the Python host
// engine keeps its hot inner loops native via this small C++ core:
//   * bulk Philox4x32-10 block generation (same constants/recurrence as
//     madsim_tpu/rand/philox.py — bit-identical output, asserted in
//     tests/test_native.py)
//   * the timer event-queue as a binary heap ordered by (deadline, seq),
//     exactly the ordering of the Python heapq path
// Built with g++ at first import (see __init__.py); the framework falls
// back to pure Python when no toolchain is available, with identical
// semantics either way.

#include <cstdint>
#include <cstring>
#include <vector>
#include <algorithm>

namespace {

constexpr uint32_t kPhiloxM0 = 0xD2511F53u;
constexpr uint32_t kPhiloxM1 = 0xCD9E8D57u;
constexpr uint32_t kPhiloxW0 = 0x9E3779B9u;
constexpr uint32_t kPhiloxW1 = 0xBB67AE85u;

inline void philox_block(uint32_t k0, uint32_t k1, uint32_t c0, uint32_t c1,
                         uint32_t c2, uint32_t c3, uint32_t* out) {
  for (int round = 0; round < 10; ++round) {
    uint64_t p0 = static_cast<uint64_t>(kPhiloxM0) * c0;
    uint64_t p1 = static_cast<uint64_t>(kPhiloxM1) * c2;
    uint32_t hi0 = static_cast<uint32_t>(p0 >> 32);
    uint32_t lo0 = static_cast<uint32_t>(p0);
    uint32_t hi1 = static_cast<uint32_t>(p1 >> 32);
    uint32_t lo1 = static_cast<uint32_t>(p1);
    uint32_t n0 = hi1 ^ c1 ^ k0;
    uint32_t n1 = lo1;
    uint32_t n2 = hi0 ^ c3 ^ k1;
    uint32_t n3 = lo0;
    c0 = n0; c1 = n1; c2 = n2; c3 = n3;
    k0 += kPhiloxW0;
    k1 += kPhiloxW1;
  }
  out[0] = c0; out[1] = c1; out[2] = c2; out[3] = c3;
}

struct TimerEntry {
  int64_t deadline;
  uint64_t seq;  // unique insertion number: FIFO tie-break AND callback key
};

struct TimerCmp {
  // std::push_heap is a max-heap; invert for earliest-(deadline, seq) first.
  bool operator()(const TimerEntry& a, const TimerEntry& b) const {
    if (a.deadline != b.deadline) return a.deadline > b.deadline;
    return a.seq > b.seq;
  }
};

struct TimerHeap {
  std::vector<TimerEntry> entries;
};

}  // namespace

extern "C" {

// Fill out[0 .. 4*nblocks) with philox blocks start_block .. start_block+nblocks.
// Counter layout matches rand/philox.py: (block & 0xffffffff, block >> 32, 0, 0).
void philox_fill(uint32_t k0, uint32_t k1, uint64_t start_block,
                 uint64_t nblocks, uint32_t* out) {
  for (uint64_t i = 0; i < nblocks; ++i) {
    uint64_t block = start_block + i;
    philox_block(k0, k1, static_cast<uint32_t>(block),
                 static_cast<uint32_t>(block >> 32), 0u, 0u, out + 4 * i);
  }
}

void* timer_new() { return new TimerHeap(); }

void timer_free(void* h) { delete static_cast<TimerHeap*>(h); }

void timer_push(void* h, int64_t deadline, uint64_t seq) {
  auto* heap = static_cast<TimerHeap*>(h);
  heap->entries.push_back(TimerEntry{deadline, seq});
  std::push_heap(heap->entries.begin(), heap->entries.end(), TimerCmp{});
}

// Pop the earliest timer; returns 0 when empty.
int timer_pop(void* h, int64_t* deadline, uint64_t* seq) {
  auto* heap = static_cast<TimerHeap*>(h);
  if (heap->entries.empty()) return 0;
  std::pop_heap(heap->entries.begin(), heap->entries.end(), TimerCmp{});
  TimerEntry e = heap->entries.back();
  heap->entries.pop_back();
  *deadline = e.deadline;
  *seq = e.seq;
  return 1;
}

// Peek the earliest deadline; returns 0 when empty.
int timer_peek(void* h, int64_t* deadline) {
  auto* heap = static_cast<TimerHeap*>(h);
  if (heap->entries.empty()) return 0;
  *deadline = heap->entries.front().deadline;
  return 1;
}

uint64_t timer_len(void* h) {
  return static_cast<TimerHeap*>(h)->entries.size();
}

}  // extern "C"
