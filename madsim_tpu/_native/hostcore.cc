// hostcore — native hot-path core for the host engine (CPython extension).
//
// The reference's runtime is native Rust end-to-end (madsim/src/sim/task/
// mod.rs:220-323 is a compiled poll loop over compiled futures). Python
// coroutines can't be compiled away, but everything AROUND them can; this
// extension keeps the host engine's inner loops native:
//
//   * Rng            — buffered Philox4x32-10 draws (bit-identical to
//                      madsim_tpu/rand/philox.py, asserted in tests)
//   * TimeCore       — the virtual clock + (deadline, seq)-ordered timer
//                      heap with PyObject callbacks (sim/time/mod.rs:45-59)
//   * run_all_ready  — the executor's drain-in-random-order poll loop
//                      (sim/task/mod.rs:263-323 + utils/mpsc.rs:73-83),
//                      including the 50-100 ns advance per poll
//
// Draw-sequence parity with the pure-Python executor loop is load-bearing:
// the Python fallback (MADSIM_TPU_NO_NATIVE=1) and this loop consume RNG
// draws in EXACTLY the same pattern (a pick draw only when >1 task is
// ready; an advance draw after every effective poll), so a seed replays
// bit-identically whichever loop ran it. The determinism log/check mode
// routes through the Python loop (it must observe every draw).

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// Philox4x32-10 (same constants/recurrence as rand/philox.py)
// ---------------------------------------------------------------------------

constexpr uint32_t kPhiloxM0 = 0xD2511F53u;
constexpr uint32_t kPhiloxM1 = 0xCD9E8D57u;
constexpr uint32_t kPhiloxW0 = 0x9E3779B9u;
constexpr uint32_t kPhiloxW1 = 0xBB67AE85u;

inline void philox_block(uint32_t k0, uint32_t k1, uint32_t c0, uint32_t c1,
                         uint32_t c2, uint32_t c3, uint32_t* out) {
  for (int round = 0; round < 10; ++round) {
    uint64_t p0 = static_cast<uint64_t>(kPhiloxM0) * c0;
    uint64_t p1 = static_cast<uint64_t>(kPhiloxM1) * c2;
    uint32_t hi0 = static_cast<uint32_t>(p0 >> 32);
    uint32_t lo0 = static_cast<uint32_t>(p0);
    uint32_t hi1 = static_cast<uint32_t>(p1 >> 32);
    uint32_t lo1 = static_cast<uint32_t>(p1);
    uint32_t n0 = hi1 ^ c1 ^ k0;
    uint32_t n1 = lo1;
    uint32_t n2 = hi0 ^ c3 ^ k1;
    uint32_t n3 = lo0;
    c0 = n0; c1 = n1; c2 = n2; c3 = n3;
    k0 += kPhiloxW0;
    k1 += kPhiloxW1;
  }
  out[0] = c0; out[1] = c1; out[2] = c2; out[3] = c3;
}

// splitmix64 — same constants as rand/philox.py:53-62; used for the
// native draw-log hashing (reference: sim/rand.rs:65-90).
inline uint64_t splitmix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ull;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ull;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBull;
  return x ^ (x >> 31);
}

// ---------------------------------------------------------------------------
// Rng — buffered philox word stream; word k == block(k/4)[k%4], identical
// to GlobalRng's consumption order (rand/__init__.py:65-93)
// ---------------------------------------------------------------------------

constexpr int kBufBlocks = 64;
constexpr int kBufWords = kBufBlocks * 4;

struct TimeCoreObject;  // fwd (observation reads the virtual clock)

// Draw observation (VERDICT r2/r3: native-loop check mode). The native
// loop's internal draws (random pick, 50-100 ns advance) never surface
// in Python, so MADSIM_TEST_CHECK_DETERMINISM used to force the pure-
// Python loop — validating a loop users didn't run. With observation
// active, EVERY rng_u32 — from the C drive loop or from Python
// next_u32 — is hashed with the virtual time exactly like
// GlobalRng._record (splitmix64((idx << 32) ^ value ^ now_ns)), into a
// native log (mode 1) or against an expected log (mode 2). The hash
// stream is bit-identical to the Python loop's, so logs compare across
// loops.
enum ObserveMode { OBS_OFF = 0, OBS_LOG = 1, OBS_CHECK = 2 };

struct RngObject {
  PyObject_HEAD
  uint32_t k0, k1;
  uint64_t counter;  // next philox block index
  int pos;           // next word in buf; kBufWords == empty
  int observe_mode;
  uint64_t draw_index;
  std::vector<uint64_t>* obs;  // log being built, or the expected log
  size_t check_pos;
  int64_t mismatch_index;  // first divergent draw (-1 = none)
  int64_t mismatch_time;
  TimeCoreObject* time_src;  // strong ref; nullable
  uint32_t buf[kBufWords];
};

inline int64_t obs_now_ns(RngObject* r);  // defined after TimeCoreObject

inline void rng_observe(RngObject* r, uint32_t v) {
  int64_t t = obs_now_ns(r);
  uint64_t h = splitmix64((r->draw_index << 32) ^ static_cast<uint64_t>(v) ^
                          static_cast<uint64_t>(t));
  r->draw_index++;
  if (r->observe_mode == OBS_LOG) {
    r->obs->push_back(h);
  } else if (r->mismatch_index < 0) {
    if (r->check_pos >= r->obs->size() || (*r->obs)[r->check_pos] != h) {
      r->mismatch_index = static_cast<int64_t>(r->draw_index - 1);
      r->mismatch_time = t;
    } else {
      r->check_pos++;
    }
  }
}

inline uint32_t rng_u32(RngObject* r) {
  if (r->pos >= kBufWords) {
    for (int i = 0; i < kBufBlocks; ++i) {
      uint64_t block = r->counter + i;
      philox_block(r->k0, r->k1, static_cast<uint32_t>(block),
                   static_cast<uint32_t>(block >> 32), 0u, 0u, r->buf + 4 * i);
    }
    r->counter += kBufBlocks;
    r->pos = 0;
  }
  uint32_t v = r->buf[r->pos++];
  if (r->observe_mode != OBS_OFF) rng_observe(r, v);
  return v;
}

inline uint64_t rng_u64(RngObject* r) {
  uint64_t lo = rng_u32(r);
  uint64_t hi = rng_u32(r);
  return (hi << 32) | lo;
}

// gen_range semantics of rand/__init__.py:152-161: low + next_u64 % span.
inline int64_t rng_range(RngObject* r, int64_t low, int64_t high) {
  uint64_t span = static_cast<uint64_t>(high - low);
  return low + static_cast<int64_t>(rng_u64(r) % span);
}

static PyObject* Rng_new(PyTypeObject* type, PyObject* args, PyObject* kwds) {
  unsigned long k0 = 0, k1 = 0;
  unsigned long long counter = 0;
  static const char* kwlist[] = {"k0", "k1", "counter", nullptr};
  if (!PyArg_ParseTupleAndKeywords(args, kwds, "kk|K",
                                   const_cast<char**>(kwlist), &k0, &k1,
                                   &counter)) {
    return nullptr;
  }
  RngObject* self = reinterpret_cast<RngObject*>(type->tp_alloc(type, 0));
  if (!self) return nullptr;
  self->k0 = static_cast<uint32_t>(k0);
  self->k1 = static_cast<uint32_t>(k1);
  self->counter = counter;
  self->pos = kBufWords;
  self->observe_mode = OBS_OFF;
  self->draw_index = 0;
  self->obs = nullptr;
  self->check_pos = 0;
  self->mismatch_index = -1;
  self->mismatch_time = 0;
  self->time_src = nullptr;
  return reinterpret_cast<PyObject*>(self);
}

static void Rng_dealloc(PyObject* self) {
  RngObject* r = reinterpret_cast<RngObject*>(self);
  PyObject_GC_UnTrack(self);
  delete r->obs;
  r->obs = nullptr;
  Py_XDECREF(reinterpret_cast<PyObject*>(r->time_src));
  r->time_src = nullptr;
  Py_TYPE(self)->tp_free(self);
}

// GC support is load-bearing: bind_time gives the Rng a STRONG ref to
// the TimeCore, closing a cycle through the whole runtime graph
// (executor -> rng -> time_src -> TimeCore -> timer wakers -> tasks ->
// executor). Without traverse/clear here that cycle is uncollectable,
// and every simulation that ends with a task parked on a timer leaks
// its entire runtime graph (~60 KB/seed, found round 5).
static int Rng_traverse(PyObject* self, visitproc visit, void* arg) {
  Py_VISIT(reinterpret_cast<PyObject*>(
      reinterpret_cast<RngObject*>(self)->time_src));
  return 0;
}

static int Rng_clear_gc(PyObject* self) {
  RngObject* r = reinterpret_cast<RngObject*>(self);
  PyObject* t = reinterpret_cast<PyObject*>(r->time_src);
  r->time_src = nullptr;
  Py_XDECREF(t);
  return 0;
}

static PyObject* Rng_next_u32(PyObject* self, PyObject*) {
  return PyLong_FromUnsignedLong(rng_u32(reinterpret_cast<RngObject*>(self)));
}

static PyObject* Rng_next_u64(PyObject* self, PyObject*) {
  return PyLong_FromUnsignedLongLong(rng_u64(reinterpret_cast<RngObject*>(self)));
}

static PyObject* Rng_gen_range(PyObject* self, PyObject* args) {
  long long low, high;
  if (!PyArg_ParseTuple(args, "LL", &low, &high)) return nullptr;
  if (high <= low) {
    PyErr_Format(PyExc_ValueError, "empty range [%lld, %lld)", low, high);
    return nullptr;
  }
  return PyLong_FromLongLong(
      rng_range(reinterpret_cast<RngObject*>(self), low, high));
}

static PyObject* Rng_random(PyObject* self, PyObject*) {
  uint64_t v = rng_u64(reinterpret_cast<RngObject*>(self));
  return PyFloat_FromDouble(static_cast<double>(v >> 11) *
                            (1.0 / 9007199254740992.0));  // 2^-53
}

static PyObject* Rng_getstate(PyObject* self, PyObject*) {
  RngObject* r = reinterpret_cast<RngObject*>(self);
  // (block_counter, words_consumed_in_buffer) — enough to assert parity
  int consumed = r->pos >= kBufWords ? 0 : r->pos;
  uint64_t base = r->pos >= kBufWords ? r->counter : r->counter - kBufBlocks;
  return Py_BuildValue("KK", base * 4 + static_cast<uint64_t>(consumed),
                       r->counter);
}

// observation methods (defined after TimeCoreObject, which bind_time needs)
static PyObject* Rng_bind_time(PyObject* self, PyObject* arg);
static PyObject* Rng_observe_log(PyObject* self, PyObject*);
static PyObject* Rng_observe_check(PyObject* self, PyObject* arg);
static PyObject* Rng_observe_off(PyObject* self, PyObject*);
static PyObject* Rng_take_obs(PyObject* self, PyObject*);
static PyObject* Rng_obs_status(PyObject* self, PyObject*);

static PyMethodDef Rng_methods[] = {
    {"next_u32", Rng_next_u32, METH_NOARGS, "next uint32 draw"},
    {"next_u64", Rng_next_u64, METH_NOARGS, "next uint64 draw (lo then hi)"},
    {"gen_range", Rng_gen_range, METH_VARARGS, "uniform int in [low, high)"},
    {"random", Rng_random, METH_NOARGS, "uniform float64 in [0,1), 53 bits"},
    {"words_drawn", Rng_getstate, METH_NOARGS,
     "(total words drawn, block counter) — for parity tests"},
    {"bind_time", Rng_bind_time, METH_O,
     "bind the TimeCore whose clock draw hashes fold in (None unbinds)"},
    {"observe_log", Rng_observe_log, METH_NOARGS,
     "start logging every draw's hash (native check mode)"},
    {"observe_check", Rng_observe_check, METH_O,
     "check every draw against a previously taken log"},
    {"observe_off", Rng_observe_off, METH_NOARGS, "stop observing"},
    {"take_obs", Rng_take_obs, METH_NOARGS,
     "finish logging; returns the list of draw hashes"},
    {"obs_status", Rng_obs_status, METH_NOARGS,
     "(mode, draws, check_pos, expected, mismatch_index, mismatch_time)"},
    {nullptr, nullptr, 0, nullptr},
};

static PyTypeObject RngType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
    "hostcore.Rng",            /* tp_name */
    sizeof(RngObject),         /* tp_basicsize */
};

// ---------------------------------------------------------------------------
// Interned attribute names (created at module init)
// ---------------------------------------------------------------------------

static PyObject* s_time_limit_hit;
static PyObject* s_ready;
static PyObject* s_scheduled;
static PyObject* s_finished;
static PyObject* s_kill_requested;
static PyObject* s_node;
static PyObject* s_coro;
static PyObject* s_cell;
static PyObject* s_killed;
static PyObject* s_paused;
static PyObject* s_paused_tasks;
static PyObject* s_tasks;
static PyObject* s_discard;
static PyObject* s_set;
static PyObject* s_close_priv;
static PyObject* s_current_task;
static PyObject* s_running_task;
static PyObject* s_panic;
static PyObject* s_handle_panic;

// True/False attr check with error propagation; -1 on error.
static int attr_truth(PyObject* obj, PyObject* name) {
  PyObject* v = PyObject_GetAttr(obj, name);
  if (!v) return -1;
  int t = PyObject_IsTrue(v);
  Py_DECREF(v);
  return t;
}

// ---------------------------------------------------------------------------
// TimeCore — virtual clock + (deadline, seq) min-heap of callbacks
// ---------------------------------------------------------------------------

struct TimerEnt {
  int64_t deadline;
  uint64_t seq;
  PyObject* cb;
};

struct TimerCmp {
  // std::*_heap are max-heaps; invert for earliest (deadline, seq) first.
  bool operator()(const TimerEnt& a, const TimerEnt& b) const {
    if (a.deadline != b.deadline) return a.deadline > b.deadline;
    return a.seq > b.seq;
  }
};

struct TimeCoreObject {
  PyObject_HEAD
  int64_t now_ns;
  uint64_t seq;
  std::vector<TimerEnt>* heap;
};

inline int64_t obs_now_ns(RngObject* r) {
  return r->time_src ? r->time_src->now_ns : 0;
}

// -- Rng observation methods (need TimeCoreObject above) --------------------

static PyObject* Rng_bind_time(PyObject* self, PyObject* arg) {
  RngObject* r = reinterpret_cast<RngObject*>(self);
  // tp_name check (TimeCoreType's definition is below this point in the
  // file, so PyObject_TypeCheck can't be used here): an arbitrary
  // object would be reinterpreted as TimeCoreObject and read garbage
  if (arg != Py_None &&
      strcmp(Py_TYPE(arg)->tp_name, "hostcore.TimeCore") != 0) {
    PyErr_Format(PyExc_TypeError, "bind_time expects a TimeCore or None, got %s",
                 Py_TYPE(arg)->tp_name);
    return nullptr;
  }
  Py_XDECREF(reinterpret_cast<PyObject*>(r->time_src));
  r->time_src = nullptr;
  if (arg != Py_None) {
    Py_INCREF(arg);
    r->time_src = reinterpret_cast<TimeCoreObject*>(arg);
  }
  Py_RETURN_NONE;
}

static PyObject* Rng_observe_log(PyObject* self, PyObject*) {
  RngObject* r = reinterpret_cast<RngObject*>(self);
  delete r->obs;
  r->obs = new std::vector<uint64_t>();
  r->observe_mode = OBS_LOG;
  r->draw_index = 0;
  r->mismatch_index = -1;
  Py_RETURN_NONE;
}

static PyObject* Rng_observe_check(PyObject* self, PyObject* arg) {
  RngObject* r = reinterpret_cast<RngObject*>(self);
  PyObject* seq = PySequence_Fast(arg, "observe_check expects a sequence");
  if (!seq) return nullptr;
  delete r->obs;
  r->obs = new std::vector<uint64_t>();
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  r->obs->reserve(static_cast<size_t>(n));
  for (Py_ssize_t i = 0; i < n; ++i) {
    uint64_t v = PyLong_AsUnsignedLongLong(PySequence_Fast_GET_ITEM(seq, i));
    if (v == static_cast<uint64_t>(-1) && PyErr_Occurred()) {
      Py_DECREF(seq);
      return nullptr;
    }
    r->obs->push_back(v);
  }
  Py_DECREF(seq);
  r->observe_mode = OBS_CHECK;
  r->draw_index = 0;
  r->check_pos = 0;
  r->mismatch_index = -1;
  Py_RETURN_NONE;
}

static PyObject* Rng_observe_off(PyObject* self, PyObject*) {
  RngObject* r = reinterpret_cast<RngObject*>(self);
  r->observe_mode = OBS_OFF;
  delete r->obs;
  r->obs = nullptr;
  Py_RETURN_NONE;
}

static PyObject* Rng_take_obs(PyObject* self, PyObject*) {
  RngObject* r = reinterpret_cast<RngObject*>(self);
  if (r->observe_mode != OBS_LOG || !r->obs) {
    PyErr_SetString(PyExc_RuntimeError, "take_obs without observe_log");
    return nullptr;
  }
  PyObject* out = PyList_New(static_cast<Py_ssize_t>(r->obs->size()));
  if (!out) return nullptr;
  for (size_t i = 0; i < r->obs->size(); ++i) {
    PyObject* v = PyLong_FromUnsignedLongLong((*r->obs)[i]);
    if (!v) { Py_DECREF(out); return nullptr; }
    PyList_SET_ITEM(out, static_cast<Py_ssize_t>(i), v);
  }
  r->observe_mode = OBS_OFF;
  delete r->obs;
  r->obs = nullptr;
  return out;
}

static PyObject* Rng_obs_status(PyObject* self, PyObject*) {
  RngObject* r = reinterpret_cast<RngObject*>(self);
  return Py_BuildValue(
      "iKnnLL", r->observe_mode, r->draw_index,
      static_cast<Py_ssize_t>(r->check_pos),
      static_cast<Py_ssize_t>(r->obs ? r->obs->size() : 0),
      static_cast<long long>(r->mismatch_index),
      static_cast<long long>(r->mismatch_time));
}

static PyObject* TimeCore_new(PyTypeObject* type, PyObject*, PyObject*) {
  TimeCoreObject* self =
      reinterpret_cast<TimeCoreObject*>(type->tp_alloc(type, 0));
  if (!self) return nullptr;
  self->now_ns = 0;
  self->seq = 0;
  self->heap = new std::vector<TimerEnt>();
  return reinterpret_cast<PyObject*>(self);
}

static void TimeCore_dealloc(PyObject* self) {
  TimeCoreObject* t = reinterpret_cast<TimeCoreObject*>(self);
  PyObject_GC_UnTrack(self);
  if (t->heap) {
    for (TimerEnt& e : *t->heap) Py_XDECREF(e.cb);
    delete t->heap;
    t->heap = nullptr;
  }
  Py_TYPE(self)->tp_free(self);
}

// GC support: pending callbacks (wakers, closures over the executor) can
// form cycles back through the runtime graph — gc must traverse them.
static int TimeCore_traverse(PyObject* self, visitproc visit, void* arg) {
  TimeCoreObject* t = reinterpret_cast<TimeCoreObject*>(self);
  if (t->heap) {
    for (TimerEnt& e : *t->heap) Py_VISIT(e.cb);
  }
  return 0;
}

static int TimeCore_clear_gc(PyObject* self) {
  TimeCoreObject* t = reinterpret_cast<TimeCoreObject*>(self);
  if (t->heap) {
    for (TimerEnt& e : *t->heap) Py_CLEAR(e.cb);
    t->heap->clear();
  }
  return 0;
}

static PyObject* TimeCore_now_ns(PyObject* self, PyObject*) {
  return PyLong_FromLongLong(
      reinterpret_cast<TimeCoreObject*>(self)->now_ns);
}

static PyObject* TimeCore_advance_ns(PyObject* self, PyObject* arg) {
  long long d = PyLong_AsLongLong(arg);
  if (d == -1 && PyErr_Occurred()) return nullptr;
  reinterpret_cast<TimeCoreObject*>(self)->now_ns += d;
  Py_RETURN_NONE;
}

static PyObject* TimeCore_push(PyObject* self, PyObject* args) {
  long long deadline;
  PyObject* cb;
  if (!PyArg_ParseTuple(args, "LO", &deadline, &cb)) return nullptr;
  TimeCoreObject* t = reinterpret_cast<TimeCoreObject*>(self);
  Py_INCREF(cb);
  t->heap->push_back(TimerEnt{deadline, ++t->seq, cb});
  std::push_heap(t->heap->begin(), t->heap->end(), TimerCmp{});
  Py_RETURN_NONE;
}

static PyObject* TimeCore_peek(PyObject* self, PyObject*) {
  TimeCoreObject* t = reinterpret_cast<TimeCoreObject*>(self);
  if (t->heap->empty()) Py_RETURN_NONE;
  return PyLong_FromLongLong(t->heap->front().deadline);
}

// ---------------------------------------------------------------------------
// TaskWaker — the per-task wake callable (reference: async-task's Waker).
// Semantics identical to the Python closure in TaskEntry.__init__:
//   if task.finished or task.scheduled: return
//   task.scheduled = True; executor.ready.append(task)
// Participates in GC (task <-> waker is a reference cycle).
// ---------------------------------------------------------------------------

struct TaskWakerObject {
  PyObject_HEAD
  PyObject* task;
  PyObject* ready;  // the executor's ready list
};

static PyTypeObject TaskWakerType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
    "hostcore.TaskWaker",      /* tp_name */
    sizeof(TaskWakerObject),   /* tp_basicsize */
};

static int taskwaker_fire(TaskWakerObject* w) {
  int finished = attr_truth(w->task, s_finished);
  if (finished < 0) return -1;
  if (finished) return 0;
  int scheduled = attr_truth(w->task, s_scheduled);
  if (scheduled < 0) return -1;
  if (scheduled) return 0;
  if (PyObject_SetAttr(w->task, s_scheduled, Py_True) < 0) return -1;
  return PyList_Append(w->ready, w->task);
}

static PyObject* TaskWaker_new(PyTypeObject* type, PyObject* args, PyObject*) {
  PyObject *task, *ready;
  if (!PyArg_ParseTuple(args, "OO!", &task, &PyList_Type, &ready)) {
    return nullptr;
  }
  TaskWakerObject* self =
      reinterpret_cast<TaskWakerObject*>(type->tp_alloc(type, 0));
  if (!self) return nullptr;
  Py_INCREF(task);
  self->task = task;
  Py_INCREF(ready);
  self->ready = ready;
  return reinterpret_cast<PyObject*>(self);
}

static void TaskWaker_dealloc(PyObject* self) {
  TaskWakerObject* w = reinterpret_cast<TaskWakerObject*>(self);
  PyObject_GC_UnTrack(self);
  Py_XDECREF(w->task);
  Py_XDECREF(w->ready);
  Py_TYPE(self)->tp_free(self);
}

static int TaskWaker_traverse(PyObject* self, visitproc visit, void* arg) {
  TaskWakerObject* w = reinterpret_cast<TaskWakerObject*>(self);
  Py_VISIT(w->task);
  Py_VISIT(w->ready);
  return 0;
}

static int TaskWaker_clear(PyObject* self) {
  TaskWakerObject* w = reinterpret_cast<TaskWakerObject*>(self);
  Py_CLEAR(w->task);
  Py_CLEAR(w->ready);
  return 0;
}

static PyObject* TaskWaker_call(PyObject* self, PyObject*, PyObject*) {
  if (taskwaker_fire(reinterpret_cast<TaskWakerObject*>(self)) < 0) {
    return nullptr;
  }
  Py_RETURN_NONE;
}

// fwd decls: native datagram wire/delivery moments (NetCore section)
extern PyTypeObject PendingSendType;
extern PyTypeObject PendingDeliverType;
static int pending_send_fire(PyObject* ps_o);
static int pending_deliver_fire(PyObject* pd_o);

// Pop the earliest timer, jump the clock, fire the callback
// (reference: sim/time/mod.rs:45-59). 1 = fired, 0 = empty, -1 = error.
static int advance_next(TimeCoreObject* t) {
  if (t->heap->empty()) return 0;
  std::pop_heap(t->heap->begin(), t->heap->end(), TimerCmp{});
  TimerEnt e = t->heap->back();
  t->heap->pop_back();
  if (e.deadline > t->now_ns) t->now_ns = e.deadline;
  int rc = 1;
  if (Py_TYPE(e.cb) == &TaskWakerType) {
    // fast path: wake a task without a Python call
    if (taskwaker_fire(reinterpret_cast<TaskWakerObject*>(e.cb)) < 0) rc = -1;
  } else if (Py_TYPE(e.cb) == &PendingSendType) {
    if (pending_send_fire(e.cb) < 0) rc = -1;
  } else if (Py_TYPE(e.cb) == &PendingDeliverType) {
    if (pending_deliver_fire(e.cb) < 0) rc = -1;
  } else {
    PyObject* r = PyObject_CallNoArgs(e.cb);
    if (!r) rc = -1;
    Py_XDECREF(r);
  }
  Py_DECREF(e.cb);
  return rc;
}

static PyObject* TimeCore_advance_to_next_event(PyObject* self, PyObject*) {
  int rc = advance_next(reinterpret_cast<TimeCoreObject*>(self));
  if (rc < 0) return nullptr;
  return PyBool_FromLong(rc);
}

static Py_ssize_t TimeCore_len(PyObject* self) {
  return static_cast<Py_ssize_t>(
      reinterpret_cast<TimeCoreObject*>(self)->heap->size());
}

static PyMethodDef TimeCore_methods[] = {
    {"now_ns", TimeCore_now_ns, METH_NOARGS, "current virtual time (ns)"},
    {"advance_ns", TimeCore_advance_ns, METH_O, "jump the clock forward"},
    {"push", TimeCore_push, METH_VARARGS, "push(deadline_ns, callback)"},
    {"peek", TimeCore_peek, METH_NOARGS, "earliest deadline or None"},
    {"advance_to_next_event", TimeCore_advance_to_next_event, METH_NOARGS,
     "pop earliest timer, jump clock, fire callback; False when empty"},
    {nullptr, nullptr, 0, nullptr},
};

static PySequenceMethods TimeCore_as_sequence = {
    TimeCore_len, /* sq_length */
};

static PyTypeObject TimeCoreType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
    "hostcore.TimeCore",       /* tp_name */
    sizeof(TimeCoreObject),    /* tp_basicsize */
};

// ---------------------------------------------------------------------------
// AwaitIter — future._Await.__await__ as a native iterator.
//
// Python semantics being mirrored (future.py:56-77): loop { poll(waker);
// Ready -> return value; PENDING -> task.pending_on = p; yield; clear },
// with p.drop() on every exit path (return, GeneratorExit via close(),
// error). Fetches the current task from the executor loop's thread-local
// (or _context.current_task() under the pure-Python loop).
// ---------------------------------------------------------------------------

static thread_local PyObject* tl_current_task = nullptr;  // borrowed

static PyObject* s_waker;
static PyObject* s_pending_on;
static PyObject* s_poll;
static PyObject* s_value;
static PyObject* s_drop;

// Lazily-imported singletons from madsim_tpu (lazy: this module is built
// and loaded by madsim_tpu._native during package import).
static PyObject* g_pending = nullptr;       // future.PENDING
static PyObject* g_ready_none = nullptr;    // shared Ready(None)
static PyObject* g_current_task_fn = nullptr;  // _context.current_task

static PyObject* g_ready_cls = nullptr;     // future.Ready (for Ready(value))

static int ensure_future_imports() {
  if (g_pending) return 0;
  PyObject* fut = PyImport_ImportModule("madsim_tpu.future");
  if (!fut) return -1;
  g_pending = PyObject_GetAttrString(fut, "PENDING");
  PyObject* ready_cls = PyObject_GetAttrString(fut, "Ready");
  Py_DECREF(fut);
  if (!g_pending || !ready_cls) {
    Py_XDECREF(ready_cls);
    return -1;
  }
  g_ready_none = PyObject_CallOneArg(ready_cls, Py_None);
  if (!g_ready_none) {
    Py_DECREF(ready_cls);
    return -1;
  }
  g_ready_cls = ready_cls;  // keep: mailbox polls build Ready(msg)
  PyObject* ctxmod = PyImport_ImportModule("madsim_tpu._context");
  if (!ctxmod) return -1;
  g_current_task_fn = PyObject_GetAttrString(ctxmod, "current_task");
  Py_DECREF(ctxmod);
  return g_current_task_fn ? 0 : -1;
}

struct AwaitIterObject {
  PyObject_HEAD
  PyObject* pollable;
  PyObject* task;   // resolved on first __next__
  PyObject* waker;  // cached task.waker
  char yielded;     // pending_on is set; clear before the next poll
  char done;
};

static void awaititer_run_drop(AwaitIterObject* it) {
  // best-effort drop() preserving any in-flight exception
  PyObject *t, *v, *tb;
  PyErr_Fetch(&t, &v, &tb);
  PyObject* r = PyObject_CallMethodNoArgs(it->pollable, s_drop);
  if (!r) PyErr_WriteUnraisable(it->pollable);
  Py_XDECREF(r);
  PyErr_Restore(t, v, tb);
}

static PyObject* AwaitIter_next(PyObject* self) {
  AwaitIterObject* it = reinterpret_cast<AwaitIterObject*>(self);
  if (it->done) {
    PyErr_SetNone(PyExc_StopIteration);
    return nullptr;
  }
  if (!it->task) {
    if (tl_current_task) {
      it->task = tl_current_task;
      Py_INCREF(it->task);
    } else {
      if (ensure_future_imports() < 0) return nullptr;
      it->task = PyObject_CallNoArgs(g_current_task_fn);
      if (!it->task) return nullptr;
    }
    it->waker = PyObject_GetAttr(it->task, s_waker);
    if (!it->waker) return nullptr;
  }
  if (it->yielded) {
    it->yielded = 0;
    if (PyObject_SetAttr(it->task, s_pending_on, Py_None) < 0) return nullptr;
  }
  PyObject* r = PyObject_CallMethodOneArg(it->pollable, s_poll, it->waker);
  if (!r) {
    it->done = 1;
    awaititer_run_drop(it);
    return nullptr;
  }
  if (r == g_pending) {
    Py_DECREF(r);
    if (PyObject_SetAttr(it->task, s_pending_on, it->pollable) < 0) {
      return nullptr;
    }
    it->yielded = 1;
    Py_RETURN_NONE;  // yield (suspend the awaiting coroutine)
  }
  PyObject* value = PyObject_GetAttr(r, s_value);
  Py_DECREF(r);
  if (!value) {
    it->done = 1;
    awaititer_run_drop(it);
    return nullptr;
  }
  it->done = 1;
  awaititer_run_drop(it);
  if (PyErr_Occurred()) {  // drop() must not mask, but self-errors count
    Py_DECREF(value);
    return nullptr;
  }
  // StopIteration(value): build the instance explicitly so tuple values
  // survive normalization (same trick as _PyGen_SetStopIterationValue)
  PyObject* exc = PyObject_CallOneArg(PyExc_StopIteration, value);
  Py_DECREF(value);
  if (!exc) return nullptr;
  PyErr_SetObject(PyExc_StopIteration, exc);
  Py_DECREF(exc);
  return nullptr;
}

// Clear task.pending_on if we suspended with it set (the Python
// version's `finally: task.pending_on = None`). Best-effort on teardown.
static void awaititer_clear_pending(AwaitIterObject* it) {
  if (it->yielded && it->task) {
    it->yielded = 0;
    if (PyObject_SetAttr(it->task, s_pending_on, Py_None) < 0) {
      PyErr_WriteUnraisable(it->task);
    }
  }
}

// close(): called by the coroutine machinery when GeneratorExit unwinds
// through the awaiting frame — the Python version's `finally` clauses.
static PyObject* AwaitIter_close(PyObject* self, PyObject*) {
  AwaitIterObject* it = reinterpret_cast<AwaitIterObject*>(self);
  awaititer_clear_pending(it);
  if (!it->done) {
    it->done = 1;
    PyObject* r = PyObject_CallMethodNoArgs(it->pollable, s_drop);
    if (!r) return nullptr;
    Py_DECREF(r);
  }
  Py_RETURN_NONE;
}

static PyObject* AwaitIter_new(PyTypeObject* type, PyObject* args, PyObject*) {
  PyObject* pollable;
  if (!PyArg_ParseTuple(args, "O", &pollable)) return nullptr;
  if (ensure_future_imports() < 0) return nullptr;
  AwaitIterObject* self =
      reinterpret_cast<AwaitIterObject*>(type->tp_alloc(type, 0));
  if (!self) return nullptr;
  Py_INCREF(pollable);
  self->pollable = pollable;
  self->task = nullptr;
  self->waker = nullptr;
  self->yielded = 0;
  self->done = 0;
  return reinterpret_cast<PyObject*>(self);
}

static void AwaitIter_dealloc(PyObject* self) {
  AwaitIterObject* it = reinterpret_cast<AwaitIterObject*>(self);
  PyObject_GC_UnTrack(self);
  awaititer_clear_pending(it);
  if (!it->done && it->pollable) {
    it->done = 1;
    awaititer_run_drop(it);
  }
  Py_XDECREF(it->pollable);
  Py_XDECREF(it->task);
  Py_XDECREF(it->waker);
  Py_TYPE(self)->tp_free(self);
}

static int AwaitIter_traverse(PyObject* self, visitproc visit, void* arg) {
  AwaitIterObject* it = reinterpret_cast<AwaitIterObject*>(self);
  Py_VISIT(it->pollable);
  Py_VISIT(it->task);
  Py_VISIT(it->waker);
  return 0;
}

static int AwaitIter_clear_gc(PyObject* self) {
  AwaitIterObject* it = reinterpret_cast<AwaitIterObject*>(self);
  Py_CLEAR(it->pollable);
  Py_CLEAR(it->task);
  Py_CLEAR(it->waker);
  return 0;
}

static PyMethodDef AwaitIter_methods[] = {
    {"close", AwaitIter_close, METH_NOARGS, "drop the pollable (GeneratorExit)"},
    {nullptr, nullptr, 0, nullptr},
};

static PyTypeObject AwaitIterType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
    "hostcore.AwaitIter",      /* tp_name */
    sizeof(AwaitIterObject),   /* tp_basicsize */
};

// ---------------------------------------------------------------------------
// Mailbox — native tag-matched mailbox + its recv pollable
// (semantics of net/endpoint.py Mailbox/_MailboxRecv, reference:
// endpoint.rs:298-352). One C object replaces the OneShotCell +
// _MailboxRecv + recv_cell stack on the RPC hot path: deliver matches
// the FIRST registered receiver for the tag (FIFO), unmatched messages
// buffer FIFO, recv(tag) scans the buffer then registers eagerly at
// CALL time (before the first poll — a message delivered between
// recv() and the await must not be missed), and drop() deregisters so
// an aborted receiver (timed-out RPC) cannot swallow a later message.
// ---------------------------------------------------------------------------

struct MailRecvObject;

struct MailboxObject {
  PyObject_HEAD
  // (tag, Message) buffered FIFO; strong refs
  std::vector<std::pair<uint64_t, PyObject*>>* msgs;
  // (tag, receiver) registered FIFO; strong refs
  std::vector<std::pair<uint64_t, MailRecvObject*>>* reg;
};

struct MailRecvObject {
  PyObject_HEAD
  MailboxObject* mb;  // strong
  uint64_t tag;
  PyObject* value;  // strong; nullptr = pending
  PyObject* waker;  // strong; last poll's waker
  char done;
  char registered;
};

static PyTypeObject MailboxType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
    "hostcore.Mailbox",        /* tp_name */
    sizeof(MailboxObject),     /* tp_basicsize */
};

static PyTypeObject MailRecvType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
    "hostcore.MailRecv",       /* tp_name */
    sizeof(MailRecvObject),    /* tp_basicsize */
};

static PyObject* Mailbox_new(PyTypeObject* type, PyObject*, PyObject*) {
  MailboxObject* self = reinterpret_cast<MailboxObject*>(type->tp_alloc(type, 0));
  if (!self) return nullptr;
  self->msgs = new std::vector<std::pair<uint64_t, PyObject*>>();
  self->reg = new std::vector<std::pair<uint64_t, MailRecvObject*>>();
  return reinterpret_cast<PyObject*>(self);
}

static int Mailbox_traverse(PyObject* self, visitproc visit, void* arg) {
  MailboxObject* m = reinterpret_cast<MailboxObject*>(self);
  if (m->msgs) {
    for (auto& p : *m->msgs) Py_VISIT(p.second);
  }
  if (m->reg) {
    for (auto& p : *m->reg) Py_VISIT(reinterpret_cast<PyObject*>(p.second));
  }
  return 0;
}

static int Mailbox_clear_gc(PyObject* self) {
  MailboxObject* m = reinterpret_cast<MailboxObject*>(self);
  if (m->msgs) {
    // swap out first: a msg dealloc re-entering this mailbox must see
    // an empty buffer, not a half-cleared vector
    std::vector<std::pair<uint64_t, PyObject*>> msgs;
    msgs.swap(*m->msgs);
    for (auto& p : msgs) Py_CLEAR(p.second);
  }
  if (m->reg) {
    // swap out BEFORE decref: dropping a receiver's last ref runs
    // MailRecv_dealloc -> mailrecv_deregister, which must not find the
    // entry still in m->reg (it would erase mid-iteration and decref a
    // mid-dealloc object)
    std::vector<std::pair<uint64_t, MailRecvObject*>> reg;
    reg.swap(*m->reg);
    for (auto& p : reg) {
      MailRecvObject* r = p.second;
      p.second = nullptr;
      if (r) {
        r->registered = 0;
        Py_DECREF(reinterpret_cast<PyObject*>(r));
      }
    }
  }
  return 0;
}

static void Mailbox_dealloc(PyObject* self) {
  MailboxObject* m = reinterpret_cast<MailboxObject*>(self);
  PyObject_GC_UnTrack(self);
  Mailbox_clear_gc(self);
  delete m->msgs;
  delete m->reg;
  m->msgs = nullptr;
  m->reg = nullptr;
  Py_TYPE(self)->tp_free(self);
}

static PyObject* s_tag;  // interned "tag" (init at module load)

static PyObject* Mailbox_deliver(PyObject* self, PyObject* msg) {
  MailboxObject* m = reinterpret_cast<MailboxObject*>(self);
  PyObject* tag_o = PyObject_GetAttr(msg, s_tag);
  if (!tag_o) return nullptr;
  uint64_t tag = PyLong_AsUnsignedLongLong(tag_o);
  Py_DECREF(tag_o);
  if (tag == static_cast<uint64_t>(-1) && PyErr_Occurred()) return nullptr;
  for (size_t i = 0; i < m->reg->size(); ++i) {
    if ((*m->reg)[i].first != tag) continue;
    MailRecvObject* r = (*m->reg)[i].second;
    m->reg->erase(m->reg->begin() + static_cast<long>(i));
    r->registered = 0;
    Py_INCREF(msg);
    r->value = msg;
    PyObject* ret = r->waker ? PyObject_CallNoArgs(r->waker) : nullptr;
    if (r->waker && !ret) {
      Py_DECREF(reinterpret_cast<PyObject*>(r));
      return nullptr;
    }
    Py_XDECREF(ret);
    Py_DECREF(reinterpret_cast<PyObject*>(r));  // drop the registry ref
    Py_RETURN_NONE;
  }
  Py_INCREF(msg);
  m->msgs->push_back({tag, msg});
  Py_RETURN_NONE;
}

static PyObject* Mailbox_recv(PyObject* self, PyObject* tag_o) {
  MailboxObject* m = reinterpret_cast<MailboxObject*>(self);
  uint64_t tag = PyLong_AsUnsignedLongLong(tag_o);
  if (tag == static_cast<uint64_t>(-1) && PyErr_Occurred()) return nullptr;
  MailRecvObject* r =
      reinterpret_cast<MailRecvObject*>(MailRecvType.tp_alloc(&MailRecvType, 0));
  if (!r) return nullptr;
  Py_INCREF(self);
  r->mb = m;
  r->tag = tag;
  r->value = nullptr;
  r->waker = nullptr;
  r->done = 0;
  r->registered = 0;
  for (size_t i = 0; i < m->msgs->size(); ++i) {
    if ((*m->msgs)[i].first != tag) continue;
    r->value = (*m->msgs)[i].second;  // transfer the buffered ref
    m->msgs->erase(m->msgs->begin() + static_cast<long>(i));
    return reinterpret_cast<PyObject*>(r);
  }
  Py_INCREF(reinterpret_cast<PyObject*>(r));  // registry ref
  m->reg->push_back({tag, r});
  r->registered = 1;
  return reinterpret_cast<PyObject*>(r);
}

static PyMethodDef Mailbox_methods[] = {
    {"deliver", Mailbox_deliver, METH_O,
     "deliver(msg): wake the first receiver registered for msg.tag, "
     "else buffer"},
    {"recv", Mailbox_recv, METH_O,
     "recv(tag) -> MailRecv pollable (buffered message or eager "
     "registration)"},
    {nullptr, nullptr, 0, nullptr},
};

static int MailRecv_traverse(PyObject* self, visitproc visit, void* arg) {
  MailRecvObject* r = reinterpret_cast<MailRecvObject*>(self);
  Py_VISIT(reinterpret_cast<PyObject*>(r->mb));
  Py_VISIT(r->value);
  Py_VISIT(r->waker);
  return 0;
}

static void mailrecv_deregister(MailRecvObject* r) {
  if (!r->registered || !r->mb || !r->mb->reg) return;
  r->registered = 0;
  auto* reg = r->mb->reg;
  for (size_t i = 0; i < reg->size(); ++i) {
    if ((*reg)[i].second != r) continue;
    reg->erase(reg->begin() + static_cast<long>(i));
    Py_DECREF(reinterpret_cast<PyObject*>(r));
    return;
  }
}

static int MailRecv_clear_gc(PyObject* self) {
  MailRecvObject* r = reinterpret_cast<MailRecvObject*>(self);
  Py_CLEAR(r->value);
  Py_CLEAR(r->waker);
  PyObject* mb = reinterpret_cast<PyObject*>(r->mb);
  r->mb = nullptr;
  Py_XDECREF(mb);
  return 0;
}

static void MailRecv_dealloc(PyObject* self) {
  MailRecvObject* r = reinterpret_cast<MailRecvObject*>(self);
  PyObject_GC_UnTrack(self);
  mailrecv_deregister(r);
  MailRecv_clear_gc(self);
  Py_TYPE(self)->tp_free(self);
}

static PyObject* MailRecv_poll(PyObject* self, PyObject* waker) {
  MailRecvObject* r = reinterpret_cast<MailRecvObject*>(self);
  if (r->value) {
    r->done = 1;
    if (ensure_future_imports() < 0) return nullptr;
    PyObject* ready = PyObject_CallOneArg(g_ready_cls, r->value);
    Py_CLEAR(r->value);
    return ready;
  }
  Py_INCREF(waker);
  Py_XSETREF(r->waker, waker);
  if (ensure_future_imports() < 0) return nullptr;
  Py_INCREF(g_pending);
  return g_pending;
}

static PyObject* MailRecv_drop(PyObject* self, PyObject*) {
  MailRecvObject* r = reinterpret_cast<MailRecvObject*>(self);
  if (!r->done) mailrecv_deregister(r);
  Py_RETURN_NONE;
}

static PyMethodDef MailRecv_methods[] = {
    {"poll", MailRecv_poll, METH_O, "Pollable.poll(waker)"},
    {"drop", MailRecv_drop, METH_NOARGS,
     "deregister a pending receiver (cancellation safety)"},
    {nullptr, nullptr, 0, nullptr},
};


// ---------------------------------------------------------------------------
// RecvDeadline — the RPC wait fused into ONE native pollable:
// race(mailbox.recv(tag), sleep_until(deadline)). Ready(msg) on arrival,
// Ready(None) on expiry — the Python caller maps None to TimeoutError.
// Replaces timeout()'s coroutine + _InlineFuture + _Race + SleepGate
// tower on the call_with_data hot path (net/rpc.py).
// ---------------------------------------------------------------------------

struct RecvDeadlineObject {
  PyObject_HEAD
  MailRecvObject* inner;  // strong; owns the mailbox registration
  TimeCoreObject* core;   // strong
  long long deadline_ns;
  char armed;
};

static PyObject* RecvDeadline_new(PyTypeObject* type, PyObject* args,
                                  PyObject*) {
  PyObject *mb, *tag_o, *core;
  long long deadline;
  if (!PyArg_ParseTuple(args, "O!OLO!", &MailboxType, &mb, &tag_o, &deadline,
                        &TimeCoreType, &core)) {
    return nullptr;
  }
  PyObject* inner = Mailbox_recv(mb, tag_o);
  if (!inner) return nullptr;
  RecvDeadlineObject* self =
      reinterpret_cast<RecvDeadlineObject*>(type->tp_alloc(type, 0));
  if (!self) { Py_DECREF(inner); return nullptr; }
  self->inner = reinterpret_cast<MailRecvObject*>(inner);
  self->deadline_ns = deadline;
  self->armed = 0;
  Py_INCREF(core);
  self->core = reinterpret_cast<TimeCoreObject*>(core);
  return reinterpret_cast<PyObject*>(self);
}

static void RecvDeadline_dealloc(PyObject* self) {
  RecvDeadlineObject* r = reinterpret_cast<RecvDeadlineObject*>(self);
  PyObject_GC_UnTrack(self);
  Py_XDECREF(reinterpret_cast<PyObject*>(r->inner));
  Py_XDECREF(reinterpret_cast<PyObject*>(r->core));
  Py_TYPE(self)->tp_free(self);
}

static int RecvDeadline_traverse(PyObject* self, visitproc visit, void* arg) {
  RecvDeadlineObject* r = reinterpret_cast<RecvDeadlineObject*>(self);
  Py_VISIT(reinterpret_cast<PyObject*>(r->inner));
  Py_VISIT(reinterpret_cast<PyObject*>(r->core));
  return 0;
}

static int RecvDeadline_clear_gc(PyObject* self) {
  RecvDeadlineObject* r = reinterpret_cast<RecvDeadlineObject*>(self);
  PyObject* i = reinterpret_cast<PyObject*>(r->inner); r->inner = nullptr;
  Py_XDECREF(i);
  PyObject* c = reinterpret_cast<PyObject*>(r->core); r->core = nullptr;
  Py_XDECREF(c);
  return 0;
}

static PyObject* RecvDeadline_poll(PyObject* self, PyObject* waker) {
  RecvDeadlineObject* r = reinterpret_cast<RecvDeadlineObject*>(self);
  // message first (the Python race polls inner before the deadline, so a
  // response arriving exactly at the deadline still wins)
  PyObject* got = MailRecv_poll(reinterpret_cast<PyObject*>(r->inner), waker);
  if (!got) return nullptr;
  if (got != g_pending) return got;  // Ready(msg)
  Py_DECREF(got);
  if (r->core->now_ns >= r->deadline_ns) {
    // expiry: release the mailbox registration immediately (the Python
    // race's drop-on-expiry semantics)
    mailrecv_deregister(r->inner);
    Py_INCREF(g_ready_none);
    return g_ready_none;
  }
  if (!r->armed) {
    r->armed = 1;
    Py_INCREF(waker);
    r->core->heap->push_back(
        TimerEnt{r->deadline_ns, ++r->core->seq, waker});
    std::push_heap(r->core->heap->begin(), r->core->heap->end(), TimerCmp{});
  }
  Py_INCREF(g_pending);
  return g_pending;
}

static PyObject* RecvDeadline_drop(PyObject* self, PyObject*) {
  RecvDeadlineObject* r = reinterpret_cast<RecvDeadlineObject*>(self);
  if (r->inner && !r->inner->done) mailrecv_deregister(r->inner);
  Py_RETURN_NONE;
}

static PyMethodDef RecvDeadline_methods[] = {
    {"poll", RecvDeadline_poll, METH_O, "Pollable.poll(waker)"},
    {"drop", RecvDeadline_drop, METH_NOARGS, "cancellation safety"},
    {nullptr, nullptr, 0, nullptr},
};

static PyTypeObject RecvDeadlineType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
    "hostcore.RecvDeadline",      /* tp_name */
    sizeof(RecvDeadlineObject),   /* tp_basicsize */
};

// ---------------------------------------------------------------------------
// SleepGate — the sleep pollable with a native poll
// (semantics of time.SleepFuture: registers a timer-wake on each poll)
// ---------------------------------------------------------------------------

struct SleepGateObject {
  PyObject_HEAD
  long long deadline_ns;
  char armed;  // a timer for this gate is already pending — don't re-push
  TimeCoreObject* core;  // strong
};

static PyObject* SleepGate_new(PyTypeObject* type, PyObject* args, PyObject*) {
  long long deadline;
  PyObject* core;
  if (!PyArg_ParseTuple(args, "LO!", &deadline, &TimeCoreType, &core)) {
    return nullptr;
  }
  SleepGateObject* self =
      reinterpret_cast<SleepGateObject*>(type->tp_alloc(type, 0));
  if (!self) return nullptr;
  self->deadline_ns = deadline;
  self->armed = 0;
  Py_INCREF(core);
  self->core = reinterpret_cast<TimeCoreObject*>(core);
  return reinterpret_cast<PyObject*>(self);
}

static void SleepGate_dealloc(PyObject* self) {
  PyObject_GC_UnTrack(self);
  Py_XDECREF(reinterpret_cast<SleepGateObject*>(self)->core);
  Py_TYPE(self)->tp_free(self);
}

static int SleepGate_traverse(PyObject* self, visitproc visit, void* arg) {
  Py_VISIT(reinterpret_cast<SleepGateObject*>(self)->core);
  return 0;
}

static int SleepGate_clear_gc(PyObject* self) {
  SleepGateObject* g = reinterpret_cast<SleepGateObject*>(self);
  Py_CLEAR(g->core);
  return 0;
}

static PyObject* SleepGate_poll(PyObject* self, PyObject* waker) {
  SleepGateObject* g = reinterpret_cast<SleepGateObject*>(self);
  if (ensure_future_imports() < 0) return nullptr;
  if (g->core->now_ns >= g->deadline_ns) {
    Py_INCREF(g_ready_none);
    return g_ready_none;
  }
  if (!g->armed) {
    // one timer per gate: re-polls before the deadline (e.g. from a race
    // partner's wake) don't push duplicates — the armed timer fires at
    // the deadline regardless (the pollable has a single awaiting task)
    g->armed = 1;
    Py_INCREF(waker);
    g->core->heap->push_back(TimerEnt{g->deadline_ns, ++g->core->seq, waker});
    std::push_heap(g->core->heap->begin(), g->core->heap->end(), TimerCmp{});
  }
  Py_INCREF(g_pending);
  return g_pending;
}

static PyObject* SleepGate_drop(PyObject*, PyObject*) { Py_RETURN_NONE; }

static PyObject* SleepGate_get_deadline(PyObject* self, void*) {
  return PyLong_FromLongLong(
      reinterpret_cast<SleepGateObject*>(self)->deadline_ns);
}

static PyMethodDef SleepGate_methods[] = {
    {"poll", SleepGate_poll, METH_O, "poll(waker) -> Ready(None) | PENDING"},
    {"drop", SleepGate_drop, METH_NOARGS, "no-op (stale wakes are harmless)"},
    {nullptr, nullptr, 0, nullptr},
};

static PyGetSetDef SleepGate_getset[] = {
    {"deadline_ns", SleepGate_get_deadline, nullptr, "timer deadline", nullptr},
    {nullptr, nullptr, nullptr, nullptr, nullptr},
};

static PyTypeObject SleepGateType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
    "hostcore.SleepGate",      /* tp_name */
    sizeof(SleepGateObject),   /* tp_basicsize */
};


// ---------------------------------------------------------------------------
// NetCore — the datagram send/wire/delivery hot path in C
// (mirrors net/__init__.py send_raw -> _send_phase2 -> network.try_send;
// reference: sim/net/mod.rs:287-334 + network.rs:261-325).
//
// Python stays the source of truth for all STATE (clog sets, socket
// tables, config, hooks, ipvs, incarnations live in the NetSim/Network
// objects; NetCore holds references and reads them at the wire moment),
// and any feature the fast path does not model — drop hooks on RPC
// traffic, IPVS rewrites — falls back to the Python _send_phase2 at
// fire time. Draw order is bit-identical to the Python path (buggify
// gate, 0-5 us delay, loss gate only when rate > 0, latency), so the
// cross-path parity tests keep holding.
// ---------------------------------------------------------------------------

static PyObject* s_buggify_enabled;
static PyObject* s_send_phase2;
static PyObject* s_deliver_m;
static PyObject* s_executor;
static PyObject* s_msg_count;
static PyObject* s_packet_loss_rate;
static PyObject* s_lat_min;
static PyObject* s_lat_max;
static PyObject* s_spike_prob;
static PyObject* s_spike_min;
static PyObject* s_spike_max;
static PyObject* g_ip_loopback = nullptr;  // "127.0.0.1"
static PyObject* g_ip_zero = nullptr;      // "0.0.0.0"
static PyObject* g_rpc_req_str = nullptr;  // "rpc_req"

struct NetCoreObject {
  PyObject_HEAD
  PyObject* netsim;
  PyObject* rng_wrap;      // GlobalRng (buggify_enabled lives here)
  RngObject* rng;          // native draw stream (strong)
  TimeCoreObject* timec;   // native timer heap (strong)
  PyObject* msg_cls;       // net.endpoint.Message
  PyObject* ctx_current;   // _context.current (panic routing)
  PyObject* cfg;           // network.config (NetConfig; storms mutate it)
  PyObject* hooks_req;     // netsim._hooks_req (list)
  PyObject* hooks_rsp;     // netsim._hooks_rsp (list)
  PyObject* ipvs_services; // netsim.ipvs._services (dict)
  PyObject* incarnation;   // netsim._incarnation (dict)
  PyObject* clogged_in;    // network.clogged_in (set)
  PyObject* clogged_out;   // network.clogged_out (set)
  PyObject* clogged_links; // network.clogged_links (set of (src, dst))
  PyObject* sockets;       // network.sockets (dict node -> {port: sock})
  PyObject* ip_node;       // network.ip_node (dict ip -> node)
  PyObject* node_ip;       // network.node_ip (dict node -> ip)
  PyObject* stat;          // network.stat
  uint64_t send_seq;       // every-16th blocking-send cadence
};

struct PendingSendObject {
  PyObject_HEAD
  NetCoreObject* nc;   // strong
  long src_node;
  long incarnation;
  PyObject* src_addr;  // (ip, port)
  PyObject* dst;       // sender-visible destination (hooks see this)
  PyObject* resolved;  // post-DNS destination
  PyObject* tag;
  PyObject* payload;
  PyObject* kind;      // None | "rpc_req" | "rpc_rsp"
};

struct PendingDeliverObject {
  PyObject_HEAD
  PyObject* sock;
  PyObject* msg;
};

static void PendingSend_dealloc(PyObject* self) {
  PendingSendObject* p = reinterpret_cast<PendingSendObject*>(self);
  PyObject_GC_UnTrack(self);
  Py_XDECREF(reinterpret_cast<PyObject*>(p->nc));
  Py_XDECREF(p->src_addr);
  Py_XDECREF(p->dst);
  Py_XDECREF(p->resolved);
  Py_XDECREF(p->tag);
  Py_XDECREF(p->payload);
  Py_XDECREF(p->kind);
  Py_TYPE(self)->tp_free(self);
}

static int PendingSend_traverse(PyObject* self, visitproc visit, void* arg) {
  PendingSendObject* p = reinterpret_cast<PendingSendObject*>(self);
  Py_VISIT(reinterpret_cast<PyObject*>(p->nc));
  Py_VISIT(p->src_addr);
  Py_VISIT(p->dst);
  Py_VISIT(p->resolved);
  Py_VISIT(p->tag);
  Py_VISIT(p->payload);
  Py_VISIT(p->kind);
  return 0;
}

static void PendingDeliver_dealloc(PyObject* self) {
  PendingDeliverObject* p = reinterpret_cast<PendingDeliverObject*>(self);
  PyObject_GC_UnTrack(self);
  Py_XDECREF(p->sock);
  Py_XDECREF(p->msg);
  Py_TYPE(self)->tp_free(self);
}

static int PendingDeliver_traverse(PyObject* self, visitproc visit, void* arg) {
  PendingDeliverObject* p = reinterpret_cast<PendingDeliverObject*>(self);
  Py_VISIT(p->sock);
  Py_VISIT(p->msg);
  return 0;
}

static void NetCore_dealloc(PyObject* self) {
  NetCoreObject* n = reinterpret_cast<NetCoreObject*>(self);
  PyObject_GC_UnTrack(self);
  Py_XDECREF(n->netsim);
  Py_XDECREF(n->rng_wrap);
  Py_XDECREF(reinterpret_cast<PyObject*>(n->rng));
  Py_XDECREF(reinterpret_cast<PyObject*>(n->timec));
  Py_XDECREF(n->msg_cls);
  Py_XDECREF(n->ctx_current);
  Py_XDECREF(n->cfg);
  Py_XDECREF(n->hooks_req);
  Py_XDECREF(n->hooks_rsp);
  Py_XDECREF(n->ipvs_services);
  Py_XDECREF(n->incarnation);
  Py_XDECREF(n->clogged_in);
  Py_XDECREF(n->clogged_out);
  Py_XDECREF(n->clogged_links);
  Py_XDECREF(n->sockets);
  Py_XDECREF(n->ip_node);
  Py_XDECREF(n->node_ip);
  Py_XDECREF(n->stat);
  Py_TYPE(self)->tp_free(self);
}

static int NetCore_traverse(PyObject* self, visitproc visit, void* arg) {
  NetCoreObject* n = reinterpret_cast<NetCoreObject*>(self);
  Py_VISIT(n->netsim);
  Py_VISIT(n->rng_wrap);
  Py_VISIT(reinterpret_cast<PyObject*>(n->rng));
  Py_VISIT(reinterpret_cast<PyObject*>(n->timec));
  Py_VISIT(n->msg_cls);
  Py_VISIT(n->ctx_current);
  Py_VISIT(n->cfg);
  Py_VISIT(n->hooks_req);
  Py_VISIT(n->hooks_rsp);
  Py_VISIT(n->ipvs_services);
  Py_VISIT(n->incarnation);
  Py_VISIT(n->clogged_in);
  Py_VISIT(n->clogged_out);
  Py_VISIT(n->clogged_links);
  Py_VISIT(n->sockets);
  Py_VISIT(n->ip_node);
  Py_VISIT(n->node_ip);
  Py_VISIT(n->stat);
  return 0;
}

static int NetCore_clear_gc(PyObject* self) {
  NetCoreObject* n = reinterpret_cast<NetCoreObject*>(self);
  Py_CLEAR(n->netsim);
  Py_CLEAR(n->rng_wrap);
  PyObject* r = reinterpret_cast<PyObject*>(n->rng); n->rng = nullptr; Py_XDECREF(r);
  PyObject* t = reinterpret_cast<PyObject*>(n->timec); n->timec = nullptr; Py_XDECREF(t);
  Py_CLEAR(n->msg_cls);
  Py_CLEAR(n->ctx_current);
  Py_CLEAR(n->cfg);
  Py_CLEAR(n->hooks_req);
  Py_CLEAR(n->hooks_rsp);
  Py_CLEAR(n->ipvs_services);
  Py_CLEAR(n->incarnation);
  Py_CLEAR(n->clogged_in);
  Py_CLEAR(n->clogged_out);
  Py_CLEAR(n->clogged_links);
  Py_CLEAR(n->sockets);
  Py_CLEAR(n->ip_node);
  Py_CLEAR(n->node_ip);
  Py_CLEAR(n->stat);
  return 0;
}

// NetCore(netsim, network, rng_wrap, rng_core, time_core, msg_cls, ctx_current)
static PyObject* NetCore_new(PyTypeObject* type, PyObject* args, PyObject*) {
  PyObject *netsim, *network, *rng_wrap, *rng_o, *time_o, *msg_cls, *ctx_cur;
  if (!PyArg_ParseTuple(args, "OOOO!O!OO", &netsim, &network, &rng_wrap,
                        &RngType, &rng_o, &TimeCoreType, &time_o, &msg_cls,
                        &ctx_cur)) {
    return nullptr;
  }
  NetCoreObject* self = reinterpret_cast<NetCoreObject*>(type->tp_alloc(type, 0));
  if (!self) return nullptr;
  self->send_seq = 0;
  Py_INCREF(netsim); self->netsim = netsim;
  Py_INCREF(rng_wrap); self->rng_wrap = rng_wrap;
  Py_INCREF(rng_o); self->rng = reinterpret_cast<RngObject*>(rng_o);
  Py_INCREF(time_o); self->timec = reinterpret_cast<TimeCoreObject*>(time_o);
  Py_INCREF(msg_cls); self->msg_cls = msg_cls;
  Py_INCREF(ctx_cur); self->ctx_current = ctx_cur;
#define PULL(dst, src, name)                                    \
  self->dst = PyObject_GetAttrString(src, name);                \
  if (!self->dst) { Py_DECREF(self); return nullptr; }
  PULL(cfg, network, "config")
  PULL(hooks_req, netsim, "_hooks_req")
  PULL(hooks_rsp, netsim, "_hooks_rsp")
  PULL(incarnation, netsim, "_incarnation")
  PULL(clogged_in, network, "clogged_in")
  PULL(clogged_out, network, "clogged_out")
  PULL(clogged_links, network, "clogged_links")
  PULL(sockets, network, "sockets")
  PULL(ip_node, network, "ip_node")
  PULL(node_ip, network, "node_ip")
  PULL(stat, network, "stat")
#undef PULL
  PyObject* ipvs = PyObject_GetAttrString(netsim, "ipvs");
  if (!ipvs) { Py_DECREF(self); return nullptr; }
  self->ipvs_services = PyObject_GetAttrString(ipvs, "_services");
  Py_DECREF(ipvs);
  if (!self->ipvs_services) { Py_DECREF(self); return nullptr; }
  return reinterpret_cast<PyObject*>(self);
}

static inline double rng_random_f64(RngObject* r) {
  return static_cast<double>(rng_u64(r) >> 11) * (1.0 / 9007199254740992.0);
}

// send(src_node, src_addr, dst, resolved, tag, payload, kind)
//   -> None            datagram scheduled natively (timer at t + delay)
//   -> (1, delay_ns)   buggified 1-5 s: caller awaits then runs phase2
//   -> (2, delay_ns)   every-16th blocking send: caller awaits then phase2
static PyObject* NetCore_send(PyObject* self, PyObject* args) {
  NetCoreObject* nc = reinterpret_cast<NetCoreObject*>(self);
  long src_node;
  PyObject *src_addr, *dst, *resolved, *tag, *payload, *kind;
  if (!PyArg_ParseTuple(args, "lOOOOOO", &src_node, &src_addr, &dst,
                        &resolved, &tag, &payload, &kind)) {
    return nullptr;
  }
  // buggify gate (rand/__init__.py buggify_with_prob: no draw when off)
  PyObject* bug = PyObject_GetAttr(nc->rng_wrap, s_buggify_enabled);
  if (!bug) return nullptr;
  int buggify = PyObject_IsTrue(bug);
  Py_DECREF(bug);
  if (buggify < 0) return nullptr;
  if (buggify && rng_random_f64(nc->rng) < 0.1) {
    int64_t big = rng_range(nc->rng, 1000000000LL, 5000000000LL);
    return Py_BuildValue("(iL)", 1, static_cast<long long>(big));
  }
  int64_t delay = rng_range(nc->rng, 0, 5000);
  if (++nc->send_seq % 16 == 0) {
    return Py_BuildValue("(iL)", 2, static_cast<long long>(delay));
  }
  long inc = 0;
  {
    PyObject* k = PyLong_FromLong(src_node);
    if (!k) return nullptr;
    PyObject* v = PyDict_GetItemWithError(nc->incarnation, k);  // borrowed
    Py_DECREF(k);
    if (!v && PyErr_Occurred()) return nullptr;
    if (v) {
      inc = PyLong_AsLong(v);
      if (inc == -1 && PyErr_Occurred()) return nullptr;
    }
  }
  PendingSendObject* ps = PyObject_GC_New(PendingSendObject, &PendingSendType);
  if (!ps) return nullptr;
  Py_INCREF(self); ps->nc = nc;
  ps->src_node = src_node;
  ps->incarnation = inc;
  Py_INCREF(src_addr); ps->src_addr = src_addr;
  Py_INCREF(dst); ps->dst = dst;
  Py_INCREF(resolved); ps->resolved = resolved;
  Py_INCREF(tag); ps->tag = tag;
  Py_INCREF(payload); ps->payload = payload;
  Py_INCREF(kind); ps->kind = kind;
  PyObject_GC_Track(reinterpret_cast<PyObject*>(ps));
  TimeCoreObject* t = nc->timec;
  // the heap takes ownership of ps (no extra incref: we hand our ref over)
  t->heap->push_back(TimerEnt{t->now_ns + delay, ++t->seq,
                              reinterpret_cast<PyObject*>(ps)});
  std::push_heap(t->heap->begin(), t->heap->end(), TimerCmp{});
  Py_RETURN_NONE;
}

// Exception during a wire/delivery moment: route to executor.panic — the
// loud-failure path _send_phase2_guarded uses (net/__init__.py).
static int route_panic(NetCoreObject* nc) {
  PyObject *etype, *evalue, *etb;
  PyErr_Fetch(&etype, &evalue, &etb);
  PyErr_NormalizeException(&etype, &evalue, &etb);
  if (etb) PyException_SetTraceback(evalue, etb);
  int ok = -1;
  PyObject* ctx = PyObject_CallNoArgs(nc->ctx_current);
  if (ctx) {
    PyObject* ex = PyObject_GetAttr(ctx, s_executor);
    Py_DECREF(ctx);
    if (ex) {
      if (PyObject_SetAttr(ex, s_panic, evalue) == 0) ok = 0;
      Py_DECREF(ex);
    }
  }
  if (ok < 0) {
    PyErr_Restore(etype, evalue, etb);
    return -1;
  }
  Py_XDECREF(etype);
  Py_XDECREF(evalue);
  Py_XDECREF(etb);
  return 0;
}

static int pending_send_fire(PyObject* ps_o) {
  PendingSendObject* ps = reinterpret_cast<PendingSendObject*>(ps_o);
  NetCoreObject* nc = ps->nc;

  // sender died between send and wire moment: drop (kill cancels the
  // suspended sender in the reference; see net/__init__.py)
  {
    PyObject* k = PyLong_FromLong(ps->src_node);
    if (!k) return route_panic(nc);
    PyObject* v = PyDict_GetItemWithError(nc->incarnation, k);
    Py_DECREF(k);
    if (!v && PyErr_Occurred()) return route_panic(nc);
    long cur = 0;
    if (v) {
      cur = PyLong_AsLong(v);
      if (cur == -1 && PyErr_Occurred()) return route_panic(nc);
    }
    if (cur != ps->incarnation) return 0;
  }

  // features the fast path does not model: RPC drop hooks, IPVS
  // rewrites -> Python _send_phase2 handles the whole wire moment
  int fallback = PyDict_Size(nc->ipvs_services) > 0;
  if (!fallback && ps->kind != Py_None) {
    int is_req = PyUnicode_CompareWithASCIIString(ps->kind, "rpc_req") == 0;
    PyObject* lst = is_req ? nc->hooks_req : nc->hooks_rsp;
    if (PyList_Check(lst) && PyList_GET_SIZE(lst) > 0) fallback = 1;
  }
  if (fallback) {
    PyObject* src_l = PyLong_FromLong(ps->src_node);
    if (!src_l) return route_panic(nc);
    PyObject* r = PyObject_CallMethodObjArgs(
        nc->netsim, s_send_phase2, src_l, ps->src_addr, ps->dst, ps->resolved,
        ps->tag, ps->payload, ps->kind, nullptr);
    Py_DECREF(src_l);
    if (!r) return route_panic(nc);
    Py_DECREF(r);
    return 0;
  }

  // ---- network.try_send, natively ----------------------------------------
  PyObject* res_ip = PyTuple_GetItem(ps->resolved, 0);   // borrowed
  PyObject* res_port = PyTuple_GetItem(ps->resolved, 1); // borrowed
  if (!res_ip || !res_port) return route_panic(nc);
  const char* ip = PyUnicode_AsUTF8(res_ip);
  if (!ip) return route_panic(nc);
  int loop = strncmp(ip, "127.", 4) == 0 || strcmp(ip, "localhost") == 0;
  long dst_node;
  if (loop) {
    dst_node = ps->src_node;
  } else {
    PyObject* dn = PyDict_GetItemWithError(nc->ip_node, res_ip);
    if (!dn) return PyErr_Occurred() ? route_panic(nc) : 0;  // no such ip: drop
    dst_node = PyLong_AsLong(dn);
    if (dst_node == -1 && PyErr_Occurred()) return route_panic(nc);
  }
  PyObject* dst_l = PyLong_FromLong(dst_node);
  if (!dst_l) return route_panic(nc);
  PyObject* socks = PyDict_GetItemWithError(nc->sockets, dst_l);  // borrowed
  if (!socks) {
    Py_DECREF(dst_l);
    return PyErr_Occurred() ? route_panic(nc) : 0;  // node gone: drop
  }
  PyObject* sock = PyDict_GetItemWithError(socks, res_port);  // borrowed
  if (!sock) {
    Py_DECREF(dst_l);
    return PyErr_Occurred() ? route_panic(nc) : 0;  // nothing bound: drop
  }
  Py_INCREF(sock);

  // clog check (network.is_clogged)
  PyObject* src_l = PyLong_FromLong(ps->src_node);
  if (!src_l) { Py_DECREF(sock); Py_DECREF(dst_l); return route_panic(nc); }
  int clogged = PySet_Contains(nc->clogged_out, src_l);
  if (clogged == 0) {
    int c2 = PySet_Contains(nc->clogged_in, dst_l);
    clogged = c2 != 0 ? c2 : 0;
    if (clogged == 0) {
      PyObject* pair = PyTuple_Pack(2, src_l, dst_l);
      if (!pair) clogged = -1;
      else {
        clogged = PySet_Contains(nc->clogged_links, pair);
        Py_DECREF(pair);
      }
    }
  }
  Py_DECREF(src_l);
  Py_DECREF(dst_l);
  if (clogged < 0) { Py_DECREF(sock); return route_panic(nc); }
  if (clogged) { Py_DECREF(sock); return 0; }

  // loss gate: draw only when the (live, storm-composited) rate > 0
  PyObject* lr = PyObject_GetAttr(nc->cfg, s_packet_loss_rate);
  if (!lr) { Py_DECREF(sock); return route_panic(nc); }
  double rate = PyFloat_AsDouble(lr);
  Py_DECREF(lr);
  if (rate == -1.0 && PyErr_Occurred()) { Py_DECREF(sock); return route_panic(nc); }
  if (rate > 0.0 && rng_random_f64(nc->rng) < rate) { Py_DECREF(sock); return 0; }

  // latency draw (network.test_link)
  PyObject* lmin_o = PyObject_GetAttr(nc->cfg, s_lat_min);
  PyObject* lmax_o = lmin_o ? PyObject_GetAttr(nc->cfg, s_lat_max) : nullptr;
  if (!lmin_o || !lmax_o) {
    Py_XDECREF(lmin_o); Py_XDECREF(lmax_o); Py_DECREF(sock);
    return route_panic(nc);
  }
  long long lmin = PyLong_AsLongLong(lmin_o);
  long long lmax = PyLong_AsLongLong(lmax_o);
  Py_DECREF(lmin_o);
  Py_DECREF(lmax_o);
  if ((lmin == -1 || lmax == -1) && PyErr_Occurred()) {
    Py_DECREF(sock);
    return route_panic(nc);
  }
  int64_t latency = rng_range(nc->rng, lmin, lmax + 1);

  // delay-spike window (network.py test_link lines ~171-177): same
  // draws in the same order as the Python path — parity requires the
  // gen_bool draw whenever the prob is nonzero
  {
    PyObject* sp = PyObject_GetAttr(nc->cfg, s_spike_prob);
    if (!sp) { Py_DECREF(sock); return route_panic(nc); }
    double spike_prob = PyFloat_AsDouble(sp);
    Py_DECREF(sp);
    if (spike_prob == -1.0 && PyErr_Occurred()) {
      Py_DECREF(sock);
      return route_panic(nc);
    }
    if (spike_prob > 0.0 && rng_random_f64(nc->rng) < spike_prob) {
      PyObject* smin_o = PyObject_GetAttr(nc->cfg, s_spike_min);
      PyObject* smax_o = smin_o ? PyObject_GetAttr(nc->cfg, s_spike_max) : nullptr;
      if (!smin_o || !smax_o) {
        Py_XDECREF(smin_o); Py_XDECREF(smax_o); Py_DECREF(sock);
        return route_panic(nc);
      }
      long long smin = PyLong_AsLongLong(smin_o);
      long long smax = PyLong_AsLongLong(smax_o);
      Py_DECREF(smin_o);
      Py_DECREF(smax_o);
      if ((smin == -1 || smax == -1) && PyErr_Occurred()) {
        Py_DECREF(sock);
        return route_panic(nc);
      }
      latency += rng_range(nc->rng, smin, smax);
    }
  }

  // stats
  {
    PyObject* cnt = PyObject_GetAttr(nc->stat, s_msg_count);
    if (!cnt) { Py_DECREF(sock); return route_panic(nc); }
    PyObject* one = PyLong_FromLong(1);
    PyObject* ncnt = one ? PyNumber_Add(cnt, one) : nullptr;
    Py_DECREF(cnt);
    Py_XDECREF(one);
    int st = ncnt ? PyObject_SetAttr(nc->stat, s_msg_count, ncnt) : -1;
    Py_XDECREF(ncnt);
    if (st < 0) { Py_DECREF(sock); return route_panic(nc); }
  }

  // source address the peer observes (NetSim._src_ip)
  PyObject* fip;
  if (loop) {
    fip = g_ip_loopback;
    Py_INCREF(fip);
  } else {
    PyObject* k = PyLong_FromLong(ps->src_node);
    if (!k) { Py_DECREF(sock); return route_panic(nc); }
    PyObject* v = PyDict_GetItemWithError(nc->node_ip, k);
    Py_DECREF(k);
    if (!v && PyErr_Occurred()) { Py_DECREF(sock); return route_panic(nc); }
    fip = v ? v : g_ip_zero;
    Py_INCREF(fip);
  }
  PyObject* src_port = PyTuple_GetItem(ps->src_addr, 1);  // borrowed
  if (!src_port) { Py_DECREF(fip); Py_DECREF(sock); return route_panic(nc); }
  PyObject* from_addr = PyTuple_Pack(2, fip, src_port);
  Py_DECREF(fip);
  if (!from_addr) { Py_DECREF(sock); return route_panic(nc); }
  PyObject* msg = PyObject_CallFunctionObjArgs(
      nc->msg_cls, ps->tag, ps->payload, from_addr, nullptr);
  Py_DECREF(from_addr);
  if (!msg) { Py_DECREF(sock); return route_panic(nc); }

  PendingDeliverObject* pd =
      PyObject_GC_New(PendingDeliverObject, &PendingDeliverType);
  if (!pd) { Py_DECREF(msg); Py_DECREF(sock); return route_panic(nc); }
  pd->sock = sock;  // both refs handed over
  pd->msg = msg;
  PyObject_GC_Track(reinterpret_cast<PyObject*>(pd));
  TimeCoreObject* t = nc->timec;
  t->heap->push_back(TimerEnt{t->now_ns + latency, ++t->seq,
                              reinterpret_cast<PyObject*>(pd)});
  std::push_heap(t->heap->begin(), t->heap->end(), TimerCmp{});
  return 0;
}

static int pending_deliver_fire(PyObject* pd_o) {
  PendingDeliverObject* pd = reinterpret_cast<PendingDeliverObject*>(pd_o);
  PyObject* r = PyObject_CallMethodObjArgs(pd->sock, s_deliver_m, pd->msg,
                                           nullptr);
  if (!r) return -1;  // propagate, like a raising Python timer callback
  Py_DECREF(r);
  return 0;
}


// rpc_call(mailbox, src_node, src_addr, dst, resolved, type_id, req,
//          data, deadline_ns)
//   -> (wait, None)               request scheduled; await `wait`
//   -> (wait, (mode, delay_ns, payload))  blocking-send case: the caller
//      awaits the delay, runs _send_phase2 with `payload`, then awaits
//      `wait`. Draw order matches the Python path exactly: rsp-tag u64,
//      then the send draws.
static PyObject* NetCore_rpc_call(PyObject* self, PyObject* args) {
  NetCoreObject* nc = reinterpret_cast<NetCoreObject*>(self);
  PyObject *mb, *src_addr, *dst, *resolved, *type_id, *req, *data;
  long src_node;
  long long deadline_ns;
  if (!PyArg_ParseTuple(args, "O!lOOOOOOL", &MailboxType, &mb, &src_node,
                        &src_addr, &dst, &resolved, &type_id, &req, &data,
                        &deadline_ns)) {
    return nullptr;
  }
  // response tag: the same draw call_with_data makes (thread_rng().next_u64())
  uint64_t rsp_tag = rng_u64(nc->rng);
  PyObject* tag_o = PyLong_FromUnsignedLongLong(rsp_tag);
  if (!tag_o) return nullptr;
  PyObject* payload = PyTuple_Pack(3, tag_o, req, data);
  if (!payload) { Py_DECREF(tag_o); return nullptr; }

  // register the receiver BEFORE the send (equivalent: the response
  // cannot arrive before the request leaves the wire moment)
  PyObject* wait_args = Py_BuildValue(
      "(OOLO)", mb, tag_o, deadline_ns,
      reinterpret_cast<PyObject*>(nc->timec));
  Py_DECREF(tag_o);
  if (!wait_args) { Py_DECREF(payload); return nullptr; }
  PyObject* wait = PyObject_CallObject(
      reinterpret_cast<PyObject*>(&RecvDeadlineType), wait_args);
  Py_DECREF(wait_args);
  if (!wait) { Py_DECREF(payload); return nullptr; }

  // ---- the send (same draws/cadence as NetCore_send) ----------------------
  PyObject* bug = PyObject_GetAttr(nc->rng_wrap, s_buggify_enabled);
  if (!bug) { Py_DECREF(wait); Py_DECREF(payload); return nullptr; }
  int buggify = PyObject_IsTrue(bug);
  Py_DECREF(bug);
  if (buggify < 0) { Py_DECREF(wait); Py_DECREF(payload); return nullptr; }
  long long blocking = -1;
  int mode = 0;
  if (buggify && rng_random_f64(nc->rng) < 0.1) {
    blocking = rng_range(nc->rng, 1000000000LL, 5000000000LL);
    mode = 1;
  } else {
    long long delay = rng_range(nc->rng, 0, 5000);
    if (++nc->send_seq % 16 == 0) {
      blocking = delay;
      mode = 2;
    } else {
      long inc = 0;
      {
        PyObject* k = PyLong_FromLong(src_node);
        if (!k) { Py_DECREF(wait); Py_DECREF(payload); return nullptr; }
        PyObject* v = PyDict_GetItemWithError(nc->incarnation, k);
        Py_DECREF(k);
        if (!v && PyErr_Occurred()) {
          Py_DECREF(wait); Py_DECREF(payload);
          return nullptr;
        }
        if (v) inc = PyLong_AsLong(v);
      }
      PendingSendObject* ps =
          PyObject_GC_New(PendingSendObject, &PendingSendType);
      if (!ps) { Py_DECREF(wait); Py_DECREF(payload); return nullptr; }
      Py_INCREF(self); ps->nc = nc;
      ps->src_node = src_node;
      ps->incarnation = inc;
      Py_INCREF(src_addr); ps->src_addr = src_addr;
      Py_INCREF(dst); ps->dst = dst;
      Py_INCREF(resolved); ps->resolved = resolved;
      Py_INCREF(type_id); ps->tag = type_id;
      ps->payload = payload;  // hand over our ref
      payload = nullptr;
      Py_INCREF(g_rpc_req_str);
      ps->kind = g_rpc_req_str;
      PyObject_GC_Track(reinterpret_cast<PyObject*>(ps));
      TimeCoreObject* t = nc->timec;
      t->heap->push_back(TimerEnt{t->now_ns + delay, ++t->seq,
                                  reinterpret_cast<PyObject*>(ps)});
      std::push_heap(t->heap->begin(), t->heap->end(), TimerCmp{});
    }
  }
  PyObject* out;
  if (mode == 0) {
    out = PyTuple_Pack(2, wait, Py_None);
  } else {
    PyObject* blk = Py_BuildValue("(iLO)", mode, blocking, payload);
    out = blk ? PyTuple_Pack(2, wait, blk) : nullptr;
    Py_XDECREF(blk);
  }
  Py_XDECREF(payload);
  Py_DECREF(wait);
  return out;
}

static PyMethodDef NetCore_methods[] = {
    {"rpc_call", NetCore_rpc_call, METH_VARARGS,
     "fused RPC initiation: tag draw + recv-with-deadline registration + "
     "native send"},
    {"send", NetCore_send, METH_VARARGS,
     "native datagram send; None = scheduled, (mode, delay_ns) = caller "
     "must await the blocking path"},
    {nullptr, nullptr, 0, nullptr},
};

static PyTypeObject NetCoreType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
    "hostcore.NetCore",        /* tp_name */
    sizeof(NetCoreObject),     /* tp_basicsize */
};

PyTypeObject PendingSendType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
    "hostcore.PendingSend",    /* tp_name */
    sizeof(PendingSendObject), /* tp_basicsize */
};

PyTypeObject PendingDeliverType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
    "hostcore.PendingDeliver",    /* tp_name */
    sizeof(PendingDeliverObject), /* tp_basicsize */
};

// ---------------------------------------------------------------------------
// run_all_ready — the executor poll loop (sim/task/mod.rs:263-323)
// ---------------------------------------------------------------------------

// Mirrors Executor.run_all_ready + _poll_task exactly, including the RNG
// draw pattern (pick draw only when len>1; advance draw after each
// effective poll; no advance draw after a panic).
// Returns 0 on success (queue drained or panic set), -1 on error.
static int run_ready_impl(PyObject* executor, PyObject* ctx, RngObject* rng,
                          TimeCoreObject* timec) {
  PyObject* ready = PyObject_GetAttr(executor, s_ready);
  if (!ready) return -1;
  if (!PyList_Check(ready)) {
    Py_DECREF(ready);
    PyErr_SetString(PyExc_TypeError, "executor.ready must be a list");
    return -1;
  }

  int ok = 0;  // 0 = error path, 1 = success
  while (true) {
    Py_ssize_t n = PyList_GET_SIZE(ready);
    if (n == 0) {
      ok = 1;
      break;
    }
    // try_recv_random: swap-remove a uniformly random element
    // (reference: sim/utils/mpsc.rs:73-83). Draw only when n > 1 —
    // identical to the Python loop's draw pattern.
    Py_ssize_t idx =
        n > 1 ? static_cast<Py_ssize_t>(rng_range(rng, 0, n)) : 0;
    PyObject* task = PyList_GET_ITEM(ready, idx);  // borrowed
    Py_INCREF(task);
    if (idx != n - 1) {
      PyObject* last = PyList_GET_ITEM(ready, n - 1);  // borrowed
      Py_INCREF(last);
      // steals our `last` ref and decrefs the old slot value (task)
      if (PyList_SetItem(ready, idx, last) < 0) {
        Py_DECREF(task);
        break;
      }
    }
    if (PyList_SetSlice(ready, n - 1, n, nullptr) < 0) {
      Py_DECREF(task);
      break;
    }

    if (PyObject_SetAttr(task, s_scheduled, Py_False) < 0) {
      Py_DECREF(task);
      break;
    }
    int finished = attr_truth(task, s_finished);
    if (finished < 0) { Py_DECREF(task); break; }
    PyObject* node = PyObject_GetAttr(task, s_node);
    if (!node) { Py_DECREF(task); break; }
    int killed = attr_truth(node, s_killed);
    if (killed < 0) { Py_DECREF(node); Py_DECREF(task); break; }
    if (finished || killed) {
      Py_DECREF(node);
      Py_DECREF(task);
      continue;
    }
    int paused = attr_truth(node, s_paused);
    if (paused < 0) { Py_DECREF(node); Py_DECREF(task); break; }
    if (paused) {
      // park until resume (reference: sim/task/mod.rs:404-424)
      PyObject* parked = PyObject_GetAttr(node, s_paused_tasks);
      int fail = !parked || PyObject_SetAttr(task, s_scheduled, Py_True) < 0 ||
                 PyList_Append(parked, task) < 0;
      Py_XDECREF(parked);
      Py_DECREF(node);
      Py_DECREF(task);
      if (fail) break;
      continue;
    }

    // ---- _poll_task ----
    PyObject* prev_task = PyObject_GetAttr(ctx, s_current_task);
    if (!prev_task) { Py_DECREF(node); Py_DECREF(task); break; }
    if (PyObject_SetAttr(ctx, s_current_task, task) < 0 ||
        PyObject_SetAttr(executor, s_running_task, task) < 0) {
      Py_DECREF(prev_task); Py_DECREF(node); Py_DECREF(task);
      break;
    }
    PyObject* coro = PyObject_GetAttr(task, s_coro);
    int poll_failed = 0;
    if (!coro) {
      poll_failed = 1;
    } else {
      PyObject* result = nullptr;
      PyObject* tl_prev = tl_current_task;
      tl_current_task = task;  // borrowed; AwaitIter reads it during send
      PySendResult sr = PyIter_Send(coro, Py_None, &result);
      tl_current_task = tl_prev;
      Py_DECREF(coro);
      if (sr == PYGEN_RETURN) {
        // StopIteration: task completed with `result`
        poll_failed = 1;  // cleared on full success
        if (PyObject_SetAttr(task, s_finished, Py_True) == 0) {
          PyObject* tasks = PyObject_GetAttr(node, s_tasks);
          if (tasks) {
            PyObject* r1 = PyObject_CallMethodOneArg(tasks, s_discard, task);
            if (r1) {
              Py_DECREF(r1);
              PyObject* cell = PyObject_GetAttr(task, s_cell);
              if (cell) {
                PyObject* pair = PyTuple_Pack(2, result, Py_None);
                if (pair) {
                  PyObject* r2 = PyObject_CallMethodOneArg(cell, s_set, pair);
                  if (r2) {
                    Py_DECREF(r2);
                    poll_failed = 0;
                  }
                  Py_DECREF(pair);
                }
                Py_DECREF(cell);
              }
            }
            Py_DECREF(tasks);
          }
        }
        Py_DECREF(result);
      } else if (sr == PYGEN_NEXT) {
        Py_XDECREF(result);  // yielded value (always None) — task suspended
      } else {
        // PYGEN_ERROR: the "panic" path — only `Exception` subclasses are
        // handled (reference catch_unwind); BaseExceptions propagate.
        if (PyErr_ExceptionMatches(PyExc_Exception)) {
          PyObject *etype, *evalue, *etb;
          PyErr_Fetch(&etype, &evalue, &etb);
          PyErr_NormalizeException(&etype, &evalue, &etb);
          if (etb) PyException_SetTraceback(evalue, etb);
          poll_failed = 1;  // cleared on full success
          if (PyObject_SetAttr(task, s_finished, Py_True) == 0) {
            PyObject* tasks = PyObject_GetAttr(node, s_tasks);
            if (tasks) {
              PyObject* r1 = PyObject_CallMethodOneArg(tasks, s_discard, task);
              if (r1) {
                Py_DECREF(r1);
                PyObject* r2 = PyObject_CallMethodObjArgs(
                    executor, s_handle_panic, task, evalue, nullptr);
                if (r2) {
                  Py_DECREF(r2);
                  poll_failed = 0;
                }
              }
              Py_DECREF(tasks);
            }
          }
          Py_XDECREF(etype);
          Py_XDECREF(evalue);
          Py_XDECREF(etb);
        } else {
          poll_failed = 1;  // propagate (GeneratorExit, KeyboardInterrupt..)
        }
      }
    }
    // finally: restore context even when an exception is propagating —
    // stash/restore the pending exception around the cleanup setattrs
    // (calling the attribute API with an exception set is not allowed)
    {
      PyObject *p_type = nullptr, *p_val = nullptr, *p_tb = nullptr;
      if (PyErr_Occurred()) PyErr_Fetch(&p_type, &p_val, &p_tb);
      if (PyObject_SetAttr(executor, s_running_task, Py_None) < 0 ||
          PyObject_SetAttr(ctx, s_current_task, prev_task) < 0) {
        poll_failed = 1;
        if (p_type) PyErr_Clear();  // original exception wins
      }
      if (p_type) PyErr_Restore(p_type, p_val, p_tb);
    }
    Py_DECREF(prev_task);
    Py_DECREF(node);

    if (!poll_failed) {
      // deferred self-cancellation (task.cancel() from inside the task)
      int kill_req = attr_truth(task, s_kill_requested);
      int fin2 = kill_req < 0 ? -1 : attr_truth(task, s_finished);
      if (kill_req < 0 || fin2 < 0) {
        poll_failed = 1;
      } else if (kill_req && !fin2) {
        if (PyObject_SetAttr(task, s_kill_requested, Py_False) < 0) {
          poll_failed = 1;
        } else {
          PyObject* r = PyObject_CallMethodNoArgs(task, s_close_priv);
          if (!r) poll_failed = 1;
          Py_XDECREF(r);
        }
      }
    }
    Py_DECREF(task);
    if (poll_failed) break;

    // stop draining on panic — BEFORE the advance draw (Python parity)
    PyObject* panic = PyObject_GetAttr(executor, s_panic);
    if (!panic) break;
    int has_panic = panic != Py_None;
    Py_DECREF(panic);
    if (has_panic) {
      ok = 1;
      break;
    }
    // Virtual time advances 50-100 ns per poll (reference :319-321).
    timec->now_ns += rng_range(rng, 50, 101);
  }

  Py_DECREF(ready);
  return ok ? 0 : -1;
}

static PyObject* host_run_all_ready(PyObject*, PyObject* args) {
  PyObject *executor, *ctx, *rng_o, *time_o;
  if (!PyArg_ParseTuple(args, "OOO!O!", &executor, &ctx, &RngType, &rng_o,
                        &TimeCoreType, &time_o)) {
    return nullptr;
  }
  if (run_ready_impl(executor, ctx, reinterpret_cast<RngObject*>(rng_o),
                     reinterpret_cast<TimeCoreObject*>(time_o)) < 0) {
    return nullptr;
  }
  Py_RETURN_NONE;
}

// drive(executor, ctx, rng, time_core, main_task) -> int
//
// The full Executor.block_on inner loop (reference: sim/task/mod.rs:220-260)
// natively: drain ready queue, then jump to the next timer; repeat.
// Return codes (the Python side raises accordingly):
//   0 = main task finished    1 = panic set
//   2 = time limit hit        3 = deadlock (no timers pending)
//   4 = draw-log check mismatch (native check mode, sim/rand.rs:65-90)
static PyObject* host_drive(PyObject*, PyObject* args) {
  PyObject *executor, *ctx, *rng_o, *time_o, *main_task;
  if (!PyArg_ParseTuple(args, "OOO!O!O", &executor, &ctx, &RngType, &rng_o,
                        &TimeCoreType, &time_o, &main_task)) {
    return nullptr;
  }
  RngObject* rng = reinterpret_cast<RngObject*>(rng_o);
  TimeCoreObject* timec = reinterpret_cast<TimeCoreObject*>(time_o);
  while (true) {
    if (run_ready_impl(executor, ctx, rng, timec) < 0) return nullptr;
    if (rng->observe_mode == OBS_CHECK && rng->mismatch_index >= 0) {
      return PyLong_FromLong(4);
    }
    PyObject* panic = PyObject_GetAttr(executor, s_panic);
    if (!panic) return nullptr;
    int has_panic = panic != Py_None;
    Py_DECREF(panic);
    if (has_panic) return PyLong_FromLong(1);
    int fin = attr_truth(main_task, s_finished);
    if (fin < 0) return nullptr;
    if (fin) return PyLong_FromLong(0);
    int limit = attr_truth(executor, s_time_limit_hit);
    if (limit < 0) return nullptr;
    if (limit) return PyLong_FromLong(2);
    int rc = advance_next(timec);
    if (rc < 0) return nullptr;
    if (rc == 0) return PyLong_FromLong(3);
  }
}

// ---------------------------------------------------------------------------
// philox_fill — bulk block generation (kept for GlobalRng fallback + tests)
// ---------------------------------------------------------------------------

static PyObject* host_philox_fill(PyObject*, PyObject* args) {
  unsigned long k0, k1;
  unsigned long long start_block, nblocks;
  if (!PyArg_ParseTuple(args, "kkKK", &k0, &k1, &start_block, &nblocks)) {
    return nullptr;
  }
  PyObject* out = PyList_New(static_cast<Py_ssize_t>(4 * nblocks));
  if (!out) return nullptr;
  uint32_t words[4];
  for (unsigned long long i = 0; i < nblocks; ++i) {
    unsigned long long block = start_block + i;
    philox_block(static_cast<uint32_t>(k0), static_cast<uint32_t>(k1),
                 static_cast<uint32_t>(block),
                 static_cast<uint32_t>(block >> 32), 0u, 0u, words);
    for (int w = 0; w < 4; ++w) {
      PyObject* v = PyLong_FromUnsignedLong(words[w]);
      if (!v) { Py_DECREF(out); return nullptr; }
      PyList_SET_ITEM(out, static_cast<Py_ssize_t>(4 * i + w), v);
    }
  }
  return out;
}

static PyMethodDef module_methods[] = {
    {"run_all_ready", host_run_all_ready, METH_VARARGS,
     "run_all_ready(executor, ctx, rng, time_core) — native poll loop"},
    {"drive", host_drive, METH_VARARGS,
     "drive(executor, ctx, rng, time_core, main_task) -> outcome code"},
    {"philox_fill", host_philox_fill, METH_VARARGS,
     "philox_fill(k0, k1, start_block, nblocks) -> list of 4*n uint32"},
    {nullptr, nullptr, 0, nullptr},
};

static struct PyModuleDef hostcore_module = {
    PyModuleDef_HEAD_INIT, "hostcore",
    "native hot paths for the madsim_tpu host engine", -1, module_methods,
};

}  // namespace

PyMODINIT_FUNC PyInit_hostcore(void) {
  RngType.tp_flags = Py_TPFLAGS_DEFAULT | Py_TPFLAGS_HAVE_GC;
  RngType.tp_new = Rng_new;
  RngType.tp_dealloc = Rng_dealloc;
  RngType.tp_traverse = Rng_traverse;
  RngType.tp_clear = Rng_clear_gc;
  RngType.tp_methods = Rng_methods;
  RngType.tp_doc = "buffered Philox4x32-10 draw stream";
  if (PyType_Ready(&RngType) < 0) return nullptr;

  TimeCoreType.tp_flags = Py_TPFLAGS_DEFAULT | Py_TPFLAGS_HAVE_GC;
  TimeCoreType.tp_new = TimeCore_new;
  TimeCoreType.tp_dealloc = TimeCore_dealloc;
  TimeCoreType.tp_traverse = TimeCore_traverse;
  TimeCoreType.tp_clear = TimeCore_clear_gc;
  TimeCoreType.tp_methods = TimeCore_methods;
  TimeCoreType.tp_as_sequence = &TimeCore_as_sequence;
  TimeCoreType.tp_doc = "virtual clock + (deadline, seq) timer heap";
  if (PyType_Ready(&TimeCoreType) < 0) return nullptr;

  TaskWakerType.tp_flags = Py_TPFLAGS_DEFAULT | Py_TPFLAGS_HAVE_GC;
  TaskWakerType.tp_new = TaskWaker_new;
  TaskWakerType.tp_dealloc = TaskWaker_dealloc;
  TaskWakerType.tp_traverse = TaskWaker_traverse;
  TaskWakerType.tp_clear = TaskWaker_clear;
  TaskWakerType.tp_call = TaskWaker_call;
  TaskWakerType.tp_doc = "per-task wake callable (schedule into ready)";
  if (PyType_Ready(&TaskWakerType) < 0) return nullptr;

  AwaitIterType.tp_flags = Py_TPFLAGS_DEFAULT | Py_TPFLAGS_HAVE_GC;
  // am_await = self: `await await_(p)` can use the AwaitIter DIRECTLY
  // as the awaitable, skipping the Python _Await wrapper per await
  static PyAsyncMethods await_iter_async = {PyObject_SelfIter, nullptr,
                                            nullptr, nullptr};
  AwaitIterType.tp_as_async = &await_iter_async;
  AwaitIterType.tp_new = AwaitIter_new;
  AwaitIterType.tp_dealloc = AwaitIter_dealloc;
  AwaitIterType.tp_traverse = AwaitIter_traverse;
  AwaitIterType.tp_clear = AwaitIter_clear_gc;
  AwaitIterType.tp_iter = PyObject_SelfIter;
  AwaitIterType.tp_iternext = AwaitIter_next;
  AwaitIterType.tp_methods = AwaitIter_methods;
  AwaitIterType.tp_doc = "native __await__ iterator over a Pollable";
  if (PyType_Ready(&AwaitIterType) < 0) return nullptr;

  SleepGateType.tp_flags = Py_TPFLAGS_DEFAULT | Py_TPFLAGS_HAVE_GC;
  SleepGateType.tp_new = SleepGate_new;
  SleepGateType.tp_dealloc = SleepGate_dealloc;
  SleepGateType.tp_traverse = SleepGate_traverse;
  SleepGateType.tp_clear = SleepGate_clear_gc;
  SleepGateType.tp_methods = SleepGate_methods;
  SleepGateType.tp_getset = SleepGate_getset;
  SleepGateType.tp_doc = "sleep pollable with a native poll";
  if (PyType_Ready(&SleepGateType) < 0) return nullptr;

  MailboxType.tp_flags = Py_TPFLAGS_DEFAULT | Py_TPFLAGS_HAVE_GC;
  MailboxType.tp_new = Mailbox_new;
  MailboxType.tp_dealloc = Mailbox_dealloc;
  MailboxType.tp_traverse = Mailbox_traverse;
  MailboxType.tp_clear = Mailbox_clear_gc;
  MailboxType.tp_methods = Mailbox_methods;
  MailboxType.tp_doc = "tag-matched mailbox (reference: endpoint.rs:298-352)";
  if (PyType_Ready(&MailboxType) < 0) return nullptr;

  MailRecvType.tp_flags = Py_TPFLAGS_DEFAULT | Py_TPFLAGS_HAVE_GC;
  MailRecvType.tp_dealloc = MailRecv_dealloc;
  MailRecvType.tp_traverse = MailRecv_traverse;
  MailRecvType.tp_clear = MailRecv_clear_gc;
  MailRecvType.tp_methods = MailRecv_methods;
  MailRecvType.tp_doc = "pending tag receive (Pollable)";
  if (PyType_Ready(&MailRecvType) < 0) return nullptr;

  RecvDeadlineType.tp_flags = Py_TPFLAGS_DEFAULT | Py_TPFLAGS_HAVE_GC;
  RecvDeadlineType.tp_new = RecvDeadline_new;
  RecvDeadlineType.tp_dealloc = RecvDeadline_dealloc;
  RecvDeadlineType.tp_traverse = RecvDeadline_traverse;
  RecvDeadlineType.tp_clear = RecvDeadline_clear_gc;
  RecvDeadlineType.tp_methods = RecvDeadline_methods;
  RecvDeadlineType.tp_doc = "fused recv-with-deadline pollable (RPC wait)";
  if (PyType_Ready(&RecvDeadlineType) < 0) return nullptr;

  NetCoreType.tp_flags = Py_TPFLAGS_DEFAULT | Py_TPFLAGS_HAVE_GC;
  NetCoreType.tp_new = NetCore_new;
  NetCoreType.tp_dealloc = NetCore_dealloc;
  NetCoreType.tp_traverse = NetCore_traverse;
  NetCoreType.tp_clear = NetCore_clear_gc;
  NetCoreType.tp_methods = NetCore_methods;
  NetCoreType.tp_doc = "native datagram send/wire/delivery hot path";
  if (PyType_Ready(&NetCoreType) < 0) return nullptr;

  PendingSendType.tp_flags = Py_TPFLAGS_DEFAULT | Py_TPFLAGS_HAVE_GC;
  PendingSendType.tp_dealloc = PendingSend_dealloc;
  PendingSendType.tp_traverse = PendingSend_traverse;
  PendingSendType.tp_doc = "scheduled datagram wire moment";
  if (PyType_Ready(&PendingSendType) < 0) return nullptr;

  PendingDeliverType.tp_flags = Py_TPFLAGS_DEFAULT | Py_TPFLAGS_HAVE_GC;
  PendingDeliverType.tp_dealloc = PendingDeliver_dealloc;
  PendingDeliverType.tp_traverse = PendingDeliver_traverse;
  PendingDeliverType.tp_doc = "scheduled datagram delivery";
  if (PyType_Ready(&PendingDeliverType) < 0) return nullptr;

  g_ip_loopback = PyUnicode_InternFromString("127.0.0.1");
  g_ip_zero = PyUnicode_InternFromString("0.0.0.0");
  g_rpc_req_str = PyUnicode_InternFromString("rpc_req");
  if (!g_ip_loopback || !g_ip_zero || !g_rpc_req_str) return nullptr;

#define INTERN(var, name)                     \
  var = PyUnicode_InternFromString(name);     \
  if (!var) return nullptr;
  INTERN(s_tag, "tag")
  INTERN(s_time_limit_hit, "_time_limit_hit")
  INTERN(s_waker, "waker")
  INTERN(s_pending_on, "pending_on")
  INTERN(s_poll, "poll")
  INTERN(s_value, "value")
  INTERN(s_drop, "drop")
  INTERN(s_ready, "ready")
  INTERN(s_scheduled, "scheduled")
  INTERN(s_finished, "finished")
  INTERN(s_kill_requested, "kill_requested")
  INTERN(s_node, "node")
  INTERN(s_coro, "coro")
  INTERN(s_cell, "cell")
  INTERN(s_killed, "killed")
  INTERN(s_paused, "paused")
  INTERN(s_paused_tasks, "paused_tasks")
  INTERN(s_tasks, "tasks")
  INTERN(s_discard, "discard")
  INTERN(s_set, "set")
  INTERN(s_close_priv, "_close")
  INTERN(s_current_task, "current_task")
  INTERN(s_running_task, "running_task")
  INTERN(s_panic, "panic")
  INTERN(s_handle_panic, "_handle_panic")
  INTERN(s_buggify_enabled, "buggify_enabled")
  INTERN(s_send_phase2, "_send_phase2")
  INTERN(s_deliver_m, "deliver")
  INTERN(s_executor, "executor")
  INTERN(s_msg_count, "msg_count")
  INTERN(s_packet_loss_rate, "packet_loss_rate")
  INTERN(s_lat_min, "send_latency_min_ns")
  INTERN(s_lat_max, "send_latency_max_ns")
  INTERN(s_spike_prob, "delay_spike_prob")
  INTERN(s_spike_min, "delay_spike_min_ns")
  INTERN(s_spike_max, "delay_spike_max_ns")
#undef INTERN

  PyObject* m = PyModule_Create(&hostcore_module);
  if (!m) return nullptr;
  Py_INCREF(&RngType);
  if (PyModule_AddObject(m, "Rng", reinterpret_cast<PyObject*>(&RngType)) < 0) {
    Py_DECREF(&RngType);
    Py_DECREF(m);
    return nullptr;
  }
  Py_INCREF(&TimeCoreType);
  if (PyModule_AddObject(m, "TimeCore",
                         reinterpret_cast<PyObject*>(&TimeCoreType)) < 0) {
    Py_DECREF(&TimeCoreType);
    Py_DECREF(m);
    return nullptr;
  }
  Py_INCREF(&TaskWakerType);
  if (PyModule_AddObject(m, "TaskWaker",
                         reinterpret_cast<PyObject*>(&TaskWakerType)) < 0) {
    Py_DECREF(&TaskWakerType);
    Py_DECREF(m);
    return nullptr;
  }
  Py_INCREF(&RecvDeadlineType);
  if (PyModule_AddObject(m, "RecvDeadline",
                         reinterpret_cast<PyObject*>(&RecvDeadlineType)) < 0) {
    Py_DECREF(&RecvDeadlineType);
    Py_DECREF(m);
    return nullptr;
  }
  Py_INCREF(&NetCoreType);
  if (PyModule_AddObject(m, "NetCore",
                         reinterpret_cast<PyObject*>(&NetCoreType)) < 0) {
    Py_DECREF(&NetCoreType);
    Py_DECREF(m);
    return nullptr;
  }
  Py_INCREF(&MailboxType);
  if (PyModule_AddObject(m, "Mailbox",
                         reinterpret_cast<PyObject*>(&MailboxType)) < 0) {
    Py_DECREF(&MailboxType);
    Py_DECREF(m);
    return nullptr;
  }
  Py_INCREF(&AwaitIterType);
  if (PyModule_AddObject(m, "AwaitIter",
                         reinterpret_cast<PyObject*>(&AwaitIterType)) < 0) {
    Py_DECREF(&AwaitIterType);
    Py_DECREF(m);
    return nullptr;
  }
  Py_INCREF(&SleepGateType);
  if (PyModule_AddObject(m, "SleepGate",
                         reinterpret_cast<PyObject*>(&SleepGateType)) < 0) {
    Py_DECREF(&SleepGateType);
    Py_DECREF(m);
    return nullptr;
  }
  return m;
}
