"""Native core loader — builds hostcore.cc (a CPython extension) with g++
on first use.

The reference runtime is native Rust; here the host engine's hot inner
loops (Philox RNG, the virtual clock + timer heap, and the executor's
random-order poll loop) run in C++ as a real extension module — method
calls cost nanoseconds, not the microseconds of a ctypes round trip.
Everything degrades to pure Python with identical semantics when no
toolchain is available (`MADSIM_TPU_NO_NATIVE=1` forces the fallback);
bit-identity between the two paths is asserted by tests/test_native.py.
"""

from __future__ import annotations

import hashlib
import importlib.machinery
import importlib.util
import os
import subprocess
import sysconfig
from typing import Any, List, Optional

_HERE = os.path.dirname(__file__)
_SRC = os.path.join(_HERE, "hostcore.cc")

_mod: Optional[Any] = None
_tried = False


def _build_and_load() -> Optional[Any]:
    if os.environ.get("MADSIM_TPU_NO_NATIVE"):
        return None
    try:
        with open(_SRC, "rb") as f:
            digest = hashlib.sha256(f.read()).hexdigest()[:16]
        # key the cache by the interpreter ABI too — the extension links
        # against Python.h internals, so a stale .so from another Python
        # version must trigger a rebuild, not a segfault
        abi = sysconfig.get_config_var("SOABI") or "abi3"
        so_path = os.path.join(_HERE, f"hostcore-{digest}-{abi}.so")
        if not os.path.exists(so_path):
            tmp = f"{so_path}.{os.getpid()}.tmp"  # unique: concurrent builders don't clobber
            include = sysconfig.get_paths()["include"]
            subprocess.run(
                [
                    "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                    f"-I{include}", "-o", tmp, _SRC,
                ],
                check=True,
                capture_output=True,
            )
            os.replace(tmp, so_path)
        loader = importlib.machinery.ExtensionFileLoader("hostcore", so_path)
        spec = importlib.util.spec_from_file_location("hostcore", so_path, loader=loader)
        assert spec is not None
        mod = importlib.util.module_from_spec(spec)
        loader.exec_module(mod)
        return mod
    except Exception:  # noqa: BLE001 - no toolchain / build failure: fall back
        return None


def get_mod() -> Optional[Any]:
    global _mod, _tried
    if not _tried:
        _mod = _build_and_load()
        _tried = True
    return _mod


def available() -> bool:
    return get_mod() is not None


def philox_fill(k0: int, k1: int, start_block: int, nblocks: int) -> List[int]:
    """nblocks philox blocks as a flat list of 4*nblocks uint32 words —
    bit-identical to repeated rand/philox.py `philox4x32` calls."""
    mod = get_mod()
    assert mod is not None
    return mod.philox_fill(k0, k1, start_block, nblocks)


def make_rng(k0: int, k1: int):
    """A native buffered Philox draw stream (see hostcore.Rng)."""
    mod = get_mod()
    assert mod is not None
    return mod.Rng(k0, k1)


def make_time_core():
    """The native virtual clock + timer heap (see hostcore.TimeCore)."""
    mod = get_mod()
    assert mod is not None
    return mod.TimeCore()


def run_all_ready(executor, ctx, rng_core, time_core) -> None:
    """The native executor poll loop (see hostcore.run_all_ready)."""
    mod = get_mod()
    assert mod is not None
    mod.run_all_ready(executor, ctx, rng_core, time_core)
