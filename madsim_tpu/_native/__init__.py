"""Native core loader — builds simcore.cc with g++ on first use.

The reference runtime is native Rust; here the host engine's hot inner
loops (bulk Philox generation, the timer heap) run in C++ via ctypes.
Everything degrades to pure Python with identical semantics when no
toolchain is available (`MADSIM_TPU_NO_NATIVE=1` forces the fallback).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
from typing import List, Optional, Tuple

_HERE = os.path.dirname(__file__)
_SRC = os.path.join(_HERE, "simcore.cc")

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build_and_load() -> Optional[ctypes.CDLL]:
    if os.environ.get("MADSIM_TPU_NO_NATIVE"):
        return None
    try:
        with open(_SRC, "rb") as f:
            digest = hashlib.sha256(f.read()).hexdigest()[:16]
        so_path = os.path.join(_HERE, f"simcore-{digest}.so")
        if not os.path.exists(so_path):
            tmp = f"{so_path}.{os.getpid()}.tmp"  # unique: concurrent builders don't clobber
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-o", tmp, _SRC],
                check=True,
                capture_output=True,
            )
            os.replace(tmp, so_path)
        lib = ctypes.CDLL(so_path)
        lib.philox_fill.argtypes = [
            ctypes.c_uint32,
            ctypes.c_uint32,
            ctypes.c_uint64,
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint32),
        ]
        lib.timer_new.restype = ctypes.c_void_p
        lib.timer_free.argtypes = [ctypes.c_void_p]
        lib.timer_push.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_uint64]
        lib.timer_pop.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.timer_pop.restype = ctypes.c_int
        lib.timer_peek.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64)]
        lib.timer_peek.restype = ctypes.c_int
        lib.timer_len.argtypes = [ctypes.c_void_p]
        lib.timer_len.restype = ctypes.c_uint64
        return lib
    except Exception:  # noqa: BLE001 - no toolchain / build failure: fall back
        return None


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if not _tried:
        _lib = _build_and_load()
        _tried = True
    return _lib


def available() -> bool:
    return get_lib() is not None


def philox_fill(k0: int, k1: int, start_block: int, nblocks: int) -> List[int]:
    """nblocks philox blocks as a flat list of 4*nblocks uint32 words —
    bit-identical to repeated rand/philox.py `philox4x32` calls."""
    lib = get_lib()
    assert lib is not None
    buf = (ctypes.c_uint32 * (4 * nblocks))()
    lib.philox_fill(k0, k1, start_block, nblocks, buf)
    return list(buf)


class NativeTimerHeap:
    """(deadline, seq)-ordered timer heap with integer ids; the Python
    side keeps id -> callback."""

    __slots__ = ("_lib", "_h")

    def __init__(self) -> None:
        self._lib = get_lib()
        assert self._lib is not None
        self._h = self._lib.timer_new()

    def push(self, deadline: int, seq: int) -> None:
        self._lib.timer_push(self._h, deadline, seq)

    def pop(self) -> Optional[Tuple[int, int]]:
        """(deadline, seq) of the earliest timer, or None."""
        deadline = ctypes.c_int64()
        seq = ctypes.c_uint64()
        if not self._lib.timer_pop(self._h, ctypes.byref(deadline), ctypes.byref(seq)):
            return None
        return deadline.value, seq.value

    def peek_deadline(self) -> Optional[int]:
        deadline = ctypes.c_int64()
        if not self._lib.timer_peek(self._h, ctypes.byref(deadline)):
            return None
        return deadline.value

    def __len__(self) -> int:
        return self._lib.timer_len(self._h)

    def __del__(self) -> None:  # noqa: D105 - freeing native memory only
        lib = getattr(self, "_lib", None)
        if lib is not None:
            lib.timer_free(self._h)
