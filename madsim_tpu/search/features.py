"""Candidate-seed schedule features — the host-side peek at what a
seed WOULD do.

The determinism contract makes guided selection cheap: a lane's fault
schedule is a pure function of (seed, FaultPlan), derived by the same
`init_lane` code the device executes. So the bias layer can score a
whole candidate pool without running a single simulation — one vmapped
jitted slice of `init_lane` over the candidate seed vector returns
every candidate's drawn (kind, apply-time, target) triples, bit-equal
to what those seeds would run (the same derivation
`engine/provenance.py` uses to decode lineage words, vectorized).

Cached on the machine object like the provenance/compiled-replay
caches: guided hunts build several escalated Engines over one machine,
and each (FaultPlan, queue, stream-version) pairing compiles its
feature slice once.
"""

from __future__ import annotations

from typing import Dict, Sequence

import numpy as np


def _feats_fn(engine):
    import jax

    cache = engine.machine.__dict__.setdefault("_search_feats_cache", {})
    key = (engine.config.faults, engine.config.queue_capacity,
           engine.config.rng_stream)
    if key not in cache:
        n = engine.machine.NUM_NODES
        fp = engine.config.faults
        lo, hi = n, n + fp.slots_per_fault * fp.n_faults

        def feats(seeds):
            def one(seed):
                s = engine.init_lane(seed)
                return (
                    s.eq_time[lo:hi], s.eq_payload[lo:hi, 0],
                    s.eq_payload[lo:hi, 1],
                )

            return jax.vmap(one)(seeds)

        cache[key] = jax.jit(feats)
    return cache[key]


def schedule_features(engine, seeds: Sequence[int]) -> Dict[str, np.ndarray]:
    """Per-seed fault-schedule features for a seed vector: int arrays
    of shape [len(seeds), n_faults] — "kinds" (K_* indices), "t_apply"
    (virtual us), "targets" (payload arg1: the node / pair-a / mask-lo
    the fault lands on). Empty [n, 0] arrays when the plan schedules
    no faults (guidance then has nothing to score — selection falls
    back to the first candidate)."""
    import jax.numpy as jnp

    fp = engine.config.faults
    n_seeds = len(seeds)
    if fp.n_faults == 0 or n_seeds == 0:
        empty = np.zeros((n_seeds, 0), np.int32)
        return {"kinds": empty, "t_apply": empty, "targets": empty}
    times, ops, args1 = _feats_fn(engine)(
        jnp.asarray(list(seeds), jnp.uint32)
    )
    times, ops, args1 = (np.asarray(x) for x in (times, ops, args1))
    spf = fp.slots_per_fault
    apply_slots = np.arange(fp.n_faults) * spf
    return {
        # the apply slot's op encodes the kind: op = 2*kind (+1 = undo)
        "kinds": (ops[:, apply_slots] // 2).astype(np.int32),
        "t_apply": times[:, apply_slots].astype(np.int32),
        "targets": args1[:, apply_slots].astype(np.int32),
    }


def kind_name_rows(engine, kinds: np.ndarray) -> list:
    """Map a [n, F] kind-index array to per-seed kind-name tuples (the
    shape `BiasState.score_kinds` consumes)."""
    from ..engine.core import FAULT_KIND_NAMES

    return [
        tuple(FAULT_KIND_NAMES[int(k)] for k in row) for row in kinds
    ]
