"""Deterministic seed mutation — the AFL "havoc" stage, shrunk to ints.

A lane's entire behavior is a pure function of its uint32 seed (the
determinism contract), so a "mutation" of a parent scenario is just a
deterministically derived child seed. The mutator below is keyed off
(parent seed, operator, batch, slot, candidate): the same guided hunt
always proposes the same children in the same order, which is what lets
a checkpointed hunt resume — or replay on a replacement fleet worker —
and produce a byte-identical seed schedule.

Children are derived with a splitmix32-style avalanche mix, so a child
schedule shares no structure with its parent; the *guidance* comes from
the selection layer (`search/bias.py` scores every candidate's
re-derived fault schedule and keeps the one the bias state likes).
Operator ids exist so the selection layer can label what a chosen child
actually changed relative to its parent (kind flip / delay-era nudge /
target rotation) — the labels feed the recorded trail, not the RNG.

Pure stdlib integer arithmetic: no jax, no numpy, no floats.
"""

from __future__ import annotations

from typing import List

_M32 = 0xFFFFFFFF

#: mutation operators — labels for the candidate streams. Each operator
#: salts the mix differently, so the three streams never collide for
#: one parent; what a chosen child *did* (vs its parent's schedule) is
#: classified after the fact by `classify_child`.
OP_KIND_FLIP = 0      # aim: a schedule drawing different fault kinds
OP_DELAY_NUDGE = 1    # aim: same kinds, shifted fault eras
OP_TARGET_ROTATE = 2  # aim: same kinds/eras, different target nodes
OP_NAMES = ("kind-flip", "delay-nudge", "target-rotate")


def mix32(x: int, salt: int) -> int:
    """Deterministic 32-bit avalanche (splitmix32 finalizer over
    x + golden-ratio * (salt+1)). Pinned by fixtures in
    tests/test_search.py — changing these constants re-keys every
    recorded guided seed schedule, so don't."""
    z = (x + ((salt + 1) * 0x9E3779B9)) & _M32
    z = ((z ^ (z >> 16)) * 0x85EBCA6B) & _M32
    z = ((z ^ (z >> 13)) * 0xC2B2AE35) & _M32
    return (z ^ (z >> 16)) & _M32


def child_seed(parent: int, op: int, batch: int, slot: int, cand: int) -> int:
    """The candidate seed for (parent, operator, batch, slot, cand).
    One mix per coordinate keeps every stream independent; the final
    value is a full-entropy uint32, never 0 (seed 0 is the conventional
    sequential-scan origin — keep mutants out of its way)."""
    z = mix32(parent & _M32, op)
    z = mix32(z ^ (batch & _M32), 3 + slot)
    z = mix32(z, 7 + cand)
    return z or 1


def children(parent: int, batch: int, slot: int, per_op: int = 1) -> List[tuple]:
    """All candidate (op, seed) pairs for one corpus parent at one
    batch slot, operator-major, deterministic order."""
    out = []
    for op in (OP_KIND_FLIP, OP_DELAY_NUDGE, OP_TARGET_ROTATE):
        for c in range(per_op):
            out.append((op, child_seed(parent, op, batch, slot, c)))
    return out


def classify_child(parent_feats: dict, child_feats: dict) -> str:
    """Label what a chosen child actually changed relative to its
    parent, from the two re-derived schedules (`search/features.py`
    dicts with "kinds" / "t_apply" / "targets" int lists). Purely
    descriptive — feeds the recorded trail so operators can see which
    mutation classes are paying."""
    if tuple(parent_feats["kinds"]) != tuple(child_feats["kinds"]):
        return OP_NAMES[OP_KIND_FLIP]
    if tuple(parent_feats["t_apply"]) != tuple(child_feats["t_apply"]):
        return OP_NAMES[OP_DELAY_NUDGE]
    return OP_NAMES[OP_TARGET_ROTATE]
