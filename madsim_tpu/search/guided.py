"""The guided hunt — coverage-feedback seed evolution over the batch
loop.

`run_guided(eng, args)` is the `--guided` twin of
`__main__._stream_batches`: same aggregate shape, same checkpoint
file, same StatsEmitter feed, same plateau detector — but instead of
streaming a flat sequential seed range, every batch's seed vector is
CHOSEN:

  * batch 0 bootstraps sequentially (no signal yet);
  * afterwards, half of each batch are mutated children of the live
    seed corpus (parents = seeds that hit new coverage slots), picked
    from three deterministic candidate streams per slot
    (`search/mutate.py`) by scoring each candidate's re-derived fault
    schedule against the bias state (`search/bias.py` x
    `search/features.py`); the other half stays fresh sequential
    exploration;
  * between batches the bias state folds in the live map's per-band
    marginals and the harvested `fail_prov` lineage words;
  * a coverage plateau escalates the fault vocabulary along the
    recorded ladder (new Engine per rung, shared machine/caches)
    instead of stopping; the ladder exhausting is the honest plateau.

Reproducibility contract: the run is completely described by the
(seed schedule, bias state) trail — both are recorded per batch in the
aggregate, the checkpoint and the fleet job result. Guidance is pure
host-side seed *selection*: the in-kernel RNG layout is untouched, so
every chosen seed replays exactly like a hand-typed `--seed N`, and a
hunt interrupted at any batch boundary (or resumed by a replacement
fleet worker) recomputes the identical schedule from the checkpoint.

The per-batch engine runs the explicit seed vector through
`Engine.run_seed_batch` (one lane per seed): guidance-off keeps the
streaming executor path byte-for-byte untouched.
"""

from __future__ import annotations

# madsim: allow-file(D001) — wall-clock reads here go through the
# `import time as wall` alias and only measure host throughput
# (seeds/s heartbeats, elapsed_s); nothing feeds simulation state or
# the seed schedule, which is a pure function of (checkpointed) search
# state. Same contract as __main__'s batch loop.
import dataclasses
import logging
import sys
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..kinds import CLI_KIND_TO_FLAG, FAULT_KIND_NAMES, KIND_BY_FLAG
from .bias import BiasState, band_fractions_from_coverage, vocabulary_for
from .features import kind_name_rows, schedule_features
from .mutate import OP_NAMES, children, classify_child

#: fraction of each post-bootstrap batch drawn from mutated corpus
#: children (the rest stays fresh sequential exploration, so guidance
#: can never starve the unexplored seed line)
MUTANT_FRAC = 0.5
#: corpus parents retained (FIFO) — bounds checkpoint size
MAX_PARENTS = 256
#: provenance word bits (mirrors engine/provenance.py: bit min(f, 29)
#: = scheduled fault f, 30 = amnesia wipe, 31 = duplicate delivery)
_PROV_FAULT_BITS = 30
_PROV_BIT_AMNESIA = 30
_PROV_BIT_DUP = 31


def base_kind_names(fp) -> Tuple[str, ...]:
    """A FaultPlan's vocabulary as CLI kind names (the inverse of
    `__main__._fault_kind_flags`, shared table)."""
    return tuple(
        name for name, field in CLI_KIND_TO_FLAG if getattr(fp, field)
    )


def engine_for_escalation(base_eng, escalation: int):
    """The Engine for escalation step `escalation` of a guided hunt
    whose base engine is `base_eng`: same machine (schedule-feature /
    compiled-replay caches accrue), same gates, fault vocabulary =
    base union ladder rung. Step 0 returns the base engine itself.
    Raises ValueError when the rung's vocabulary cannot be built for
    this machine (e.g. torn without a durable_spec) — the guided loop
    skips such rungs."""
    if escalation == 0:
        return base_eng
    cache = base_eng.machine.__dict__.setdefault("_guided_engine_cache", {})
    key = (base_eng.config, escalation)
    if key in cache:
        return cache[key]
    from ..engine.core import Engine

    vocab = set(vocabulary_for(base_kind_names(base_eng.config.faults),
                               escalation))
    fp = dataclasses.replace(
        base_eng.config.faults,
        **{field: name in vocab for name, field in CLI_KIND_TO_FLAG},
    )
    eng = Engine(base_eng.machine, dataclasses.replace(
        base_eng.config, faults=fp
    ))
    cache[key] = eng
    return eng


def _prov_kind_counts(eng, feats_kinds: np.ndarray,
                      words: List[int]) -> Dict[str, int]:
    """Per-kind lineage-implication counts for one batch's finds,
    decoded from the harvested provenance words against the seeds'
    re-derived schedules (vectorized twin of
    `engine/provenance.kind_counts`; a find counts once per kind)."""
    counts: Dict[str, int] = {}
    n_faults = feats_kinds.shape[1]
    for row, word in zip(feats_kinds, words):
        kinds = set()
        for f in range(n_faults):
            if (word >> min(f, _PROV_FAULT_BITS - 1)) & 1:
                kinds.add(FAULT_KIND_NAMES[int(row[f])])
        if (word >> _PROV_BIT_AMNESIA) & 1:
            kinds.add("strict-restart")
        if (word >> _PROV_BIT_DUP) & 1:
            kinds.add("dup")
        for k in kinds:
            counts[k] = counts.get(k, 0) + 1
    return counts


def _select_batch(
    bias: BiasState,
    eng,
    parents: List[int],
    seen: set,
    cursor: int,
    batch_index: int,
    chunk: int,
) -> Tuple[List[int], int, int, Dict[str, int]]:
    """Choose one batch's seed vector. Pure function of its arguments
    (the whole resumable selection state), so a checkpoint resume
    re-derives the identical schedule. Returns (seeds, new_cursor,
    n_mutants, op_label_counts)."""
    seeds: List[int] = []
    op_counts: Dict[str, int] = {}
    n_mut = 0
    if parents and batch_index > 0:
        want_mut = int(chunk * MUTANT_FRAC)
        slots = [
            (j, parents[j % len(parents)]) for j in range(want_mut)
        ]
        # one vectorized feature pass over every candidate AND parent
        cands = [
            children(parent, batch_index, j) for j, parent in slots
        ]
        flat = [s for group in cands for _op, s in group]
        uniq_parents = sorted(set(p for _j, p in slots))
        feats = schedule_features(eng, flat + uniq_parents)
        names = kind_name_rows(eng, feats["kinds"])
        parent_row = {
            p: len(flat) + i for i, p in enumerate(uniq_parents)
        }
        per_slot = len(cands[0]) if cands else 0
        for si, (j, parent) in enumerate(slots):
            best = None  # (score, order) -> candidate index
            for ci in range(per_slot):
                fi = si * per_slot + ci
                seed = cands[si][ci][1]
                if seed in seen or seed in seeds:
                    continue
                score = bias.score_kinds(names[fi])
                if best is None or score > best[0]:
                    best = (score, fi, seed)
            if best is None:
                continue  # every candidate already ran: leave to fresh
            _score, fi, seed = best
            seeds.append(seed)
            n_mut += 1
            pi = parent_row[parent]
            label = classify_child(
                {k: feats[k][pi] for k in ("kinds", "t_apply", "targets")},
                {k: feats[k][fi] for k in ("kinds", "t_apply", "targets")},
            ) if feats["kinds"].shape[1] else OP_NAMES[0]
            op_counts[label] = op_counts.get(label, 0) + 1
    # fresh sequential exploration fills the rest (skipping anything a
    # mutant already claimed)
    while len(seeds) < chunk:
        if cursor not in seen and cursor not in seeds:
            seeds.append(cursor)
        cursor += 1
    return seeds, cursor, n_mut, op_counts


def _guided_heartbeat(bi, planned, completed, n_mut, el, slots_hit,
                      new_slots, failing, escalation, vocab,
                      device_count=1, escalated_to=None):
    """The guided per-batch heartbeat line (format pinned in tests):
    like the unguided one it names the device count the unit spanned,
    plus the mutation tally, coverage delta and the escalation rung the
    batch RAN under."""
    tail = f" -> escalated to step {escalated_to}" if escalated_to else ""
    return (
        f"guided batch {bi}/{planned}: {completed} seeds ({n_mut} mutants) "
        f"in {el:.1f}s ({completed / el:.0f} seeds/s) on {device_count} "
        f"device(s), coverage {slots_hit} slots (+{new_slots}), "
        f"{failing} failing so far, escalation {escalation} "
        f"[{','.join(vocab)}]{tail}"
    )


def run_guided(eng, args, purpose: str = "hunt") -> dict:
    """The guided batch loop. `eng` is the base (escalation step 0)
    engine — coverage gate required (the feedback signal). Returns an
    aggregate shaped like `_stream_batches`' plus a "guided" record:
    {"trail": per-batch (seed schedule, bias state) records,
    "bias": final bias state, "escalation": final step,
    "failing_escalation": {seed: step it was found under}}."""
    import time as wall  # madsim: allow(D001) — host throughput only

    from ..__main__ import _make_emitter
    from ..runtime.coverage import (
        PlateauDetector, cell_table, coverage_dict, decode_map, encode_map,
        unpack_map,
    )

    if not eng.config.coverage:
        sys.exit("--guided needs --coverage: the bias signal IS the live map")

    log = logging.getLogger(f"madsim_tpu.{purpose}")
    emitter = _make_emitter(args)
    plateau_n = int(getattr(args, "stop_on_plateau", 0) or 0)
    # Two plateau signals, two granularities. The ESCALATION trigger
    # watches the coarse (band x phase) CELL grid — "this vocabulary
    # has stopped touching new scenario classes" fires in batches, not
    # hours, because the grid has at most 2^band_bits * 8 cells. The
    # STOP signal keeps `--stop-on-plateau`'s recorded raw-slot
    # semantics: the hunt only ends early when raw slots plateau AND
    # the escalation ladder is exhausted.
    detector = PlateauDetector(plateau_n) if plateau_n else None
    cell_detector = PlateauDetector(plateau_n) if plateau_n else None
    stop_after = int(getattr(args, "stop_after_batches", 0) or 0)

    chunk = min(args.seeds, args.batch)
    planned = -(-args.seeds // chunk)  # ceil

    agg: dict = {
        "completed": 0, "failing": [], "infra": [], "abandoned": [],
        "seeds_consumed": 0, "stats": {}, "provenance": {},
    }
    base_kinds = base_kind_names(eng.config.faults)
    bias = BiasState.fresh(base_kinds)
    parents: List[int] = []
    parent_set: set = set()
    seen: set = set()
    trail: List[dict] = []
    failing_escalation: Dict[int, int] = {}
    prov_counts: Dict[str, int] = {}
    cov_map: Optional[np.ndarray] = None
    cursor = args.seed
    plateaued = False
    start_bi = 0

    ckpt_path = getattr(args, "checkpoint", None)
    if ckpt_path:
        from ..runtime.checkpoint import check_fingerprint, load_checkpoint

        ck = load_checkpoint(ckpt_path)
        if ck is not None:
            err = check_fingerprint(ck, args)
            if err:
                sys.exit(f"--checkpoint {ckpt_path}: {err}")
            g = ck.get("guided") or {}
            agg["completed"] = int(ck["completed"])
            agg["seeds_consumed"] = int(ck["seeds_consumed"])
            agg["failing"] = [tuple(x) for x in ck["failing"]]
            agg["infra"] = [tuple(x) for x in ck["infra"]]
            agg["abandoned"] = list(ck["abandoned"])
            agg["provenance"] = {
                int(k): int(v) for k, v in (ck.get("prov") or {}).items()
            }
            cursor = int(ck["cursor"])
            start_bi = int(ck["batch"])
            plateaued = bool(ck.get("plateau", False))
            if ck.get("cov_b64"):
                cov_map = decode_map(ck["cov_b64"], eng.config.cov_slots_log2)
            if detector is not None and ck.get("detector"):
                d = ck["detector"]
                detector.best = int(d["best"])
                detector.streak = int(d["streak"])
                detector.batches = int(d["batches"])
            if cell_detector is not None and g.get("cell_detector"):
                d = g["cell_detector"]
                cell_detector.best = int(d["best"])
                cell_detector.streak = int(d["streak"])
                cell_detector.batches = int(d["batches"])
            bias = BiasState.from_dict(g["bias"]) if g.get("bias") else bias
            parents = [int(s) for s in g.get("parents", [])]
            parent_set = set(parents)
            trail = list(g.get("trail", []))
            failing_escalation = {
                int(k): int(v)
                for k, v in (g.get("failing_escalation") or {}).items()
            }
            prov_counts = {
                k: int(v) for k, v in (g.get("prov_counts") or {}).items()
            }
            seen = set()
            for rec in trail:
                seen.update(int(s) for s in rec["seeds"])
            if ck.get("done"):
                print(
                    f"checkpoint {ckpt_path}: guided run already complete "
                    f"({start_bi}/{planned} batches, "
                    f"{agg['completed']} seeds) — nothing to resume"
                )
            else:
                print(f"resumed at batch {start_bi + 1}/{planned} "
                      f"({agg['completed']} seeds already completed, "
                      f"escalation step {bias.escalation})")
                log.info("checkpoint %s: guided resume at batch %d/%d",
                         ckpt_path, start_bi + 1, planned)

    def _save_ckpt(bi_done: int, done_flag: bool) -> None:
        if not ckpt_path:
            return
        from ..runtime.checkpoint import (
            fingerprint_from_args, save_checkpoint,
        )

        save_checkpoint(ckpt_path, {
            "fingerprint": fingerprint_from_args(args),
            "batch": bi_done,
            "planned": planned,
            "cursor": cursor,
            "completed": agg["completed"],
            "seeds_consumed": agg["seeds_consumed"],
            "failing": [list(x) for x in agg["failing"]],
            "infra": [list(x) for x in agg["infra"]],
            "abandoned": list(agg["abandoned"]),
            "prov": {str(k): v for k, v in agg["provenance"].items()},
            "cov_b64": encode_map(cov_map) if cov_map is not None else None,
            "detector": (
                {"best": detector.best, "streak": detector.streak,
                 "batches": detector.batches}
                if detector is not None else None
            ),
            "plateau": plateaued,
            "done": done_flag,
            # the (seed schedule, bias state) record: everything a
            # resume — or a replacement worker — needs to recompute the
            # identical remaining schedule, and everything an auditor
            # needs to replay the hunt from nothing
            "guided": {
                "bias": bias.to_dict(),
                "parents": list(parents),
                "prov_counts": dict(sorted(prov_counts.items())),
                "trail": trail,
                "failing_escalation": {
                    str(k): v for k, v in failing_escalation.items()
                },
                "cell_detector": (
                    {"best": cell_detector.best,
                     "streak": cell_detector.streak,
                     "batches": cell_detector.batches}
                    if cell_detector is not None else None
                ),
            },
        })

    t_start = wall.perf_counter()
    bi = start_bi - 1
    for bi in range(start_bi, planned):
        remaining = args.seeds - agg["completed"]
        if remaining <= 0:
            _save_ckpt(bi, True)
            break
        this_chunk = min(chunk, remaining)
        ran_escalation = bias.escalation
        cur_eng = engine_for_escalation(eng, ran_escalation)
        vocab = vocabulary_for(base_kinds, ran_escalation)
        weights_used = dict(bias.weights)
        seeds, cursor, n_mut, op_counts = _select_batch(
            bias, cur_eng, parents, seen, cursor, bi, this_chunk,
        )
        seen.update(seeds)
        t0 = wall.perf_counter()
        out = cur_eng.run_seed_batch(seeds, max_steps=args.max_steps)
        el = max(wall.perf_counter() - t0, 1e-9)

        agg["completed"] += out["completed"]
        agg["seeds_consumed"] += out["seeds_consumed"]
        agg["failing"].extend(out["failing"])
        agg["infra"].extend(out["infra"])
        agg["abandoned"].extend(out["abandoned"])
        agg["provenance"].update(out.get("provenance", {}))
        for s, _c in out["failing"]:
            failing_escalation[int(s)] = ran_escalation

        # corpus evolution: lanes whose map contributed new slots to
        # the cumulative OR become parents of the next batch's mutants
        lane_bits = unpack_map(
            out["cov_lane_words"], eng.config.cov_slots_log2
        )
        prev = (
            np.zeros(lane_bits.shape[1], bool) if cov_map is None else cov_map
        )
        fresh_bits = lane_bits & ~prev[None, :]
        new_parent_mask = fresh_bits.any(axis=1)
        cov_map = prev | lane_bits.any(axis=0)
        slots_hit = int(cov_map.sum())
        new_slots = slots_hit - int(prev.sum())
        for s, is_new in zip(seeds, new_parent_mask):
            if is_new and s not in parent_set:
                parents.append(int(s))
                parent_set.add(int(s))
        if len(parents) > MAX_PARENTS:
            for s in parents[:-MAX_PARENTS]:
                parent_set.discard(s)
            parents = parents[-MAX_PARENTS:]

        # feedback fold: lineage words of this batch's finds + the live
        # map's per-band marginals
        if out.get("provenance"):
            find_seeds = sorted(out["provenance"])
            feats = schedule_features(cur_eng, find_seeds)
            for k, v in _prov_kind_counts(
                cur_eng, feats["kinds"],
                [out["provenance"][s] for s in find_seeds],
            ).items():
                prov_counts[k] = prov_counts.get(k, 0) + v
        cov_sum = coverage_dict(
            cov_map, eng.config.cov_slots_log2, band_bits=eng.cov_band_bits
        )
        bias.update(
            band_fractions_from_coverage(
                cov_sum, eng.config.cov_slots_log2, eng.cov_band_bits
            ),
            prov_counts,
        )

        escalated_to = None
        cells_hit = None
        if detector is not None:
            raw_plateau = detector.update(slots_hit)
            cells_hit = int((cell_table(
                cov_map, eng.config.cov_slots_log2,
                band_bits=eng.cov_band_bits,
            ) > 0).sum())
            cell_plateau = cell_detector.update(cells_hit)
            if cell_plateau or raw_plateau:
                if bias.escalate(base_kinds) is not None:
                    # skip rungs this machine cannot build (e.g. torn
                    # without a durable_spec): keep climbing until an
                    # engine constructs or the ladder exhausts
                    while True:
                        try:
                            engine_for_escalation(eng, bias.escalation)
                            escalated_to = bias.escalation
                            break
                        except ValueError:
                            if bias.escalate(base_kinds) is None:
                                break
                if escalated_to is not None:
                    detector.streak = 0
                    cell_detector.streak = 0
                elif raw_plateau:
                    # ladder exhausted AND raw slots saturated: the
                    # honest early stop --stop-on-plateau promised
                    plateaued = True

        trail.append({
            "batch": bi,
            # the step this batch RAN under (an escalation at the end
            # of this batch applies from the next batch on)
            "escalation": ran_escalation,
            "kinds": ",".join(vocab),
            "seeds": [int(s) for s in seeds],
            "mutants": n_mut,
            "ops": dict(sorted(op_counts.items())),
            "weights": {k: weights_used[k] for k in sorted(weights_used)},
            "slots_hit": slots_hit,
            "new_slots": new_slots,
            "cells_hit": cells_hit,
            "failing": len(agg["failing"]),
            "escalated_to": escalated_to,
        })
        log.info("%s", _guided_heartbeat(
            bi + 1, planned, out["completed"], n_mut, el,
            slots_hit, new_slots, len(agg["failing"]),
            ran_escalation, vocab,
            device_count=int(getattr(args, "devices", 0) or 0) or 1,
            escalated_to=escalated_to,
        ))
        if emitter is not None:
            emitter.emit({
                "kind": f"{purpose}_batch",
                "machine": args.machine,
                "batch": bi + 1,
                "batches": planned,
                "completed": agg["completed"],
                "batch_completed": out["completed"],
                "seeds_per_sec": round(out["completed"] / el, 1),
                "failing": len(agg["failing"]),
                "infra": len(agg["infra"]),
                "abandoned": len(agg["abandoned"]),
                "coverage": {"slots_hit": slots_hit, "new_slots": new_slots},
                "guided": {
                    "escalation": ran_escalation,
                    "kinds": ",".join(vocab),
                    "mutants": n_mut,
                    "parents": len(parents),
                    **({"escalated_to": escalated_to} if escalated_to else {}),
                },
            })
        _save_ckpt(bi + 1, plateaued)
        if plateaued:
            log.info(
                "coverage plateau with the escalation ladder exhausted: "
                "stopping after batch %d/%d", bi + 1, planned,
            )
            break
        if stop_after and bi + 1 >= stop_after:
            log.info(
                "stopping after guided batch %d/%d (--stop-after-batches "
                "%d; resumable via --checkpoint)", bi + 1, planned,
                stop_after,
            )
            break
    else:
        _save_ckpt(planned, True)

    agg["elapsed_s"] = wall.perf_counter() - t_start
    agg["batches_run"] = bi + 1
    agg["batches_planned"] = planned
    agg["plateau"] = plateaued
    if cov_map is not None:
        agg["coverage_map"] = cov_map
        agg["stats"] = dict(agg["stats"])
        agg["stats"]["coverage"] = {
            **coverage_dict(
                cov_map, eng.config.cov_slots_log2,
                band_bits=eng.cov_band_bits,
            ),
            "plateau": plateaued,
            "plateau_patience": plateau_n,
        }
    if agg["provenance"]:
        agg["stats"] = dict(agg["stats"])
        agg["stats"]["fault_attribution"] = dict(sorted(prov_counts.items()))
    guided_rec = {
        "trail": trail,
        "bias": bias.to_dict(),
        "escalation": bias.escalation,
        "parents": len(parents),
        "failing_escalation": dict(failing_escalation),
    }
    agg["guided"] = guided_rec
    agg["stats"] = dict(agg["stats"])
    agg["stats"]["guided"] = {
        "escalation": bias.escalation,
        "parents": len(parents),
        "batches": len(trail),
        "mutants": sum(r["mutants"] for r in trail),
    }
    if emitter is not None:
        emitter.emit({
            "kind": f"{purpose}_summary",
            "machine": args.machine,
            "completed": agg["completed"],
            "failing": len(agg["failing"]),
            "infra": len(agg["infra"]),
            "abandoned": len(agg["abandoned"]),
            "batches_run": agg["batches_run"],
            "batches_planned": planned,
            "plateau": plateaued,
            "elapsed_s": round(agg["elapsed_s"], 2),
            **(
                {"coverage": agg["stats"]["coverage"]}
                if cov_map is not None else {}
            ),
            "guided": agg["stats"]["guided"],
        })
        emitter.close()
    return agg
