"""madsim_tpu/search — coverage-feedback guided hunting.

The subsystem that finally *acts* on the observability the engine
pays for: `bias.py` turns the live coverage map's per-band marginals
and harvested failure-lineage words into per-kind draw weights (plus
the recorded fault-vocabulary escalation ladder), `mutate.py` derives
deterministic child seeds for the AFL-style corpus, `features.py`
re-derives candidate schedules host-side for scoring, and `guided.py`
runs the `--guided` batch loop with exact (seed schedule, bias state)
recording — checkpoint/resume and fleet worker replacement reproduce
byte-identically, and guidance-off leaves every HEAD code path
untouched.

`bias` and `mutate` are jax-free (the fleet control plane reads
recorded bias trails); `features`/`guided` touch jax only when called.
"""

from .bias import (  # noqa: F401
    ESCALATION_LADDER,
    BiasState,
    next_escalation,
    vocabulary_for,
)
from .mutate import child_seed, children, mix32  # noqa: F401
