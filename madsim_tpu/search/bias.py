"""Host-side bias state — the coverage/provenance feedback distilled to
per-kind draw weights, plus the recorded fault-vocabulary escalation
ladder.

The guided hunt never touches the in-kernel RNG layout: every lane
still derives its schedule from its seed exactly as HEAD does (all
golden streams stay byte-stable, guidance-off is bit-identical). What
the bias state perturbs is the *host-side choice of which seeds run
next*: `search/guided.py` proposes candidate seeds, re-derives each
candidate's fault schedule with the same `init_lane` derivation the
device executes (`search/features.py`), and keeps the candidates whose
schedules this state scores highest — thin-coverage-band kinds and
kinds that appear in failure lineages (`fail_prov`) score high.

Two feedback signals, one pure update per batch:

  * coverage thinness — the live map's per-band marginals (the banded
    `[band|phase|mix]` layout from PR 4 makes per-fault-kind counts
    directly decodable): the emptier a kind's band, the more the next
    batch should draw it;
  * failure lineage — PR 7's provenance words, decoded to per-kind
    implication counts: kinds that actually cause failures get hunted
    harder.

`update()` is a pure deterministic function of its inputs (fixed
iteration order, no wall clock, no entropy), and `to_dict`/`from_dict`
round-trip exactly — a guided hunt checkpointed mid-run, resumed, or
replayed on a replacement fleet worker recomputes the identical weight
trail. Pinned with hand-computed fixtures in tests/test_search.py.

jax-free by contract: the fleet control plane and the `coverage`
subcommand import this module on boxes with no accelerator stack.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

# The single-source fault-kind vocabulary (madsim_tpu/kinds.py). The
# escalation ladder below must BIND these tables — lint rule G009
# statically refuses a hand-maintained mirror here, exactly like
# G001-G007 refuse them everywhere else.
from ..kinds import CLI_KIND_TO_FLAG, FAULT_KIND_NAMES, band_name

#: The recorded escalation ladder: when a guided hunt plateaus, the
#: fault vocabulary widens to the next rung instead of stopping —
#: core scheduled kinds, then the window kinds (pause/skew), then the
#: storage kinds (torn/heal-asym), then the full 11-kind palette
#: (adding per-delivery duplication). Each rung is a slice of the K_*
#: index space, so a rung never reorders recorded schedule semantics;
#: the hunt's *base* vocabulary (whatever the operator asked for) is
#: always unioned in.
ESCALATION_LADDER = (
    FAULT_KIND_NAMES[:6],
    FAULT_KIND_NAMES[:8],
    FAULT_KIND_NAMES[:10],
    FAULT_KIND_NAMES + ("dup",),
)

#: thinness gain: how hard an empty band pulls vs a saturated one
#: (weight factor spans [1.0, 1.0 + THIN_GAIN])
THIN_GAIN = 1.0

_CLI_ORDER = tuple(name for name, _field in CLI_KIND_TO_FLAG)


def vocabulary_for(base_kinds: Sequence[str], escalation: int) -> Tuple[str, ...]:
    """The fault-kind vocabulary at escalation step `escalation`:
    step 0 is the hunt's base vocabulary; step e >= 1 unions rung e-1
    of the ladder. Rendered in the CLI's historical print order so
    recorded `--fault-kinds` strings stay canonical."""
    if not 0 <= escalation <= len(ESCALATION_LADDER):
        raise ValueError(
            f"escalation step {escalation} out of range "
            f"[0, {len(ESCALATION_LADDER)}]"
        )
    kinds = set(base_kinds)
    if escalation:
        kinds |= set(ESCALATION_LADDER[escalation - 1])
    return tuple(k for k in _CLI_ORDER if k in kinds)


def next_escalation(base_kinds: Sequence[str], escalation: int) -> Optional[int]:
    """The next ladder step that actually WIDENS the vocabulary, or
    None when the ladder is exhausted (the hunt should then honestly
    plateau). Steps that add nothing over the current vocabulary are
    skipped — a hunt already running the full palette has nowhere to
    escalate."""
    cur = set(vocabulary_for(base_kinds, escalation))
    for step in range(escalation + 1, len(ESCALATION_LADDER) + 1):
        if set(vocabulary_for(base_kinds, step)) - cur:
            return step
    return None


@dataclasses.dataclass
class BiasState:
    """Per-kind draw weights + the escalation cursor. `weights` covers
    the SCHEDULED kinds of the current vocabulary (dup is per-delivery
    chaos, not a schedule draw — it has no weight), normalized to sum
    1.0; a fresh state is uniform."""

    kinds: Tuple[str, ...]          # current vocabulary (CLI names)
    weights: Dict[str, float]
    escalation: int = 0
    updates: int = 0

    @staticmethod
    def fresh(kinds: Sequence[str], escalation: int = 0) -> "BiasState":
        sched = [k for k in kinds if k in FAULT_KIND_NAMES]
        n = max(1, len(sched))
        return BiasState(
            kinds=tuple(kinds),
            weights={k: 1.0 / n for k in sched},
            escalation=escalation,
        )

    def update(self, band_fractions: Dict[str, float],
               prov_counts: Dict[str, int]) -> None:
        """One batch's feedback fold: weight_k proportional to
        (1 + lineage implications of k) * (1 + THIN_GAIN * (1 - the
        fill fraction of k's coverage band)), renormalized. Iteration
        order is the kinds-table order — the update is bit-deterministic
        for identical inputs (pinned by hand-computed fixtures)."""
        sched = [k for k in FAULT_KIND_NAMES if k in self.kinds]
        raw = {}
        for k in sched:
            frac = float(band_fractions.get(band_name(k), 0.0))
            frac = min(max(frac, 0.0), 1.0)
            raw[k] = (1.0 + float(prov_counts.get(k, 0))) * (
                1.0 + THIN_GAIN * (1.0 - frac)
            )
        total = sum(raw.values())
        if total > 0.0:
            self.weights = {k: raw[k] / total for k in sched}
        self.updates += 1

    def score_kinds(self, kind_names: Sequence[str]) -> float:
        """Score one candidate schedule: the sum of its drawn kinds'
        weights (a schedule drawing three thin-band kinds outranks one
        drawing three saturated ones)."""
        return sum(self.weights.get(k, 0.0) for k in kind_names)

    def escalate(self, base_kinds: Sequence[str]) -> Optional[Tuple[str, ...]]:
        """Advance to the next widening ladder step, re-seeding weights
        uniformly over the new vocabulary (fresh kinds have no history;
        the next update() re-learns from the live map). Returns the new
        vocabulary, or None when the ladder is exhausted."""
        step = next_escalation(base_kinds, self.escalation)
        if step is None:
            return None
        vocab = vocabulary_for(base_kinds, step)
        old = self.weights
        fresh = BiasState.fresh(vocab, escalation=step)
        # carry learned weight mass for kinds that survive the widening
        carried = {
            k: old.get(k, fresh.weights[k]) for k in fresh.weights
        }
        total = sum(carried.values()) or 1.0
        self.kinds = vocab
        self.weights = {k: v / total for k, v in carried.items()}
        self.escalation = step
        return vocab

    # -- exact persistence ---------------------------------------------------

    def to_dict(self) -> dict:
        return {
            "kinds": list(self.kinds),
            "weights": {k: self.weights[k] for k in sorted(self.weights)},
            "escalation": self.escalation,
            "updates": self.updates,
        }

    @staticmethod
    def from_dict(d: dict) -> "BiasState":
        return BiasState(
            kinds=tuple(d["kinds"]),
            weights={k: float(v) for k, v in d["weights"].items()},
            escalation=int(d["escalation"]),
            updates=int(d["updates"]),
        )


def band_fractions_from_coverage(cov: dict, slots_log2: int,
                                 band_bits: int) -> Dict[str, float]:
    """Per-band fill fractions from a `coverage_dict`-shaped summary
    (the SAME artifact `madsim_tpu coverage --json` renders and the
    stats feed carries): band hit count / band slot capacity."""
    band_size = (1 << slots_log2) >> band_bits
    return {
        name: hits / band_size for name, hits in cov["by_band"].items()
    }
