"""Simulated ctrl-c signal (reference: madsim/src/sim/signal.rs).

Each node has a list of ctrl-c subscribers; `Handle.send_ctrl_c` either
delivers to them or, with no subscriber, kills the node
(reference: sim/task/mod.rs:106-111,:166-175,:426-441).
"""

from __future__ import annotations

from typing import Callable

from . import _context
from .future import OneShotCell, Pollable, await_


class _CtrlCFuture(Pollable):
    """Deregisters its watcher cell when the waiter goes away, so a
    cancelled `ctrl_c()` does not swallow a later signal."""

    __slots__ = ("node", "cell")

    def __init__(self, node, cell: OneShotCell):
        self.node = node
        self.cell = cell

    def poll(self, waker: Callable[[], None]):
        return self.cell.poll(waker)

    def drop(self) -> None:
        try:
            self.node.ctrl_c_watchers.remove(self.cell)
        except ValueError:
            pass


async def ctrl_c() -> None:
    """Complete when ctrl-c is sent to the current node."""
    task = _context.current_task()
    cell = OneShotCell()
    task.node.ctrl_c_watchers.append(cell)
    await await_(_CtrlCFuture(task.node, cell))
