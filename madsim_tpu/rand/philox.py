"""Philox4x32-10 counter-based RNG — the shared determinism substrate.

The reference uses a *stateful* Xoshiro256++ behind a mutex
(reference: madsim/src/sim/rand.rs:28 `GlobalRng`). A mutated-state RNG
cannot be replayed lane-parallel on TPU, so this framework uses a
*counter-based* generator instead: draw ``i`` of seed ``s`` is the pure
function ``philox4x32(key=s, counter=i)``. The host engine and the TPU
engine evaluate the very same integer recurrence (here in pure Python
ints, in `madsim_tpu.engine.rng` with jax uint32 lanes), which is what
makes TPU-found failing seeds replay bit-identically on the host.

Philox4x32-10 constants per Salmon et al., "Parallel random numbers: as
easy as 1, 2, 3" (SC'11).
"""

from __future__ import annotations

from typing import Tuple

PHILOX_M0 = 0xD2511F53
PHILOX_M1 = 0xCD9E8D57
PHILOX_W0 = 0x9E3779B9
PHILOX_W1 = 0xBB67AE85
_M32 = 0xFFFFFFFF
ROUNDS = 10


def philox4x32(key: Tuple[int, int], ctr: Tuple[int, int, int, int]) -> Tuple[int, int, int, int]:
    """One Philox4x32-10 block: (k0,k1) x (c0..c3) -> 4 uint32 words.

    Pure-Python reference implementation; `madsim_tpu.engine.rng.philox4x32`
    is the vectorized jax twin. `tests/test_rand.py` asserts they agree
    word-for-word.
    """
    k0, k1 = key[0] & _M32, key[1] & _M32
    c0, c1, c2, c3 = (c & _M32 for c in ctr)
    for _ in range(ROUNDS):
        p0 = PHILOX_M0 * c0
        p1 = PHILOX_M1 * c2
        hi0, lo0 = (p0 >> 32) & _M32, p0 & _M32
        hi1, lo1 = (p1 >> 32) & _M32, p1 & _M32
        c0, c1, c2, c3 = (
            (hi1 ^ c1 ^ k0) & _M32,
            lo1,
            (hi0 ^ c3 ^ k1) & _M32,
            lo0,
        )
        k0 = (k0 + PHILOX_W0) & _M32
        k1 = (k1 + PHILOX_W1) & _M32
    return c0, c1, c2, c3


def splitmix64(x: int) -> int:
    """64-bit mixer used for draw-log hashing and seed derivation.

    Same constants as the public-domain splitmix64; also implemented in
    jax by the TPU engine for on-device draw logging.
    """
    x = (x + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    return x ^ (x >> 31)
