"""Deterministic global RNG — every source of randomness in a simulation.

Reference parity (madsim/src/sim/rand.rs):
  * one global RNG per Runtime seeded from the test seed (:28-62)
  * a determinism *log/check* mode: each draw records a hash of
    (draw value, virtual time); a second run in check mode compares and
    raises on divergence (:65-117, surfaced as `NonDeterminism`)
  * buggify probability draws (:119-135)
  * `thread_rng()` / `random()` user API (rand crate surface)

Architectural difference (TPU-first): the generator is counter-based
Philox (see `philox.py`) rather than shared-mutation Xoshiro, so the
same draw sequence can be produced lane-parallel on device. libc
interposition (reference :197 `getrandom` override) has no Python
equivalent — determinism instead comes from API discipline plus this
draw-log checker, which catches code that consulted an outside RNG and
then influenced the schedule.
"""

from __future__ import annotations

from typing import Iterable, List, MutableSequence, Optional, Sequence, TypeVar

from .. import _context
from ..errors import NonDeterminism
from .philox import philox4x32, splitmix64

T = TypeVar("T")

__all__ = [
    "GlobalRng",
    "thread_rng",
    "random",
    "philox4x32",
    "splitmix64",
]


class GlobalRng:
    """The per-Runtime deterministic RNG (reference: sim/rand.rs:28)."""

    def __init__(self, seed: int):
        self.seed = seed & 0xFFFFFFFFFFFFFFFF
        # Key schedule: mix the seed so nearby seeds give unrelated streams.
        mixed = splitmix64(self.seed)
        self._key = (mixed & 0xFFFFFFFF, (mixed >> 32) & 0xFFFFFFFF)
        self._counter = 0  # next philox block index
        self._buf: List[int] = []  # leftover uint32 words, drained LIFO-stable (pop from end? no: FIFO)
        self._buf_pos = 0
        # determinism log/check (reference: sim/rand.rs:65-117)
        self._log: Optional[List[int]] = None
        self._check: Optional[List[int]] = None
        self._check_pos = 0
        self._draw_index = 0
        # buggify state (reference: sim/buggify.rs + sim/rand.rs:119-135)
        self.buggify_enabled = False
        from .. import _native

        # Native draw stream (hostcore.Rng) — the SAME stream object the
        # native executor loop draws from, so scheduling draws and user
        # draws interleave identically to the pure-Python loop.
        self._core = (
            _native.make_rng(self._key[0], self._key[1])
            if _native.available()
            else None
        )
        self._native_obs = False

    # -- core draws ---------------------------------------------------------

    @property
    def recording(self) -> bool:
        """True while the determinism log/check observes every draw.

        With a native core, observation happens INSIDE the core
        (hostcore `rng_observe`, VERDICT r2/r3 native-check directive):
        the executor keeps using the native drive loop and the loop's
        own scheduling draws are hashed too — check mode validates the
        loop that actually ran. Without the core, the executor routes
        through its Python loop so `_record` sees every draw."""
        return self._log is not None or self._check is not None or self._native_obs

    @property
    def native_observing(self) -> bool:
        """Observation handled by the native core (executor may stay on
        the native drive loop)."""
        return self._native_obs

    def _refill(self) -> None:
        """Refill the pure-Python word buffer (native builds draw from
        `_core` instead; the word *sequence* is identical either way)."""
        c = self._counter
        self._buf = list(
            philox4x32(self._key, (c & 0xFFFFFFFF, (c >> 32) & 0xFFFFFFFF, 0, 0))
        )
        self._counter += 1
        self._buf_pos = 0

    def next_u32(self) -> int:
        core = self._core
        if core is not None:
            v = core.next_u32()
        else:
            if self._buf_pos >= len(self._buf):
                self._refill()
            v = self._buf[self._buf_pos]
            self._buf_pos += 1
        if self._log is not None or self._check is not None:
            self._record(v)
        return v

    def next_u64(self) -> int:
        # native fast path: one C call for both words; draw observation
        # (log/check hashing) happens inside the core either way
        core = self._core
        if core is not None:
            return core.next_u64()
        lo = self.next_u32()
        hi = self.next_u32()
        return (hi << 32) | lo

    def _record(self, value: int) -> None:
        """Draw-log hashing (reference: sim/rand.rs:65-90).

        The hash folds in virtual time so a draw happening at a different
        sim-time is also flagged, matching the reference's
        `hash(rng_peek ^ sim_time_nanos)` scheme.
        """
        log = self._log
        check = self._check
        if log is None and check is None:
            return
        t = _context.try_time_ns()
        h = splitmix64((self._draw_index << 32) ^ value ^ (t if t is not None else 0))
        self._draw_index += 1
        if log is not None:
            log.append(h)
        if check is not None:
            if self._check_pos >= len(check) or check[self._check_pos] != h:
                raise NonDeterminism(
                    f"non-determinism detected at draw #{self._draw_index - 1}, "
                    f"sim time {t} ns: the same seed produced a different "
                    f"randomness sequence. Check for use of outside RNGs, wall "
                    f"clocks, real threads, or iteration over unordered sets."
                )
            self._check_pos += 1

    # -- log / check control (reference: sim/rand.rs:103-117) ---------------

    def enable_log(self) -> None:
        if self._core is not None:
            self._core.observe_log()
            self._native_obs = True
            return
        self._log = []
        self._draw_index = 0

    def take_log(self) -> List[int]:
        if self._native_obs:
            self._native_obs = False
            return self._core.take_obs()
        log = self._log or []
        self._log = None
        return log

    def enable_check(self, log: List[int]) -> None:
        if self._core is not None:
            self._core.observe_check(log)
            self._native_obs = True
            return
        self._check = log
        self._check_pos = 0
        self._draw_index = 0

    def raise_native_mismatch(self) -> None:
        """Raise for a divergence the native core recorded (executor
        drive code 4, or finish_check below)."""
        _mode, _draws, _pos, _expected, mm_idx, mm_t = self._core.obs_status()
        raise NonDeterminism(
            f"non-determinism detected at draw #{mm_idx}, sim time {mm_t} ns: "
            f"the same seed produced a different randomness sequence. Check "
            f"for use of outside RNGs, wall clocks, real threads, or "
            f"iteration over unordered sets."
        )

    def finish_check(self) -> None:
        """Assert the checked run consumed the WHOLE draw log — a run that
        diverges by drawing fewer values is also non-deterministic."""
        if self._native_obs:
            _mode, draws, pos, expected, mm_idx, _t = self._core.obs_status()
            self._native_obs = False
            self._core.observe_off()
            if mm_idx >= 0:
                self.raise_native_mismatch()
            if pos != expected:
                raise NonDeterminism(
                    f"non-determinism detected: second run made {pos} "
                    f"RNG draws but the first made {expected}"
                )
            return
        if self._check is not None and self._check_pos != len(self._check):
            raise NonDeterminism(
                f"non-determinism detected: second run made {self._check_pos} "
                f"RNG draws but the first made {len(self._check)}"
            )

    # -- user-facing draws --------------------------------------------------

    def random(self) -> float:
        """Uniform float64 in [0, 1) with 53 bits, identical across engines."""
        core = self._core
        if core is not None:
            return core.random()  # same (u64 >> 11) * 2^-53, one C call
        return (self.next_u64() >> 11) * (2.0**-53)

    def gen_range(self, low: int, high: int) -> int:
        """Uniform integer in [low, high). Deterministic (64-bit modulo).

        Bias for spans far below 2^64 is negligible and, crucially,
        reproducible on both engines.
        """
        if high <= low:
            raise ValueError(f"empty range [{low}, {high})")
        span = high - low
        core = self._core
        # fast path only within int64 bounds AND span: the C parser is
        # int64 and signed high-low must not overflow — out-of-range
        # bounds take the bignum path (identical draw sequence: it pulls
        # next_u64 from the same core stream)
        if (
            core is not None
            and -0x8000000000000000 <= low
            and high <= 0x7FFFFFFFFFFFFFFF
            and span <= 0x7FFFFFFFFFFFFFFF
        ):
            return core.gen_range(low, high)  # same low + u64 % span
        return low + self.next_u64() % span

    def gen_bool(self, p: float) -> bool:
        return self.random() < p

    def gen_bytes(self, n: int) -> bytes:
        out = bytearray()
        while len(out) < n:
            out += self.next_u32().to_bytes(4, "little")
        return bytes(out[:n])

    def shuffle(self, seq: MutableSequence[T]) -> None:
        for i in range(len(seq) - 1, 0, -1):
            j = self.gen_range(0, i + 1)
            seq[i], seq[j] = seq[j], seq[i]

    def choice(self, seq: Sequence[T]) -> T:
        return seq[self.gen_range(0, len(seq))]

    # -- buggify draws (reference: sim/rand.rs:119-135) ---------------------

    def buggify_with_prob(self, p: float) -> bool:
        if not self.buggify_enabled:
            return False
        return self.gen_bool(p)


def thread_rng() -> GlobalRng:
    """The current simulation's RNG (reference: rand crate `thread_rng`).

    Must be called from inside a running simulation.
    """
    return _context.current_rng()


def random() -> float:
    """Uniform float in [0,1) from the simulation RNG."""
    return thread_rng().random()
