"""Protocol state-machine authoring API for the TPU engine.

The host engine runs free-form async Python (like the reference runs
arbitrary futures). Arbitrary coroutines cannot run on TPU, so the TPU
engine runs *protocol step functions*: a `Machine` is a pure, traceable
transition system over fixed-shape jax arrays (SURVEY.md §7 "hard parts"
item 3 — this authoring model is first-class).

Per-lane calling convention (the engine vmaps over lanes):

  * node state: a pytree whose every leaf has leading dim N (num nodes)
  * handlers receive the whole pytree + a scalar node index and return
    (new pytree, Outbox); use `update_node` / `.at[i]` scatters
  * Outbox: fixed-width message/timer slots with validity masks — the
    fixed-shape equivalent of the reference's dynamic spawn/send
    (sim/net/mod.rs send path); invalid slots are ignored

Timer id 0 (`BOOT`) is reserved: the engine delivers it to every node at
t=0 and after every restart — machines schedule their initial timers in
response (the analogue of NodeBuilder.init closures,
reference: sim/runtime/mod.rs:359-375).
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp
from flax import struct

BOOT = 0  # reserved timer id

# Storage-atomicity classes for torn/lost-write faults
# (`Machine.torn_spec()`, consumed by `torn_restart_if`): what a torn
# restart may do to a DURABLE leaf. Volatile leaves (durable_spec False)
# ignore their class — they are wiped like any amnesia restart.
TORN_ATOMIC = 1  # the write is atomic+fsynced: the leaf row survives intact
TORN_LOSE = 2    # all-or-nothing lost write: the whole row may revert to
#                  its fresh-init value (the write never reached the disk)
TORN_PREFIX = 3  # torn multi-element write: the row keeps only a seeded
#                  prefix along its trailing axis, the suffix reverts
#                  (1-D rows degrade to TORN_LOSE — no axis to tear)

# torn damage hash: mix a (payload ^ step-salt) seed word with the leaf's
# static flatten index — murmur3-fmix-style, same avalanche family as
# core.digest_fold / ops.coverage.cov_mix
_TORN_GOLDEN = 0x9E3779B9
_TORN_M1 = 0x85EBCA6B
_TORN_M2 = 0xC2B2AE35


def torn_hash(seed, leaf_idx: int) -> jax.Array:
    """Deterministic uint32 damage word for durable leaf `leaf_idx`
    (static flatten position) under the traced torn seed word."""
    h = jnp.asarray(seed).astype(jnp.uint32) ^ jnp.uint32(
        (_TORN_GOLDEN * (leaf_idx + 1)) & 0xFFFFFFFF
    )
    h = (h ^ (h >> 16)) * jnp.uint32(_TORN_M1)
    h = (h ^ (h >> 13)) * jnp.uint32(_TORN_M2)
    return h ^ (h >> 16)


@struct.dataclass
class Outbox:
    """Fixed-capacity per-step outputs of a handler."""

    msg_dst: jax.Array  # int32[M] destination node (-1 = invalid)
    msg_payload: jax.Array  # int32[M, P]
    msg_valid: jax.Array  # bool[M]
    timer_delay_us: jax.Array  # int32[T]
    timer_id: jax.Array  # int32[T]
    timer_valid: jax.Array  # bool[T]


def empty_outbox(max_msgs: int, max_timers: int, payload_width: int) -> Outbox:
    return Outbox(
        msg_dst=jnp.full((max_msgs,), -1, jnp.int32),
        msg_payload=jnp.zeros((max_msgs, payload_width), jnp.int32),
        msg_valid=jnp.zeros((max_msgs,), bool),
        timer_delay_us=jnp.zeros((max_timers,), jnp.int32),
        timer_id=jnp.zeros((max_timers,), jnp.int32),
        timer_valid=jnp.zeros((max_timers,), bool),
    )


# All writes below are mask-based `where` selects rather than scatters:
# scatters with traced indices are hostile to the TPU vectorizer (and the
# axon compiler rejects multi-index forms outright), while a masked select
# over a small fixed axis is pure VPU work.


def _slot_mask(n: int, slot) -> jax.Array:
    return jnp.arange(n) == slot


def send(outbox: Outbox, slot: int, dst, payload) -> Outbox:
    """Set message slot `slot`."""
    return send_if(outbox, slot, jnp.bool_(True), dst, payload)


def send_if(outbox: Outbox, slot: int, cond, dst, payload) -> Outbox:
    """Conditionally set message slot `slot` (traced condition)."""
    m = _slot_mask(outbox.msg_dst.shape[0], slot) & cond
    return outbox.replace(
        msg_dst=jnp.where(m, jnp.int32(dst), outbox.msg_dst),
        msg_payload=jnp.where(m[:, None], payload[None, :], outbox.msg_payload),
        msg_valid=outbox.msg_valid | m,
    )


def set_timer(outbox: Outbox, slot: int, delay_us, timer_id) -> Outbox:
    return set_timer_if(outbox, slot, jnp.bool_(True), delay_us, timer_id)


def set_timer_if(outbox: Outbox, slot: int, cond, delay_us, timer_id) -> Outbox:
    m = _slot_mask(outbox.timer_id.shape[0], slot) & cond
    return outbox.replace(
        timer_delay_us=jnp.where(m, jnp.int32(delay_us), outbox.timer_delay_us),
        timer_id=jnp.where(m, jnp.int32(timer_id), outbox.timer_id),
        timer_valid=outbox.timer_valid | m,
    )


def set_at(arr: jax.Array, i, value, cond=True) -> jax.Array:
    """`arr.at[i].set(value)` for traced i, as a masked select; `cond`
    (traced bool) gates the whole write."""
    mask = (jnp.arange(arr.shape[0]) == i) & cond
    while mask.ndim < arr.ndim:
        mask = mask[..., None]
    return jnp.where(mask, value, arr)


def update_node(nodes: Any, i, **updates) -> Any:
    """Write per-field updates into node i of a state dataclass."""
    return nodes.replace(**{k: set_at(getattr(nodes, k), i, v) for k, v in updates.items()})


def make_payload(width: int, *vals) -> jax.Array:
    """Pack scalars into a fixed-width int32 payload vector."""
    parts = [jnp.asarray(v, jnp.int32) for v in vals]
    parts += [jnp.int32(0)] * (width - len(parts))
    return jnp.stack(parts)


class Machine:
    """Base class: subclass and override the handlers.

    Class attributes to set:
      NUM_NODES, PAYLOAD_WIDTH, MAX_MSGS, MAX_TIMERS
    """

    NUM_NODES: int = 1
    PAYLOAD_WIDTH: int = 4
    MAX_MSGS: int = 4
    MAX_TIMERS: int = 2

    def empty_outbox(self) -> Outbox:
        return empty_outbox(self.MAX_MSGS, self.MAX_TIMERS, self.PAYLOAD_WIDTH)

    # -- required overrides --------------------------------------------------

    def init(self, rng_key) -> Any:
        """Initial node-state pytree (every leaf leading dim NUM_NODES)."""
        raise NotImplementedError

    def _wipe_node_if(self, nodes: Any, i, cond, rng_key) -> Any:
        """Non-virtual building block: copy row i from a fresh init()
        under `cond` (never dispatches to overrides — safe to call from
        any subclass hook without recursion)."""
        fresh = self.init(rng_key)
        return jax.tree.map(lambda cur, f: set_at(cur, i, f, cond), nodes, fresh)

    def init_node(self, nodes: Any, i, rng_key) -> Any:
        """Reset node i to its initial state (legacy restart hook).
        Default: re-derive from init() and copy row i."""
        return self._wipe_node_if(nodes, i, jnp.bool_(True), rng_key)

    def restart_if(self, nodes: Any, i, cond, rng_key) -> Any:
        """Conditionally reset node i — the engine's restart-fault hook
        (`cond` is a traced bool). The default honors a subclass's
        `init_node` override (the older restart hook), so machines with
        durable/volatile splits written against that API keep their
        semantics; override `restart_if` directly and fold `cond` into
        your own row masks to skip the full-tree select (it cost ~30% of
        raft's eager step time)."""
        fresh = self.init_node(nodes, i, rng_key)
        return jax.tree.map(lambda c, f: jnp.where(cond, f, c), nodes, fresh)

    def durable_spec(self) -> Any:
        """Optional durable-state contract for crash-with-amnesia faults
        (`FaultPlan.strict_restart`): a pytree CONGRUENT to `init()`'s
        node state whose every leaf is a python bool — True marks a
        leaf as durable (survives restart: stable storage / WAL /
        fsynced log), False as volatile (a restarted node must lose
        it). The engine wipes volatile leaves generically from a fresh
        `init()` in `restart_node_if(..., strict=True)` — the model's
        hand-written `restart_if` is bypassed, so a machine whose
        restart code quietly keeps state its own contract calls
        volatile can no longer hide it (the classic DST finding class:
        "node restarts but illegally kept volatile state").

        Default None: no contract declared — the engine refuses
        `strict_restart` for such machines rather than guessing.
        """
        return None

    def amnesia_restart_if(self, nodes: Any, i, cond, rng_key) -> Any:
        """Crash-with-amnesia restart: reset every leaf `durable_spec()`
        marks volatile to its fresh-`init()` value for node row i (a
        masked row write per volatile leaf; durable leaves cost nothing
        — the keep is a static python branch)."""
        spec = self.durable_spec()
        if spec is None:
            raise ValueError(
                f"{type(self).__name__} declares no durable_spec(); "
                f"strict_restart (crash-with-amnesia) needs the durable-"
                f"state contract to know which leaves to wipe"
            )
        fresh = self.init(rng_key)
        return jax.tree.map(
            lambda durable, cur, f: cur if durable else set_at(cur, i, f, cond),
            spec, nodes, fresh,
        )

    def torn_spec(self) -> Any:
        """Optional storage-atomicity contract for torn/lost-write
        faults (`FaultPlan.allow_torn`): a pytree CONGRUENT to `init()`'s
        node state whose every leaf is one of TORN_ATOMIC / TORN_LOSE /
        TORN_PREFIX — what a torn restart may do to that DURABLE leaf
        (volatile leaves ignore their class; they are wiped like any
        amnesia restart). Default None: every durable write is atomic
        and fsynced, so a torn restart degrades to exactly the amnesia
        wipe — a machine with only a `durable_spec()` gets the K_TORN
        kind for free and survives it by construction. A machine
        modelling a non-atomic storage path (a snapshot file written
        without fsync, a multi-page WAL append) marks those leaves
        TORN_LOSE / TORN_PREFIX, and its recovery path must tolerate
        the damage or the checkers convict it — the FoundationDB
        buggify finding class ("the disk lied")."""
        return None

    def torn_restart_if(self, nodes: Any, i, cond, rng_key, torn_seed) -> Any:
        """Torn/lost-write restart (K_TORN undo op): volatile leaves
        wipe exactly as `amnesia_restart_if`; each durable leaf then
        takes its `torn_spec()` damage — TORN_LOSE rows revert whole
        under a seeded coin, TORN_PREFIX rows keep only a seeded prefix
        of their trailing axis. `torn_seed` is a traced uint32 (the
        fault payload's schedule-drawn mask xor the step's torn salt
        word); damage is a pure function of (torn_seed, leaf position),
        so replays are bit-identical."""
        spec = self.durable_spec()
        if spec is None:
            raise ValueError(
                f"{type(self).__name__} declares no durable_spec(); "
                f"allow_torn (torn/lost-write storage faults) needs the "
                f"durable-state contract to know which leaves exist"
            )
        tspec = self.torn_spec()
        if tspec is None:
            tspec = jax.tree.map(lambda _leaf: TORN_ATOMIC, spec)
        fresh = self.init(rng_key)
        leaf_idx = [0]

        def damage(durable, cls, cur, f):
            li = leaf_idx[0]
            leaf_idx[0] += 1
            if not durable:
                return set_at(cur, i, f, cond)  # amnesia wipe
            if cls == TORN_ATOMIC:
                return cur
            h = torn_hash(torn_seed, li)
            if cls == TORN_LOSE or cur.ndim < 2:
                lost = (h & 1) == 1
                return set_at(cur, i, f, cond & lost)
            if cls == TORN_PREFIX:
                size = cur.shape[-1]
                cut = (h >> 1) % jnp.uint32(size + 1)
                torn_tail = jnp.arange(size) >= cut.astype(jnp.int32)
                row = (jnp.arange(cur.shape[0]) == i) & cond
                mask = row.reshape((-1,) + (1,) * (cur.ndim - 1)) & torn_tail
                return jnp.where(mask, f, cur)
            raise ValueError(
                f"{type(self).__name__}.torn_spec() leaf {li} has "
                f"unknown atomicity class {cls!r} (expected TORN_ATOMIC/"
                f"TORN_LOSE/TORN_PREFIX)"
            )

        return jax.tree.map(damage, spec, tspec, nodes, fresh)

    def restart_node_if(self, nodes: Any, i, cond, rng_key, strict: bool = False) -> Any:
        """Engine-facing restart dispatch — do NOT override. With
        `strict` (static, from `FaultPlan.strict_restart`) the generic
        crash-with-amnesia wipe runs instead of the model's own restart
        hook — the durable_spec contract, not the handler code, decides
        what survives. Otherwise picks the restart hook by MRO position
        so both authoring styles work:

          * a subclass overriding `restart_if` (the fast path) wins when
            it is at least as derived as any `init_node` override;
          * a subclass overriding only the legacy `init_node` hook gets
            the generic bridge (fresh = init_node; tree-select on cond)
            even when a base model ships a fast-path `restart_if` —
            otherwise the override would be silently ignored, and a
            guard inside each model's restart_if can mutually recurse
            with init_node shims that delegate to restart_if.
        """
        if strict:
            return self.amnesia_restart_if(nodes, i, cond, rng_key)
        mro = type(self).__mro__

        def hook_owner(name):
            return next(c for c in mro if name in c.__dict__)

        init_owner = hook_owner("init_node")
        rif_owner = hook_owner("restart_if")
        if init_owner is not Machine and mro.index(init_owner) < mro.index(rif_owner):
            # the generic bridge; naming the base class cannot recurse
            return Machine.restart_if(self, nodes, i, cond, rng_key)
        return self.restart_if(nodes, i, cond, rng_key)

    def on_timer(self, nodes: Any, node, timer_id, now_us, rand_u32) -> Tuple[Any, Outbox]:
        raise NotImplementedError

    def on_message(self, nodes: Any, node, src, payload, now_us, rand_u32) -> Tuple[Any, Outbox]:
        raise NotImplementedError

    # -- optional overrides --------------------------------------------------

    def invariant(self, nodes: Any, now_us) -> Tuple[jax.Array, jax.Array]:
        """(ok: bool, code: int32). A False freezes the lane as FAILED —
        the on-device analogue of a failing assertion in a #[madsim::test]."""
        return jnp.bool_(True), jnp.int32(0)

    def is_done(self, nodes: Any, now_us) -> jax.Array:
        """Early-success predicate (lane stops exploring)."""
        return jnp.bool_(False)

    def summary(self, nodes: Any) -> Any:
        """Small pytree gathered back to host per lane."""
        return jnp.int32(0)

    def coverage_projection(self, nodes: Any, now_us) -> jax.Array:
        """Abstract-state word for the scenario-coverage map
        (`EngineConfig.coverage`, ops/coverage.py): project the whole
        node-state pytree down to a uint32 of coarse buckets — the
        engine hashes it with the popped event kind and fault context
        into the per-lane hit map every step.

        Contract: pure function of (nodes, now_us); put the model's
        coarsest "phase" notion (progress stage, term/txn/generation
        bucket) in the LOW 3 BITS — those become the visible phase axis
        of the (band, phase) cell report — and keep the whole word to a
        handful of small bucketed fields. Too fine a projection (raw
        counters, timestamps) saturates the map and destroys the
        plateau signal; too coarse and saturation is declared early.

        Default: constant 0. Coverage still distinguishes event kinds,
        destination nodes and fault contexts, so the map works for any
        machine — a model projection just makes it much sharper.
        """
        return jnp.uint32(0)
