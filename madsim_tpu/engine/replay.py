"""Bit-identical single-lane replay on the host CPU — the debugger path.

The TPU batch explores thousands of seeds; any failing seed is re-run
here, eagerly, one event at a time, with a full event trace the user can
print, filter, or step through. Because the replay executes the *same*
jax ops (threefry draws, int32 time math, argmin pops) outside jit on
CPU, the outcome is bit-identical to the lane's on-device execution —
the property the reference gets from reproduce-by-seed
(madsim/src/sim/runtime/mod.rs:205-210), upgraded to cross-engine.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, List, Optional

import jax

from ..ops import pop_earliest
from .core import EV_FAULT, EV_MSG, EV_TIMER, Engine, LaneState

_KIND_NAMES = {EV_TIMER: "timer", EV_MSG: "msg", EV_FAULT: "fault"}


@dataclasses.dataclass
class TraceEvent:
    step: int
    time_us: int
    kind: str
    node: int
    src: int
    payload: tuple
    # the event's queue sequence number — unique per lane, assigned at
    # push time, so (together with the per-step next_seq watermarks) the
    # host can reconstruct exactly which step enqueued which event: the
    # send->delivery / arm->fire lineage engine/provenance.py and the
    # Perfetto flow arrows are built from. -1 on traces recorded before
    # the field existed.
    seq: int = -1
    # the event's causal-provenance word (EngineConfig.provenance;
    # 0 when the gate is off): one bit per scheduled fault slot in the
    # event's lineage, bits 30/31 = strict-restart wipe / dup delivery
    prov: int = 0

    def __repr__(self) -> str:
        src = f" src={self.src}" if self.kind == "msg" else ""
        return (
            f"[{self.time_us:>10}us] #{self.step:<5} {self.kind:<5} "
            f"node={self.node}{src} payload={list(self.payload)}"
        )


@dataclasses.dataclass
class ReplayResult:
    state: LaneState
    trace: List[TraceEvent]

    @property
    def failed(self) -> bool:
        return bool(self.state.failed)

    @property
    def fail_code(self) -> int:
        return int(self.state.fail_code)


def replay_diff(
    engine: Engine,
    seed_a: int,
    seed_b: int,
    max_steps: int = 10_000,
    context: int = 3,
) -> Optional[int]:
    """Debugging aid: replay two seeds and report the first step where
    their event streams diverge (printing `context` events around it).
    Returns the diverging step index, or None if the shorter trace is a
    prefix of the longer (seeds that only differ later in latencies).

    Typical use: diff a failing seed against its nearest passing
    neighbor to see where the schedules fork."""
    ra = replay(engine, seed_a, max_steps=max_steps)
    rb = replay(engine, seed_b, max_steps=max_steps)

    def key(ev: TraceEvent):
        return (ev.time_us, ev.kind, ev.node, ev.src, ev.payload)

    for i, (ea, eb) in enumerate(zip(ra.trace, rb.trace)):
        if key(ea) != key(eb):
            lo = max(0, i - context)
            print(f"traces diverge at step {i}:")
            for j in range(lo, min(i + context + 1, min(len(ra.trace), len(rb.trace)))):
                marker = ">>" if j == i else "  "
                print(f"{marker} seed {seed_a}: {ra.trace[j]}")
                print(f"{marker} seed {seed_b}: {rb.trace[j]}")
            return i
    la, lb = len(ra.trace), len(rb.trace)
    if la != lb:
        print(f"trace of seed {seed_a} ({la} events) is a prefix-match of "
              f"seed {seed_b} ({lb} events); no per-event divergence")
    else:
        print(f"seeds {seed_a} and {seed_b} produced identical {la}-event traces")
    return None


def decode_ring(lane_ring) -> List[TraceEvent]:
    """Decode one lane's on-device event ring (Engine.ring_trace) into
    TraceEvents, oldest first. Entries with step < 0 are unused slots."""
    import numpy as np

    step = np.asarray(lane_ring["step"])
    order = np.argsort(step)  # unused (-1) sort first; slice them off
    order = order[step[order] >= 0]
    time_us = np.asarray(lane_ring["time"])
    kinds = np.asarray(lane_ring["kind"])
    node = np.asarray(lane_ring["node"])
    src = np.asarray(lane_ring["src"])
    pay = np.asarray(lane_ring["payload"])
    return [
        TraceEvent(
            step=int(step[i]),
            time_us=int(time_us[i]),
            kind=_KIND_NAMES.get(int(kinds[i]), "?"),
            node=int(node[i]),
            src=int(src[i]),
            payload=tuple(int(x) for x in pay[i]),
        )
        for i in order
    ]


def _replay_cache(engine: Engine) -> dict:
    """Compiled-replay cache, held on the MACHINE object so every Engine
    wrapping the same machine shares it (shrink builds a fresh Engine per
    candidate config; without sharing, each candidate pays a multi-second
    lane_step compile — the measured 10x collapse of high-find-rate
    hunts was exactly this, not the stream drain)."""
    return engine.machine.__dict__.setdefault("_replay_jit_cache", {})


def _trace_affecting_key(engine: Engine) -> tuple:
    """Config fields that change the lane_step trace. horizon_us is
    deliberately absent: the replay paths pass it as a traced value."""
    cfg = engine.config
    return (
        cfg.queue_capacity,
        cfg.latency_min_us,
        cfg.latency_max_us,
        cfg.packet_loss_rate,
        cfg.handler_rand_words,
        cfg.trace_ring,
        cfg.clog_packed,
        cfg.flight_recorder,
        cfg.fr_digest_every,
        cfg.fr_digest_ring,
        # PR-5/PR-6 chaos gates compiled INTO the step (defer logic,
        # skew scaling, amnesia/torn restarts, asymmetric-heal word
        # ops) — unlike the legacy kinds, which only shape the schedule
        # in the initial state
        cfg.faults.allow_pause,
        cfg.faults.allow_skew,
        cfg.faults.strict_restart,
        cfg.faults.allow_torn,
        cfg.faults.allow_heal_asym,
        cfg.provenance,  # lineage words compiled into the step
        engine._rng_layout,  # stream version + word-block layout (incl. dup)
        engine.use_pallas_pop,
    )


def _fast_outcome_fn(engine: Engine):
    """One jitted dispatch for a whole no-trace replay: while-loop of
    freeze-wrapped lane_steps (a done/failed lane passes through
    untouched, so the final state is bit-exactly the state at the
    stopping step). max_steps and horizon ride as traced scalars — one
    compile serves every shrink candidate and every seed."""
    from jax import lax

    cache = _replay_cache(engine)
    key = ("fast-outcome", _trace_affecting_key(engine))
    if key not in cache:

        def run(state: LaneState, horizon_us, n_steps):
            def body(_i, s):
                return lax.cond(
                    s.done | s.failed,
                    lambda x: x,
                    lambda x: engine.lane_step(x, horizon_us=horizon_us),
                    s,
                )

            return lax.fori_loop(0, n_steps, body, state)

        cache[key] = jax.jit(run)
    return cache[key]


def replay_outcome(engine: Engine, seed: int, max_steps: int = 10_000) -> ReplayResult:
    """Traceless replay of one seed in a single compiled dispatch —
    bit-identical final state (same lane_step ops), ~1000x fewer host
    round-trips than the eager trace path. The shrink verification
    workhorse."""
    import jax.numpy as jnp

    cpus = jax.devices("cpu")
    with jax.default_device(cpus[0]):
        state = engine.init_lane(seed)
        state = _fast_outcome_fn(engine)(
            state,
            jnp.int32(engine.config.horizon_us),
            jnp.int32(max_steps),
        )
        return ReplayResult(state=jax.device_get(state), trace=[])


def replay(
    engine: Engine,
    seed: int,
    max_steps: int = 10_000,
    on_step: Optional[Callable[[TraceEvent, LaneState], None]] = None,
    trace: bool = True,
) -> ReplayResult:
    """Replay one seed eagerly on CPU with a full event trace.

    `on_step(event, state)` is the debugging hook: runs as plain Python
    after every event — print, assert, drop into pdb, anything.

    With `trace=False` and no hook, the replay collapses into ONE
    compiled dispatch (`replay_outcome`) — same final state, none of the
    per-event host syncs.
    """
    if not trace and on_step is None:
        return replay_outcome(engine, seed, max_steps=max_steps)
    cpus = jax.devices("cpu")
    with jax.default_device(cpus[0]):
        state = engine.init_lane(seed)
        # jit the single-lane step: still bit-identical (XLA integer ops are
        # exact and threefry is backend-stable), but the replay materializes
        # the full state between events so hooks can inspect anything.
        # Cached on the machine so repeated replays don't recompile.
        cache = _replay_cache(engine)
        skey = ("trace-step", _trace_affecting_key(engine), engine.config.horizon_us)
        if skey not in cache:
            cache[skey] = jax.jit(engine.lane_step)
        step_fn = cache[skey]
        events: List[TraceEvent] = []
        step = 0
        prov_on = engine.config.provenance
        while not bool(state.done | state.failed) and step < max_steps:
            idx, any_valid = pop_earliest(state.eq_time, state.eq_seq, state.eq_valid)
            ev = TraceEvent(
                step=step,
                time_us=int(state.eq_time[idx]),
                kind=_KIND_NAMES.get(int(state.eq_kind[idx]), "?"),
                node=int(state.eq_node[idx]),
                src=int(state.eq_src[idx]),
                payload=tuple(int(x) for x in state.eq_payload[idx]),
                seq=int(state.eq_seq[idx]),
                prov=int(state.eq_prov[idx]) if prov_on else 0,
            ) if bool(any_valid) else None
            state = step_fn(state)
            if ev is not None:
                if trace:
                    events.append(ev)
                if on_step is not None:
                    on_step(ev, state)
            step += 1
        return ReplayResult(state=state, trace=events)
