"""Causal provenance — decoding "why did this seed fail?".

The step kernel (`EngineConfig.provenance`, engine/core.py) tags every
queued event and every node with a 32-bit lineage word: bit f = \"the
effects of scheduled fault f are in this value's causal past\", bits
30/31 = the two non-scheduled chaos channels (crash-with-amnesia wipes,
Bernoulli duplicate deliveries). Words OR along deliveries and the
violating lane's word is harvested with the failure ring. This module is
the host half:

  * `fault_schedule(engine, seed)` re-derives the seed's drawn fault
    schedule (kind, virtual time, target) from the same `init_lane`
    derivation the device ran — the decode table for the word's bits;
  * `implicated(engine, seed, word)` names the faults/kinds the word
    convicts (fault attribution: the hunt report / stats consumer);
  * `replay_with_lineage(engine, seed)` replays eagerly and
    reconstructs exact event-level causality from the queue sequence
    numbers (each step's push watermark says which step enqueued which
    seq), so `past_cone` can cut a trace to the violation's causal past
    — the `python -m madsim_tpu why` renderer and the Perfetto flow
    arrows (engine/trace_export.py) both read the result.

Soundness shape: the device word is an OVER-approximation of the true
cause set (a fault that touched a node marks everything the node later
influences, whether or not the influence mattered), never an
under-approximation for effects that flow through state and messages.
The consumers are honest about that: shrink treats attribution as a
candidate ORDER (every candidate is still verified by a full replay),
and `why` prints the word alongside the decoded faults.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from .core import (
    F_CLOG_DIR,
    F_CLOG_GROUP,
    F_CLOG_PAIR,
    F_DELAY_END,
    F_DELAY_SPIKE,
    F_HASYM,
    F_HASYM_HEAL,
    F_LOSS_END,
    F_LOSS_STORM,
    F_UNCLOG_DIR,
    F_UNCLOG_GROUP,
    F_UNCLOG_PAIR,
    FAULT_KIND_NAMES,
    PROV_BIT_AMNESIA,
    PROV_BIT_DUP,
    PROV_FAULT_BITS,
    Engine,
)
from .replay import ReplayResult, TraceEvent, replay

# fault ops whose provenance touches both payload endpoints / every node
# (host mirror of the step kernel's touched-mask classes)
_PAIR_OPS = {
    F_CLOG_PAIR, F_UNCLOG_PAIR, F_CLOG_DIR, F_UNCLOG_DIR,
    F_HASYM, F_HASYM_HEAL,
}
_GLOBAL_OPS = {
    F_CLOG_GROUP, F_UNCLOG_GROUP, F_LOSS_STORM, F_LOSS_END,
    F_DELAY_SPIKE, F_DELAY_END,
}

# attribution pseudo-kinds for the non-scheduled chaos bits — named like
# the CLI flags that enable them, so the implicated kind set is directly
# comparable with shrink's minimal `--fault-kinds` / `--strict-restart`
KIND_DUP = "dup"
KIND_AMNESIA = "strict-restart"


@dataclasses.dataclass(frozen=True)
class ScheduledFault:
    """One drawn fault of a lane's schedule, decoded to host values."""

    index: int          # schedule position (provenance bit = min(index, 29))
    kind: int           # K_* index
    kind_name: str      # FAULT_KIND_NAMES[kind]
    t_apply_us: int
    t_undo_us: int
    arg1: int           # payload[1] of the apply op (node a / mask lo / rate)
    arg2: int           # payload[2] (node b / mask hi / q10 / damage mask)
    t_heal2_us: Optional[int] = None  # heal-asym second-direction heal time

    @property
    def bit(self) -> int:
        return min(self.index, PROV_FAULT_BITS - 1)

    @property
    def target(self) -> str:
        k = self.kind_name
        if k in ("pair", "heal-asym"):
            return f"nodes {self.arg1}<->{self.arg2}"
        if k == "dir":
            return f"link {self.arg1}->{self.arg2}"
        if k == "group":
            return f"group mask 0x{(self.arg2 << 30) | self.arg1:x}"
        if k == "storm":
            return f"loss {self.arg1}/65536 (all links)"
        if k == "delay":
            return "all links"
        return f"node {self.arg1}"

    def describe(self) -> str:
        extra = ""
        if self.t_heal2_us is not None:
            extra = f", heal2 t={self.t_heal2_us}us"
        return (
            f"fault #{self.index} [bit {self.bit}]: {self.kind_name} on "
            f"{self.target}, apply t={self.t_apply_us}us, "
            f"undo t={self.t_undo_us}us{extra}"
        )


def _sched_fn(engine: Engine):
    """Jitted `seed -> fault-slot arrays` cached on the machine object
    (same discipline as the compiled-replay cache: shrink and hunts
    build many Engines over one machine)."""
    import jax

    cache = engine.machine.__dict__.setdefault("_prov_sched_cache", {})
    key = (engine.config.faults, engine.config.queue_capacity,
           engine.config.provenance, engine.config.rng_stream)
    if key not in cache:
        n = engine.machine.NUM_NODES
        spf = engine.config.faults.slots_per_fault
        nf = engine.config.faults.n_faults
        lo, hi = n, n + spf * nf

        def sched(seed):
            s = engine.init_lane(seed)
            return (
                s.eq_time[lo:hi], s.eq_payload[lo:hi], s.eq_valid[lo:hi]
            )

        cache[key] = jax.jit(sched)
    return cache[key]


def fault_schedule(engine: Engine, seed: int) -> List[ScheduledFault]:
    """Re-derive the fault schedule lane `seed` ran under — the decode
    table for its provenance bits. Reads the fault slots of the same
    `init_lane` derivation the device executed (bit-identical by the
    determinism contract)."""
    import numpy as np

    fp = engine.config.faults
    if fp.n_faults == 0:
        return []
    times, pays, valids = (np.asarray(x) for x in _sched_fn(engine)(seed))
    spf = fp.slots_per_fault
    out = []
    for f in range(fp.n_faults):
        apply_t = int(times[spf * f])
        undo_t = int(times[spf * f + 1])
        op, a1, a2 = (int(x) for x in pays[spf * f][:3])
        heal2 = None
        if fp.allow_heal_asym and bool(valids[spf * f + 2]):
            heal2 = int(times[spf * f + 2])
        kind = op // 2
        out.append(
            ScheduledFault(
                index=f,
                kind=kind,
                kind_name=FAULT_KIND_NAMES[kind],
                t_apply_us=apply_t,
                t_undo_us=undo_t,
                arg1=a1,
                arg2=a2,
                t_heal2_us=heal2,
            )
        )
    return out


@dataclasses.dataclass
class Attribution:
    """A violation provenance word decoded against its fault schedule."""

    word: int
    faults: List[ScheduledFault]   # scheduled faults the word implicates
    kinds: Tuple[str, ...]         # implicated kind names (sorted), incl.
    #                                the dup / strict-restart pseudo-kinds
    aliased: bool                  # >30 scheduled faults: bit 29 is shared

    def describe(self) -> List[str]:
        lines = [f.describe() for f in self.faults]
        if (self.word >> PROV_BIT_AMNESIA) & 1:
            lines.append(
                f"crash-with-amnesia wipe in lineage [bit {PROV_BIT_AMNESIA}]"
            )
        if (self.word >> PROV_BIT_DUP) & 1:
            lines.append(
                f"duplicate delivery in lineage [bit {PROV_BIT_DUP}]"
            )
        if self.aliased:
            lines.append(
                f"(schedule has more than {PROV_FAULT_BITS} faults: "
                f"bit {PROV_FAULT_BITS - 1} aliases the tail)"
            )
        return lines


def implicated(engine: Engine, seed: int, word: int) -> Attribution:
    """Decode a violation provenance word: which scheduled faults (and
    which non-scheduled chaos channels) are in the violation's past."""
    sched = fault_schedule(engine, seed)
    faults = [f for f in sched if (word >> f.bit) & 1]
    kinds: Set[str] = {f.kind_name for f in faults}
    if (word >> PROV_BIT_AMNESIA) & 1:
        kinds.add(KIND_AMNESIA)
    if (word >> PROV_BIT_DUP) & 1:
        kinds.add(KIND_DUP)
    return Attribution(
        word=word,
        faults=faults,
        kinds=tuple(sorted(kinds)),
        aliased=len(sched) > PROV_FAULT_BITS,
    )


def kind_counts(engine: Engine, prov_by_seed: Dict[int, int]) -> Dict[str, int]:
    """Per-kind fault-attribution marginals over a hunt's finds: how many
    failures implicate each chaos kind (a find counts once per kind).
    The per-find reward signal coverage-guided hunting needs, aggregated
    the way the stats JSONL / `/stats` service report it."""
    counts: Dict[str, int] = {}
    for seed, word in prov_by_seed.items():
        for k in implicated(engine, seed, word).kinds:
            counts[k] = counts.get(k, 0) + 1
    return dict(sorted(counts.items()))


# -- event-level lineage (the `why` cone) ------------------------------------


@dataclasses.dataclass
class Lineage:
    """Exact event-level causality of one replayed seed.

    `parents[i]` are trace indices that causally precede trace event i
    by one hop: the step that ENQUEUED it (send->delivery / arm->fire /
    schedule->injection), plus the previous step at each node the event
    touched (program order — the state it read). `seq_pusher` maps queue
    sequence numbers to the trace index that pushed them."""

    trace: List[TraceEvent]
    parents: List[Set[int]]
    seq_pusher: Dict[int, int]
    # per-step next_seq watermarks (after each step): step i pushed the
    # seqs in [watermark[i-1], watermark[i]) — kept so host oracles can
    # re-derive lineage words independently (tests/test_provenance.py)
    next_seq_after: List[int] = dataclasses.field(default_factory=list)

    def past_cone(self, target: int) -> List[int]:
        """Trace indices in the causal past of trace event `target`
        (inclusive), ascending."""
        seen = {target}
        frontier = [target]
        while frontier:
            nxt = []
            for i in frontier:
                for p in self.parents[i]:
                    if p not in seen:
                        seen.add(p)
                        nxt.append(p)
            frontier = nxt
        return sorted(seen)

    def message_flows(self) -> List[Tuple[int, int]]:
        """(sender trace index, delivery trace index) pairs for every
        delivered message with a known pusher — the Perfetto flow
        arrows."""
        out = []
        for j, ev in enumerate(self.trace):
            if ev.kind == "msg" and ev.seq in self.seq_pusher:
                out.append((self.seq_pusher[ev.seq], j))
        return out


def _touched_nodes(ev: TraceEvent, num_nodes: int) -> List[int]:
    """Host mirror of the step kernel's provenance touched-mask."""
    if ev.kind != "fault":
        return [ev.node]
    op = ev.payload[0]
    if op in _GLOBAL_OPS:
        return list(range(num_nodes))
    if op in _PAIR_OPS:
        return sorted({ev.payload[1], ev.payload[2]})
    return [ev.payload[1]]


def build_lineage(
    engine: Engine, trace: List[TraceEvent], next_seq_after: List[int]
) -> Lineage:
    """Reconstruct event-level causality from a replayed trace plus the
    per-step `next_seq` watermarks (`replay_with_lineage` captures
    them): step i pushed exactly the seqs in [watermark[i-1],
    watermark[i]), so every later pop of such a seq has step i as its
    enqueueing parent."""
    n = engine.machine.NUM_NODES
    fp = engine.config.faults
    init_seq = n + fp.slots_per_fault * fp.n_faults
    horizon = engine.config.horizon_us
    seq_pusher: Dict[int, int] = {}
    prev = init_seq
    for i, after in enumerate(next_seq_after):
        for q in range(prev, after):
            seq_pusher[q] = i
        prev = after
    parents: List[Set[int]] = []
    last_touch: Dict[int, int] = {}
    for i, ev in enumerate(trace):
        ps: Set[int] = set()
        if ev.seq in seq_pusher and seq_pusher[ev.seq] < i:
            ps.add(seq_pusher[ev.seq])
        touched = _touched_nodes(ev, n)
        for node in touched:
            if node in last_touch:
                ps.add(last_touch[node])
        parents.append(ps)
        if ev.time_us < horizon:  # horizon-hit pops are never processed
            for node in touched:
                last_touch[node] = i
    return Lineage(
        trace=trace, parents=parents, seq_pusher=seq_pusher,
        next_seq_after=list(next_seq_after),
    )


def replay_with_lineage(
    engine: Engine, seed: int, max_steps: int = 10_000
) -> Tuple[ReplayResult, Lineage]:
    """Eager traced replay + exact lineage reconstruction. Works with the
    provenance gate on OR off (lineage needs only the queue sequence
    numbers); with the gate on, every TraceEvent additionally carries
    its device-identical provenance word and the final state carries
    `fail_prov`."""
    marks: List[int] = []

    def hook(_ev, state) -> None:
        marks.append(int(state.next_seq))

    rp = replay(engine, seed, max_steps=max_steps, on_step=hook)
    return rp, build_lineage(engine, rp.trace, marks)


def render_why(
    engine: Engine,
    seed: int,
    rp: ReplayResult,
    lineage: Lineage,
    cone: List[int],
    attribution: Attribution,
    max_events: int = 0,
) -> str:
    """The `why <seed>` text report: verdict line, decoded implicated
    faults, then the violation's past cone as an annotated event list
    (implicated-fault injections flagged, message hops shown)."""
    lines = [
        f"seed {seed} fails with code {rp.fail_code} at "
        f"t={int(rp.state.now_us)}us after {len(lineage.trace)} events",
        f"violation provenance word: 0x{attribution.word:08x}",
        "implicated faults:",
    ]
    lines += ["  " + d for d in attribution.describe()] or [
        "  none (violation is fault-free)"
    ]
    lines.append("implicated kinds: " + (",".join(attribution.kinds) or "none"))
    shown = cone if not max_events else cone[-max_events:]
    lines.append(
        f"causal past cone: {len(cone)} of {len(lineage.trace)} events"
        + (f" (last {len(shown)} shown)" if len(shown) < len(cone) else "")
    )
    implicated_steps = {
        lineage.trace[i].step
        for i in cone
        if lineage.trace[i].kind == "fault"
    }
    for i in shown:
        ev = lineage.trace[i]
        mark = "!" if ev.step in implicated_steps else " "
        hop = ""
        if ev.kind == "msg" and ev.seq in lineage.seq_pusher:
            hop = f"  <= #{lineage.trace[lineage.seq_pusher[ev.seq]].step}"
        lines.append(f" {mark} {ev!r}{hop}")
    return "\n".join(lines)
