"""Failing-seed corpus — found bugs as durable regression artifacts.

The reference's workflow stops at printing `MADSIM_TEST_SEED=N` repro
hints; FoundationDB-style DST practice goes further: every found seed
becomes a corpus entry that is re-verified forever. An entry is "open"
while the bug reproduces (the repro must keep failing — if it stops,
the bug was fixed, or the repro rotted) and "fixed" once resolved (the
seed must pass forever — failing again is a regression alarm).

Entries carry everything needed to rebuild the run: machine name (CLI
registry), node count, seed, expected fail code, the (shrunk) engine
config, and a sufficient step budget. `python -m madsim_tpu hunt`
explores + shrinks + appends; `python -m madsim_tpu regress` re-verifies
every entry bit-identically on the host replay path.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Callable, List, Optional

from .core import Engine, EngineConfig, FaultPlan
from .replay import replay

STATUS_OPEN = "open"    # bug reproduces: entry must keep failing with its code
STATUS_FIXED = "fixed"  # bug resolved: entry must keep passing


def config_to_dict(cfg: EngineConfig) -> dict:
    d = dataclasses.asdict(cfg)
    # host-side knobs, never trace-affecting: a corpus entry must replay
    # on any machine — not name some other box's cache directory, and
    # not demand (or forbid) the fused step kernel its recording box
    # happened to resolve (the megakernel is asserted bit-identical to
    # the XLA oracle under its gate)
    d.pop("compile_cache_dir", None)
    d.pop("pallas_megakernel", None)
    # the flight recorder is asserted bit-identical under its gate, so
    # entries don't record it: the digest trail lives in the entry's own
    # digests/digest_final fields, and the auditor re-enables the
    # recorder itself at the recorded cadence
    for k in ("flight_recorder", "fr_digest_every", "fr_digest_ring"):
        d.pop(k, None)
    # scenario coverage is the same class of gate: write-only telemetry,
    # asserted bit-identical — entries must replay with or without it
    # (cov_buffer is the buffered-fold perf knob: final maps are
    # bit-identical to the per-event path, so it never enters an entry)
    for k in ("coverage", "cov_slots_log2", "cov_band_bits_min", "cov_buffer"):
        d.pop(k, None)
    # causal provenance too: lineage words never feed back into results,
    # and `why` re-enables the gate itself at replay time
    d.pop("provenance", None)
    return d


def config_from_dict(d: dict) -> EngineConfig:
    d = dict(d)
    faults = d.pop("faults", None)
    cfg = EngineConfig(**d, faults=FaultPlan(**faults) if faults else FaultPlan())
    return cfg


@dataclasses.dataclass
class CorpusEntry:
    machine: str
    seed: int
    fail_code: int
    status: str  # STATUS_OPEN | STATUS_FIXED
    config: EngineConfig
    max_steps: int
    nodes: int = 0
    note: str = ""
    # Flight-recorder provenance (engine/audit.py): the digest trail
    # recorded when the entry was (re-)recorded — checkpoints every
    # `digest_every` steps as [step, d0, d1], the final [step, d0, d1],
    # and the environment fingerprint (jax/jaxlib/python/engine
    # versions) it was recorded under. `python -m madsim_tpu audit`
    # replays the entry and bisects this trail to the first divergent
    # checkpoint; entries predating the recorder carry empty trails.
    digest_every: int = 0
    digests: list = dataclasses.field(default_factory=list)
    digest_final: list = dataclasses.field(default_factory=list)
    # Free-form provenance. `audit.record_entry` merges the environment
    # fingerprint (jax/jaxlib/python/engine versions) in here; entries
    # filed by the hunt fleet additionally carry `filed_by` ({job,
    # worker, fingerprint_sha} — which fleet job found this), `repro`
    # (the minimal replay command line) and `why_kinds` (the causally
    # implicated fault kinds from the provenance word). Keys survive
    # re-recording: the auditor merges rather than replaces.
    meta: dict = dataclasses.field(default_factory=dict)

    @property
    def key(self) -> tuple:
        return (self.machine, self.nodes, self.seed, self.fail_code)

    def to_dict(self) -> dict:
        d = {
            "machine": self.machine,
            "nodes": self.nodes,
            "seed": self.seed,
            "fail_code": self.fail_code,
            "status": self.status,
            "max_steps": self.max_steps,
            "note": self.note,
            "config": config_to_dict(self.config),
        }
        if self.digest_every:
            d["digest_every"] = self.digest_every
            d["digests"] = [[int(x) for x in ck] for ck in self.digests]
            d["digest_final"] = [int(x) for x in self.digest_final]
        if self.meta:
            d["meta"] = dict(self.meta)
        return d

    @staticmethod
    def from_dict(d: dict) -> "CorpusEntry":
        return CorpusEntry(
            machine=d["machine"],
            nodes=int(d.get("nodes", 0)),
            seed=int(d["seed"]),
            fail_code=int(d["fail_code"]),
            status=d.get("status", STATUS_OPEN),
            max_steps=int(d["max_steps"]),
            note=d.get("note", ""),
            config=config_from_dict(d["config"]),
            digest_every=int(d.get("digest_every", 0)),
            digests=[[int(x) for x in ck] for ck in d.get("digests", [])],
            digest_final=[int(x) for x in d.get("digest_final", [])],
            meta=dict(d.get("meta", {})),
        )


def load(path: str) -> List[CorpusEntry]:
    if not os.path.exists(path):
        return []
    with open(path) as f:
        data = json.load(f)
    return [CorpusEntry.from_dict(d) for d in data.get("entries", [])]


def save(path: str, entries: List[CorpusEntry]) -> None:
    from ..runtime.atomicio import atomic_write_json

    atomic_write_json(
        path, {"version": 1, "entries": [e.to_dict() for e in entries]},
        indent=2, sort_keys=False,
    )


def add(path: str, entry: CorpusEntry) -> bool:
    """Append an entry unless one with the same (machine, nodes, seed,
    code) already exists. Returns True if added."""
    entries = load(path)
    if any(e.key == entry.key for e in entries):
        return False
    entries.append(entry)
    save(path, entries)
    return True


@dataclasses.dataclass
class RegressOutcome:
    entry: CorpusEntry
    failed: bool            # did the replay fail
    fail_code: int
    ok: bool                # outcome matches the entry's status contract
    verdict: str            # human-readable disposition


def check(entry: CorpusEntry, build_machine: Callable[[str, int], object]) -> RegressOutcome:
    """Re-run one entry on the host replay path and judge it against its
    status contract. `build_machine(name, nodes)` resolves the machine."""
    eng = Engine(build_machine(entry.machine, entry.nodes), entry.config)
    rp = replay(eng, entry.seed, max_steps=entry.max_steps, trace=False)
    failed = bool(rp.failed)
    code = int(rp.fail_code)
    same_failure = failed and code == entry.fail_code
    if entry.status == STATUS_OPEN:
        if same_failure:
            return RegressOutcome(entry, failed, code, True, "still open (reproduces)")
        if failed:
            return RegressOutcome(
                entry, failed, code, False,
                f"DRIFT: fails with code {code}, expected {entry.fail_code}",
            )
        return RegressOutcome(
            entry, failed, code, False,
            "appears FIXED (no longer reproduces) — re-run with --promote",
        )
    # STATUS_FIXED: must pass
    if not failed:
        return RegressOutcome(entry, failed, code, True, "fixed (still passes)")
    return RegressOutcome(
        entry, failed, code, False, f"REGRESSION: fails again with code {code}"
    )
