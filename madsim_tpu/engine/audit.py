"""Divergence auditor — digest trails and first-divergent-step bisection.

The flight recorder (core.py) folds every popped event tuple + step-RNG
word block into a rolling per-lane digest and checkpoints it every
`fr_digest_every` steps. Two executions of the same (machine, config,
seed) agree on a checkpoint exactly as far as their event streams agree,
and once diverged they stay diverged (the fold is a bijective mix per
word, so re-convergence is a ~2^-64 accident). That monotonicity is what
makes the checkpoint trail *bisectable*: the first divergent checkpoint
localizes a determinism break — corpus rot, a stream-version skew, a
jax upgrade that moved threefry, a broken engine change — to one
`fr_digest_every`-step segment without storing full traces.

Corpus entries record their trail at hunt/record time
(`CorpusEntry.digests` + `digest_final` + environment `meta`);
`python -m madsim_tpu audit` replays each entry on the host replay path
(the bit-identity oracle) and reports "first divergent checkpoint at
step k: expected d₀ got d₁" — turning "the corpus rotted" from folklore
into a one-command diagnosis.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Sequence, Tuple

from .core import Engine

DEFAULT_DIGEST_EVERY = 64


@dataclasses.dataclass(frozen=True)
class DigestTrail:
    """One execution's digest trail: checkpoints at exact step multiples
    of `every`, plus the final (step, digest) when the lane stopped."""

    every: int
    checkpoints: Tuple[Tuple[int, int, int], ...]  # (step, d0, d1), ascending
    final_step: int
    final: Tuple[int, int]  # (d0, d1) at the stopping step
    failed: bool
    fail_code: int

    def to_lists(self) -> Tuple[List[List[int]], List[int]]:
        """(digests, digest_final) in the corpus JSON shape."""
        return (
            [[s, d0, d1] for s, d0, d1 in self.checkpoints],
            [self.final_step, *self.final],
        )


def decode_checkpoint_ring(lane_fr) -> List[Tuple[int, int, int]]:
    """Decode one lane's checkpoint ring (LaneState.fr slice) into
    (step, d0, d1) tuples, oldest first. Slots with step < 0 are unused."""
    import numpy as np

    steps = np.asarray(lane_fr["ck_step"])
    order = np.argsort(steps, kind="stable")
    order = order[steps[order] >= 0]
    d0 = np.asarray(lane_fr["ck_d0"])
    d1 = np.asarray(lane_fr["ck_d1"])
    return [(int(steps[i]), int(d0[i]), int(d1[i])) for i in order]


def fr_variant(engine: Engine, every: int, ring: int) -> Engine:
    """An Engine identical to `engine` but with the flight recorder on at
    the given checkpoint cadence. Because the recorder is asserted
    bit-identical under its gate, the trail is a property of the
    underlying run, not of the recording."""
    cfg = dataclasses.replace(
        engine.config,
        flight_recorder=True,
        fr_digest_every=every,
        fr_digest_ring=ring,
    )
    return Engine(engine.machine, cfg, use_pallas_pop=engine.use_pallas_pop)


def collect_trail(
    engine: Engine,
    seed: int,
    max_steps: int,
    every: int = DEFAULT_DIGEST_EVERY,
) -> DigestTrail:
    """Replay one seed on the host replay path (single compiled dispatch,
    bit-identical to the device lane) with the recorder on, retaining
    EVERY checkpoint (the ring is sized past max_steps, so it never
    wraps)."""
    from .replay import replay

    eng = engine
    if (
        not engine.config.flight_recorder
        or engine.config.fr_digest_every != every
        or engine.config.fr_digest_ring * every <= max_steps
    ):
        eng = fr_variant(engine, every, max_steps // every + 2)
    rp = replay(eng, seed, max_steps=max_steps, trace=False)
    fr = rp.state.fr
    return DigestTrail(
        every=every,
        checkpoints=tuple(decode_checkpoint_ring(fr)),
        final_step=int(rp.state.step),
        final=(int(fr["d0"]), int(fr["d1"])),
        failed=bool(rp.state.failed),
        fail_code=int(rp.state.fail_code),
    )


@dataclasses.dataclass(frozen=True)
class Divergence:
    """First point where a replayed trail leaves the recorded one."""

    step: int  # recorded checkpoint (or final) step that mismatched
    expected: Tuple[int, int]
    got: Optional[Tuple[int, int]]  # None: replay never reached that step
    segment: Tuple[int, int]  # (last agreeing step, first divergent step]
    at_final: bool  # divergence surfaced only at the final digest

    def __str__(self) -> str:
        got = (
            f"got {self.got[0]:#010x}:{self.got[1]:#010x}"
            if self.got is not None
            else "replay never reached that step"
        )
        where = "final digest" if self.at_final else "checkpoint"
        return (
            f"first divergent {where} at step {self.step} (segment "
            f"({self.segment[0]}, {self.segment[1]}]): expected "
            f"{self.expected[0]:#010x}:{self.expected[1]:#010x}, {got}"
        )


def first_divergence(
    recorded: Sequence[Sequence[int]],
    recorded_final: Optional[Sequence[int]],
    replayed: DigestTrail,
) -> Optional[Divergence]:
    """Binary-search the recorded checkpoint list for the first entry the
    replayed trail contradicts.

    Divergence is monotone along the trail (streams that have forked
    never re-agree), so "checkpoint i mismatches" is a sorted predicate
    and O(log n) probes suffice — the protocol stays cheap even for
    trails with thousands of checkpoints. Returns None when every
    checkpoint AND the final digest agree.
    """
    rep = {s: (d0, d1) for s, d0, d1 in replayed.checkpoints}
    rec = [(int(s), int(d0), int(d1)) for s, d0, d1 in recorded]

    def bad(i: int) -> bool:
        s, d0, d1 = rec[i]
        return rep.get(s) != (d0, d1)

    first_bad = len(rec)
    lo, hi = 0, len(rec) - 1
    while lo <= hi:
        mid = (lo + hi) // 2
        if bad(mid):
            first_bad = mid
            hi = mid - 1
        else:
            lo = mid + 1
    if first_bad < len(rec):
        s, d0, d1 = rec[first_bad]
        prev = rec[first_bad - 1][0] if first_bad else 0
        return Divergence(
            step=s,
            expected=(d0, d1),
            got=rep.get(s),
            segment=(prev, s),
            at_final=False,
        )
    if recorded_final is not None:
        fs, fd0, fd1 = (int(x) for x in recorded_final)
        if (fs, fd0, fd1) != (replayed.final_step, *replayed.final):
            prev = rec[-1][0] if rec else 0
            return Divergence(
                step=fs,
                expected=(fd0, fd1),
                got=replayed.final,
                segment=(prev, fs),
                at_final=True,
            )
    return None


def engine_meta(config) -> dict:
    """Environment fingerprint recorded next to a digest trail — when an
    audit later reports divergence, this says what the trail was
    recorded UNDER (the usual rot suspects: jax/jaxlib upgrade, python
    major, engine stream version)."""
    import platform

    import jax
    import jaxlib

    import madsim_tpu

    return {
        "madsim_tpu": getattr(madsim_tpu, "__version__", "?"),
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "python": platform.python_version(),
        "rng_stream": config.rng_stream,
        "digest": "fr-v1",
    }


@dataclasses.dataclass
class AuditOutcome:
    entry: object  # CorpusEntry
    status: str  # "match" | "diverged" | "no-digests"
    divergence: Optional[Divergence]
    trail: DigestTrail
    verdict: str

    @property
    def ok(self) -> bool:
        return self.status != "diverged"


def audit_entry(entry, build_machine: Callable[[str, int], object]) -> AuditOutcome:
    """Replay one corpus entry on the host path and bisect its recorded
    digest trail. Also cross-checks the behavioral outcome (fail code)
    so a divergence report says whether the finding itself survived."""
    eng = Engine(build_machine(entry.machine, entry.nodes), entry.config)
    every = entry.digest_every or DEFAULT_DIGEST_EVERY
    trail = collect_trail(eng, entry.seed, entry.max_steps, every=every)
    behavior = (
        f"replay {'fails with code ' + str(trail.fail_code) if trail.failed else 'passes'}"
        f" at step {trail.final_step} (entry expects code {entry.fail_code})"
    )
    if not entry.digests and not entry.digest_final:
        return AuditOutcome(
            entry, "no-digests", None, trail,
            f"no recorded digests (re-record with `audit --record`); {behavior}",
        )
    div = first_divergence(entry.digests, entry.digest_final or None, trail)
    if div is None:
        return AuditOutcome(
            entry, "match", None, trail,
            f"digest trail matches ({len(entry.digests)} checkpoints); {behavior}",
        )
    return AuditOutcome(entry, "diverged", div, trail, f"{div}; {behavior}")


def record_entry(
    entry,
    build_machine: Callable[[str, int], object],
    every: int = DEFAULT_DIGEST_EVERY,
):
    """Re-record one corpus entry's digest trail + environment metadata
    at HEAD. Returns (updated_entry, trail) — the trail carries the
    behavioral outcome (failed / fail_code) so callers can check the
    entry's status contract before saving."""
    eng = Engine(build_machine(entry.machine, entry.nodes), entry.config)
    trail = collect_trail(eng, entry.seed, entry.max_steps, every=every)
    digests, final = trail.to_lists()
    new = dataclasses.replace(
        entry,
        digest_every=every,
        digests=digests,
        digest_final=final,
        # MERGE with the caller's meta rather than replacing it: the
        # fleet files entries with provenance keys (`filed_by`,
        # `repro`, `why_kinds`) that must survive re-recording; the
        # environment fingerprint wins on any key collision.
        meta={**entry.meta, **engine_meta(entry.config)},
    )
    return new, trail
