"""Failing-seed shrinking — minimize the reproduction of a flagged seed.

The reference reproduces a failure with `MADSIM_TEST_SEED=N` and the full
original config; this module goes further and bisects the *config* down
to a minimal one that still reproduces the same failure code:

  * fewer injected faults (fault i's parameters are drawn from an
    independent key-chain position, so a plan with n_faults=f keeps the
    first f faults bit-identical — candidates are honest prefixes)
  * packet loss off (if it was on)
  * fault-KIND ablation: each enabled `allow_*` chaos flag (and
    `strict_restart`) is tried off — candidates whose honest replay
    still fails with the same code drop the kind, so the result names
    the minimal chaos vocabulary. (Turning a scheduled kind off changes
    the remaining faults' drawn parameters — that's fine: every
    candidate is verified by a full replay, never assumed.)
  * horizon cut to just past the failure time
  * step budget cut to just past the failing step

Every candidate is verified by an actual replay; the result reports only
transformations that kept the SAME fail code. Exposed as
`python -m madsim_tpu shrink --machine M --seed N ...`.

With `EngineConfig.provenance` (or an explicit `prov_word`), the
violation's causal-provenance word steers the candidate ORDER — the
fault-count scan jumps straight to the smallest prefix containing every
implicated fault, and the kind ablation bulk-drops the non-implicated
kinds in one candidate — cutting replays on multi-fault finds while the
verify-by-replay contract stays intact (attribution is an
over-approximation and is never trusted, only used to order guesses).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ..kinds import FLAG_BY_KIND
from .core import Engine, EngineConfig
from .replay import ReplayResult, replay


# Ablation order: newest/most-exotic kinds first so the reported
# minimal set leans on the legacy vocabulary when possible. The order
# is shrink policy; the name -> FaultPlan-field pairing comes from the
# shared madsim_tpu/kinds.py table (lint rule G003 asserts this list
# covers the whole vocabulary). Each entry is (report name, field).
ABLATION_ORDER = (
    "torn", "heal-asym", "delay", "storm", "group", "dir",
    "pause", "skew", "dup", "strict-restart", "kill", "pair",
)
ABLATABLE_KINDS = tuple((name, FLAG_BY_KIND[name]) for name in ABLATION_ORDER)


@dataclasses.dataclass
class ShrinkResult:
    seed: int
    fail_code: int
    original: EngineConfig
    shrunk: EngineConfig
    steps: int              # events to failure under the shrunk config
                            # (itself a sufficient --max-steps budget)
    fail_time_us: int
    attempts: int           # replays spent shrinking
    kinds_removed: tuple = ()  # chaos flags ablated off (honest replays)
    guided: bool = False       # provenance attribution steered the order
    prov_kinds: tuple = ()     # kinds the violation's provenance implicated

    def summary(self) -> str:
        o, s = self.original, self.shrunk
        parts = []
        if s.faults.n_faults != o.faults.n_faults:
            parts.append(f"faults {o.faults.n_faults} -> {s.faults.n_faults}")
        if s.packet_loss_rate != o.packet_loss_rate:
            parts.append(f"loss {o.packet_loss_rate} -> 0")
        if self.kinds_removed:
            parts.append("kinds -" + ",-".join(self.kinds_removed))
        if s.horizon_us != o.horizon_us:
            parts.append(f"horizon {o.horizon_us}us -> {s.horizon_us}us")
        changed = "; ".join(parts) if parts else "config already minimal"
        guided = (
            f", provenance-guided by [{','.join(self.prov_kinds)}]"
            if self.guided else ""
        )
        return (
            f"seed {self.seed} fails with code {self.fail_code} in "
            f"{self.steps} events (t={self.fail_time_us}us); {changed} "
            f"[{self.attempts} verification replays{guided}]"
        )


def _fails_same(engine: Engine, seed: int, max_steps: int, code: int) -> Optional[ReplayResult]:
    rp = replay(engine, seed, max_steps=max_steps, trace=False)
    if rp.failed and rp.fail_code == code:
        return rp
    return None


def shrink(
    engine: Engine,
    seed: int,
    max_steps: int = 10_000,
    prov_word: Optional[int] = None,
) -> ShrinkResult:
    """Minimize the failing configuration for `seed`.

    With a violation provenance word (`prov_word`, or for free from the
    base replay when `engine.config.provenance` is on), attribution
    steers the candidate ORDER: the fault-count scan first tries the
    smallest prefix that still contains every implicated fault, and the
    kind ablation first tries every NON-implicated kind off in one bulk
    candidate — cutting the replay count on multi-fault finds. Guidance
    never weakens the contract: every accepted candidate is still
    verified by a full honest replay reproducing the same fail code
    (attribution over-approximates, so a guided guess can fail — the
    scan then falls back to the unguided order).

    Raises ValueError if the seed does not fail under the given engine.
    """
    base = replay(engine, seed, max_steps=max_steps, trace=False)
    if not base.failed:
        raise ValueError(
            f"seed {seed} does not fail under this config (within "
            f"{max_steps} steps) — nothing to shrink"
        )
    code = base.fail_code
    attempts = 1
    cfg = engine.config
    best = base

    # provenance attribution (when available): implicated fault indices
    # + kind names — the candidate-ordering hints
    if prov_word is None and engine.config.provenance:
        prov_word = int(base.state.fail_prov)
    att = None
    if prov_word:
        from .provenance import implicated

        att = implicated(engine, seed, int(prov_word))
    guided = att is not None
    imp_kinds = set(att.kinds) if att else set()

    # 1. fewest faults whose prefix-plan still reproduces (linear scan from
    #    zero: the minimal candidate first). Guided: a prefix can only
    #    reproduce if it contains the implicated faults, so try the
    #    smallest such prefix FIRST — on a hit that is ONE replay where
    #    the unguided scan pays max(implicated)+2; on a miss (attribution
    #    over-approximated nothing away) fall back to the full scan.
    def try_n_faults(f: int):
        cand_cfg = dataclasses.replace(
            cfg, faults=dataclasses.replace(cfg.faults, n_faults=f)
        )
        rp = _fails_same(Engine(engine.machine, cand_cfg), seed, max_steps, code)
        return cand_cfg, rp

    guessed = False
    tried_guess = None
    if att and att.faults and not att.aliased:
        guess = max(f.index for f in att.faults) + 1
        if guess < cfg.faults.n_faults:
            attempts += 1
            tried_guess = guess
            cand_cfg, rp = try_n_faults(guess)
            if rp is not None:
                cfg, best = cand_cfg, rp
                guessed = True
    if not guessed:
        for f in range(cfg.faults.n_faults):
            if f == tried_guess:
                continue  # already replayed above
            attempts += 1
            cand_cfg, rp = try_n_faults(f)
            if rp is not None:
                cfg, best = cand_cfg, rp
                break

    # 2. packet loss off
    if cfg.packet_loss_rate > 0:
        cand_cfg = dataclasses.replace(cfg, packet_loss_rate=0.0)
        attempts += 1
        rp = _fails_same(Engine(engine.machine, cand_cfg), seed, max_steps, code)
        if rp is not None:
            cfg, best = cand_cfg, rp

    # 3. fault-kind ablation: try each enabled chaos flag off. Honest —
    #    every candidate is a full replay required to reproduce the SAME
    #    fail code; flags whose removal changes the outcome stay. A
    #    scheduled plan must keep at least one kind (the constructor
    #    rejects an empty vocabulary with n_faults > 0).
    #    Guided: attribution names the implicated kinds, so first try
    #    every NON-implicated kind off in ONE bulk candidate — on a hit
    #    the per-kind scan then only visits the implicated kinds
    #    (1 + |implicated| replays instead of |enabled|).
    kinds_removed = []
    enabled = [
        (name, field)
        for name, field in ABLATABLE_KINDS
        if getattr(cfg.faults, field)
    ]
    scan = enabled
    if guided:
        non_imp = [(n, f) for n, f in enabled if n not in imp_kinds]
        if len(non_imp) >= 2:
            bulk_faults = dataclasses.replace(
                cfg.faults, **{f: False for _n, f in non_imp}
            )
            if bulk_faults.n_faults == 0 or bulk_faults.enabled_kinds():
                cand_cfg = dataclasses.replace(cfg, faults=bulk_faults)
                attempts += 1
                rp = _fails_same(
                    Engine(engine.machine, cand_cfg), seed, max_steps, code
                )
                if rp is not None:
                    cfg, best = cand_cfg, rp
                    kinds_removed.extend(n for n, _f in non_imp)
                    # only the implicated kinds are left to try
                    scan = [(n, f) for n, f in enabled if n in imp_kinds]
    for kind_name, field in scan:
        if not getattr(cfg.faults, field):
            continue
        cand_faults = dataclasses.replace(cfg.faults, **{field: False})
        if cand_faults.n_faults > 0 and not cand_faults.enabled_kinds():
            continue
        cand_cfg = dataclasses.replace(cfg, faults=cand_faults)
        attempts += 1
        rp = _fails_same(Engine(engine.machine, cand_cfg), seed, max_steps, code)
        if rp is not None:
            cfg, best = cand_cfg, rp
            kinds_removed.append(kind_name)

    # 4. horizon just past the failure (sound by construction — events at
    #    t < horizon are unaffected by the horizon value — but verified)
    fail_t = int(best.state.now_us)
    if fail_t + 1 < cfg.horizon_us:
        cand_cfg = dataclasses.replace(cfg, horizon_us=fail_t + 1)
        attempts += 1
        rp = _fails_same(Engine(engine.machine, cand_cfg), seed, max_steps, code)
        if rp is not None:
            cfg, best = cand_cfg, rp

    # 5. the exact failing step count is itself a sufficient step budget
    steps = int(best.state.step)
    return ShrinkResult(
        seed=seed,
        fail_code=code,
        original=engine.config,
        shrunk=cfg,
        steps=steps,
        fail_time_us=int(best.state.now_us),
        attempts=attempts,
        kinds_removed=tuple(kinds_removed),
        guided=guided,
        prov_kinds=tuple(att.kinds) if att else (),
    )
