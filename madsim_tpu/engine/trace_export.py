"""Host-side trace export: Perfetto/Chrome trace_event JSON and JSONL.

A replayed seed's event trace is a virtual-time timeline: every popped
event names the node that handled it and the virtual microsecond it ran
at. The Chrome `trace_event` export maps that onto the profiler UI's
native model — one process per simulated seed, one thread row per node,
instant events at virtual timestamps — so `chrome://tracing` or
https://ui.perfetto.dev renders a seed's schedule (elections, message
storms, fault windows) exactly like a CPU profile, scrubber and all.

The JSONL export is the machine-readable sibling: one JSON object per
event, grep/jq-able, stable keys — the structured counterpart of
`replay --tail`'s human lines (the logging-based JSONL sink for *live*
host-runtime logs is `tracing.JsonlHandler`; this module serializes
engine traces).
"""

from __future__ import annotations

import json
from typing import List, Optional

from .replay import TraceEvent


def trace_event_dict(
    events: List[TraceEvent],
    *,
    machine: str = "machine",
    seed: int = 0,
    num_nodes: Optional[int] = None,
) -> dict:
    """Build the Chrome trace_event JSON object (dict) for one replayed
    seed. Timestamps are VIRTUAL microseconds (trace_event's native
    unit, so the UI's time axis reads as simulation time directly)."""
    pid = 0
    out: List[dict] = [
        {
            "ph": "M",
            "pid": pid,
            "name": "process_name",
            "args": {"name": f"{machine} seed {seed}"},
        }
    ]
    nodes = sorted({ev.node for ev in events})
    if num_nodes is not None:
        nodes = sorted(set(nodes) | set(range(num_nodes)))
    for n in nodes:
        out.append(
            {
                "ph": "M",
                "pid": pid,
                "tid": n,
                "name": "thread_name",
                "args": {"name": f"node {n}"},
            }
        )
        # sort_index keeps node rows in id order (tracing UIs otherwise
        # order threads by first event)
        out.append(
            {
                "ph": "M",
                "pid": pid,
                "tid": n,
                "name": "thread_sort_index",
                "args": {"sort_index": n},
            }
        )
    for ev in events:
        name = ev.kind
        if ev.kind == "msg":
            name = f"msg<-{ev.src}"
        elif ev.kind == "fault":
            name = f"fault op={ev.payload[0]}"
        elif ev.kind == "timer":
            name = f"timer id={ev.payload[0]}"
        out.append(
            {
                "ph": "i",  # instant: handlers take zero virtual time
                "s": "t",  # thread-scoped marker
                "pid": pid,
                "tid": ev.node,
                "ts": ev.time_us,
                "name": name,
                "args": {
                    "step": ev.step,
                    "src": ev.src,
                    "payload": list(ev.payload),
                },
            }
        )
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def write_perfetto(
    path: str,
    events: List[TraceEvent],
    *,
    machine: str = "machine",
    seed: int = 0,
    num_nodes: Optional[int] = None,
) -> int:
    """Write the Perfetto/Chrome trace_event JSON file. Returns the
    number of trace events written (excluding metadata records)."""
    doc = trace_event_dict(events, machine=machine, seed=seed, num_nodes=num_nodes)
    with open(path, "w") as f:
        json.dump(doc, f)
        f.write("\n")
    return len(events)


def write_jsonl(
    path: str,
    events: List[TraceEvent],
    *,
    machine: str = "machine",
    seed: int = 0,
) -> int:
    """Write one JSON object per trace event: {"machine", "seed",
    "step", "t_us", "kind", "node", "src", "payload"}. Returns the
    number of lines written."""
    with open(path, "w") as f:
        for ev in events:
            f.write(
                json.dumps(
                    {
                        "machine": machine,
                        "seed": seed,
                        "step": ev.step,
                        "t_us": ev.time_us,
                        "kind": ev.kind,
                        "node": ev.node,
                        "src": ev.src,
                        "payload": list(ev.payload),
                    }
                )
            )
            f.write("\n")
    return len(events)
