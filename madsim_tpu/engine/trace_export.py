"""Host-side trace export: Perfetto/Chrome trace_event JSON and JSONL.

A replayed seed's event trace is a virtual-time timeline: every popped
event names the node that handled it and the virtual microsecond it ran
at. The Chrome `trace_event` export maps that onto the profiler UI's
native model — one process per simulated seed, one thread row per node,
1µs slices at virtual timestamps — so `chrome://tracing` or
https://ui.perfetto.dev renders a seed's schedule (elections, message
storms, fault windows) exactly like a CPU profile, scrubber and all.
Message causality renders natively too: send→delivery pairs become flow
arrows (`ph: s/f` bound to the 1µs slices — flows cannot bind to bare
instants, which is why handler events are slices, not `ph: i` marks),
and fault injections get globally-scoped instant markers named by fault
kind so chaos windows are findable at a glance.

The JSONL export is the machine-readable sibling: one JSON object per
event, grep/jq-able, stable keys — the structured counterpart of
`replay --tail`'s human lines (the logging-based JSONL sink for *live*
host-runtime logs is `tracing.JsonlHandler`; this module serializes
engine traces).
"""

from __future__ import annotations

import json
from typing import List, Optional, Sequence, Set, Tuple

from .core import FAULT_KIND_NAMES
from .replay import TraceEvent

# payload[0] of a fault event -> human name (apply ops are even, the
# matching undo odd, op = 2*kind — engine/core.py's op numbering)
def _fault_op_name(op: int) -> str:
    kind = op // 2
    name = (
        FAULT_KIND_NAMES[kind] if 0 <= kind < len(FAULT_KIND_NAMES)
        else f"op{op}"
    )
    return f"{name}{'+' if op % 2 == 0 else '-'}"


def trace_event_dict(
    events: List[TraceEvent],
    *,
    machine: str = "machine",
    seed: int = 0,
    num_nodes: Optional[int] = None,
    flows: Optional[Sequence[Tuple[TraceEvent, TraceEvent]]] = None,
    highlight: Optional[Set[int]] = None,
) -> dict:
    """Build the Chrome trace_event JSON object (dict) for one replayed
    seed. Timestamps are VIRTUAL microseconds (trace_event's native
    unit, so the UI's time axis reads as simulation time directly).

    `flows` are (send event, delivery event) pairs — each becomes a flow
    arrow from the sender's slice to the delivery's slice
    (engine/provenance.py's `Lineage.message_flows` computes them from
    the queue sequence numbers, no provenance gate required).
    `highlight` is a set of step numbers to tag with `"cone": true`
    (the `why` CLI marks the violation's causal past so the cone is
    filterable in the UI)."""
    pid = 0
    out: List[dict] = [
        {
            "ph": "M",
            "pid": pid,
            "name": "process_name",
            "args": {"name": f"{machine} seed {seed}"},
        }
    ]
    nodes = sorted({ev.node for ev in events})
    if num_nodes is not None:
        nodes = sorted(set(nodes) | set(range(num_nodes)))
    for n in nodes:
        out.append(
            {
                "ph": "M",
                "pid": pid,
                "tid": n,
                "name": "thread_name",
                "args": {"name": f"node {n}"},
            }
        )
        # sort_index keeps node rows in id order (tracing UIs otherwise
        # order threads by first event)
        out.append(
            {
                "ph": "M",
                "pid": pid,
                "tid": n,
                "name": "thread_sort_index",
                "args": {"sort_index": n},
            }
        )
    for ev in events:
        name = ev.kind
        if ev.kind == "msg":
            name = f"msg<-{ev.src}"
        elif ev.kind == "fault":
            name = f"fault {_fault_op_name(ev.payload[0])}"
        elif ev.kind == "timer":
            name = f"timer id={ev.payload[0]}"
        args = {
            "step": ev.step,
            "src": ev.src,
            "payload": list(ev.payload),
        }
        if ev.seq >= 0:
            args["seq"] = ev.seq
        if ev.prov:
            args["prov"] = f"0x{ev.prov & 0xFFFFFFFF:08x}"
        if highlight is not None and ev.step in highlight:
            args["cone"] = True
        out.append(
            {
                "ph": "X",  # 1µs slice: flows can bind, instants cannot
                "dur": 1,
                "pid": pid,
                "tid": ev.node,
                "ts": ev.time_us,
                "name": name,
                "args": args,
            }
        )
        if ev.kind == "fault":
            # globally-scoped instant: fault injections draw a full-
            # height marker so chaos windows are visible at any zoom
            out.append(
                {
                    "ph": "i",
                    "s": "g",
                    "pid": pid,
                    "tid": ev.node,
                    "ts": ev.time_us,
                    "name": f"inject {_fault_op_name(ev.payload[0])}",
                    "args": {"step": ev.step},
                }
            )
    for send, recv in flows or ():
        fid = recv.seq if recv.seq >= 0 else (send.step << 16) | recv.step
        common = {"pid": pid, "cat": "msg", "name": "send", "id": fid}
        out.append(
            {"ph": "s", "tid": send.node, "ts": send.time_us, **common}
        )
        out.append(
            {"ph": "f", "bp": "e", "tid": recv.node, "ts": recv.time_us, **common}
        )
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def write_perfetto(
    path: str,
    events: List[TraceEvent],
    *,
    machine: str = "machine",
    seed: int = 0,
    num_nodes: Optional[int] = None,
    flows: Optional[Sequence[Tuple[TraceEvent, TraceEvent]]] = None,
    highlight: Optional[Set[int]] = None,
) -> int:
    """Write the Perfetto/Chrome trace_event JSON file. Returns the
    number of trace events written (excluding metadata records)."""
    doc = trace_event_dict(
        events, machine=machine, seed=seed, num_nodes=num_nodes,
        flows=flows, highlight=highlight,
    )
    with open(path, "w") as f:
        json.dump(doc, f)
        f.write("\n")
    return len(events)


def write_jsonl(
    path: str,
    events: List[TraceEvent],
    *,
    machine: str = "machine",
    seed: int = 0,
) -> int:
    """Write one JSON object per trace event: {"machine", "seed",
    "step", "t_us", "kind", "node", "src", "payload"} plus "seq" (and
    "prov" under the provenance gate). Returns the number of lines
    written."""
    with open(path, "w") as f:
        for ev in events:
            row = {
                "machine": machine,
                "seed": seed,
                "step": ev.step,
                "t_us": ev.time_us,
                "kind": ev.kind,
                "node": ev.node,
                "src": ev.src,
                "payload": list(ev.payload),
            }
            if ev.seq >= 0:
                row["seq"] = ev.seq
            if ev.prov:
                row["prov"] = ev.prov & 0xFFFFFFFF
            f.write(json.dumps(row))
            f.write("\n")
    return len(events)
