"""The TPU engine: batched discrete-event simulation on device.

See `core.py` for the architecture. Public surface:

  * `Machine` — protocol step-function authoring base (machine.py)
  * `Engine(machine, EngineConfig)` — batch runner: `make_runner()`,
    `run_batch(seeds)`, `failing_seeds(result)`
  * `Engine.run_stream(n_seeds, ...)` / `make_stream_runner(...)` — the
    pipelined streaming executor: donated `StreamCarry`, device-side
    supersegments (`segments_per_dispatch`), K-deep async dispatch
    (`dispatch_depth`); `pipelined=False` keeps the r5 per-segment
    driver for one release (bit-identical results either way)
  * `replay(engine, seed)` — bit-identical single-seed CPU replay
  * `FaultPlan` — randomized chaos schedules: pair/dir/group
    partitions, kill/restart, loss storms, delay spikes, pause/resume
    windows (freeze + deferred delivery), per-node clock-skew windows,
    Bernoulli message duplication (`allow_dup`), and crash-with-amnesia
    restarts (`strict_restart` + `Machine.durable_spec()`)
  * `shrink(engine, seed)` — minimize a failing seed's config (shrink.py)
  * `EngineConfig(trace_ring=R)` + `Engine.ring_trace(result, lane)` —
    on-device last-R-events ring for post-mortems without replay
  * `EngineConfig(flight_recorder=True)` — rolling per-lane trace
    digests + checkpoint ring + on-device fault/queue metrics;
    `audit.collect_trail` / `audit.first_divergence` bisect two trails
    to the first divergent checkpoint (audit.py)
  * `EngineConfig(coverage=True)` — scenario-coverage telemetry:
    per-lane AFL-style hit maps over (model projection, event kind,
    fault context), OR-reduced at stream harvest into
    `stats["coverage"]` (ops/coverage.py device side,
    runtime/coverage.py host side: plateau policy, persistence, diff)
"""

from .core import (
    BatchResult,
    Engine,
    EngineConfig,
    FaultPlan,
    LaneState,
    StreamCarry,
    EV_FAULT,
    EV_MSG,
    EV_TIMER,
    FAULT_KIND_NAMES,
    OVERFLOW,
)
from . import audit
from .machine import (
    BOOT,
    Machine,
    Outbox,
    empty_outbox,
    send,
    send_if,
    set_timer,
    set_timer_if,
    update_node,
)
from .replay import ReplayResult, TraceEvent, decode_ring, replay, replay_diff
from . import corpus
from .shrink import ShrinkResult, shrink

__all__ = [
    "BatchResult",
    "Engine",
    "EngineConfig",
    "FaultPlan",
    "LaneState",
    "StreamCarry",
    "Machine",
    "Outbox",
    "BOOT",
    "empty_outbox",
    "send",
    "send_if",
    "set_timer",
    "set_timer_if",
    "update_node",
    "replay",
    "replay_diff",
    "decode_ring",
    "shrink",
    "corpus",
    "ShrinkResult",
    "ReplayResult",
    "TraceEvent",
    "EV_TIMER",
    "EV_MSG",
    "EV_FAULT",
    "FAULT_KIND_NAMES",
    "OVERFLOW",
    "audit",
]
