"""The TPU engine — the discrete-event loop as a batched JAX computation.

This is the tpu-native re-design of the reference's hot loop
(`Executor::block_on` + timer queue + NetSim delivery,
madsim/src/sim/task/mod.rs:220-323, sim/time/mod.rs:45-59,
sim/net/mod.rs:298-334): one `lax.while_loop` advances a struct-of-arrays
state where the leading dimension is the *seed lane*. Thousands of
independent seeds + fault schedules run in lockstep on one chip; lanes
shard over a `jax.sharding.Mesh` for multi-chip scale-out
(seed-batch scaling, SURVEY.md §2.9).

Design rules that make host replay bit-identical (SURVEY.md §7):
  * integer virtual time (int32 microseconds), no float latency math
  * counter-based RNG (jax threefry via jax.random — bit-deterministic
    across CPU/TPU and eager/jit), one key per lane
  * fixed-shape everything: event slots, outbox slots, node arrays;
    overflow = lane failure (code OVERFLOW), never dynamic allocation

Chaos parity with the host fabric: uniform integer latency in
[min,max), Bernoulli loss, directional link clogging, node kill/restart
with re-init (reference: sim/net/network.rs:261-270 + supervisor ops
sim/runtime/mod.rs:272-301), driven by a per-lane `FaultPlan` drawn from
the lane seed.
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
from functools import partial
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import struct
from jax import lax

import os

_stream_log = logging.getLogger("madsim_tpu.stream")

from .. import kinds as _kinds
from ..compile_cache import enable_compile_cache
from ..ops import find_free_slot, pop_earliest
from ..ops.coverage import (
    COV_BAND_AMNESIA,
    COV_BAND_DUP,
    COV_BUFFER_DEFAULT,
    COV_SLOTS_LOG2_DEFAULT,
    cov_band,
    cov_fold,
    cov_fold_words,
    cov_push,
    cov_slot,
    empty_cov_map,
)
from ..ops.pallas_pop import (
    HAVE_PALLAS,
    cov_flush_batch,
    pop_earliest_batch,
    pop_gather_batch,
    step_megakernel,
)
from ..ops.step_rng import (
    RNG_STREAM_COUNTER,
    RNG_STREAM_LEGACY,
    RNG_STREAM_VERSIONS,
    layout_for,
    step_words as draw_step_words,
)
from ..perf import xprof as _xprof
from ..utils import set2d, tree_where
from .machine import BOOT, Machine, Outbox

# Event kinds
EV_TIMER = 0
EV_MSG = 1
EV_FAULT = 2

# Fault ops (payload[0]). Apply ops are even, the matching undo is
# apply+1, and apply = 2*kind where kind indexes FaultPlan.enabled_kinds.
F_CLOG_PAIR = 0
F_UNCLOG_PAIR = 1
F_KILL = 2
F_RESTART = 3
F_CLOG_DIR = 4  # one-way clog a->b (reference Direction, sim/net/network.rs:108)
F_UNCLOG_DIR = 5
F_CLOG_GROUP = 6  # group partition: payload[1] is a node bitmask; every
F_UNCLOG_GROUP = 7  # link crossing the group boundary clogs both ways
F_LOSS_STORM = 8  # timed packet-loss storm: payload[1] = rate in 1/65536
F_LOSS_END = 9
F_DELAY_SPIKE = 10  # timed delay-spike window: ~10% of sends +1-5 virt s
F_DELAY_END = 11    # (the device analogue of the host buggify delay,
#                     reference sim/net/mod.rs:287-296)
F_PAUSE = 12   # pause window: node frozen (state survives; deliveries
F_RESUME = 13  # targeting it DEFER past resume, not drop) — the device
#                analogue of Handle::pause (reference runtime/mod.rs)
F_SKEW = 14      # clock-skew window: payload[2] is a q10 multiplier —
F_SKEW_END = 15  # the node's timer delays are stretched/compressed
F_TORN = 16          # torn/lost-write fault: kill node a; payload[2] is the
F_TORN_RESTART = 17  # schedule-drawn damage mask — the restart wipes
#                      volatile leaves AND damages durable leaves per the
#                      machine's torn_spec() atomicity contract ("the
#                      disk lied" — the FoundationDB buggify class)
F_HASYM = 18       # asymmetric partition: clog pair a<->b both ways; the
F_HASYM_HEAL = 19  # heal op unclogs ONE direction arg1->arg2 — the two
#                    directions heal at independently drawn times, so
#                    every partition tail is a one-way-link window

# FaultPlan kind indices (op_apply = 2*kind)
K_PAIR = 0
K_KILL = 1
K_DIR = 2
K_GROUP = 3
K_STORM = 4
K_DELAY = 5
K_PAUSE = 6
K_SKEW = 7
K_TORN = 8
K_HEAL_ASYM = 9

# delay-spike parameters — the host fabric's buggify numbers
# (net/__init__.py rand_delay: 10% of sends suspended 1-5 s)
DELAY_PROB_U32 = int(0.1 * 0xFFFFFFFF)
DELAY_EXTRA_MIN_US = 1_000_000
DELAY_EXTRA_SPAN_US = 4_000_001

# message duplication (FaultPlan.allow_dup): Bernoulli per successful
# delivery-push, duplicate re-enqueued with an independently drawn
# latency (the at-least-once property real networks have and loss-only
# chaos never exercises)
DUP_PROB_U32 = int(0.1 * 0xFFFFFFFF)

# clock-skew factor: a q10 fixed-point timer-delay multiplier drawn
# uniform in [SKEW_Q10_MIN, SKEW_Q10_MIN + SKEW_Q10_SPAN) — 0.5x..2.0x,
# wide enough to break lease/heartbeat "my timer fires before your
# timeout" assumptions in both directions. Applied 32-bit-exactly as
#   scaled = (d >> 10) * q + (((d & 1023) * q) >> 10)
# (no int64, no floats — the determinism rules).
SKEW_Q10_ONE = 1024
SKEW_Q10_MIN = 512
SKEW_Q10_SPAN = 1536


def skew_scale_us(delay_us, q10):
    """Stretch/compress an int32 microsecond delay by the q10 factor,
    exactly, within int32 (delay < ~2^21 s-scale values stay exact:
    (d>>10)*q <= 2e7 and the remainder term <= 2.1e6)."""
    d = jnp.asarray(delay_us).astype(jnp.int32)
    q = jnp.asarray(q10).astype(jnp.int32)
    return (d >> 10) * q + (((d & 1023) * q) >> 10)

# Failure codes
OK = 0
OVERFLOW = 1  # event queue full — lane aborts (host fallback)

# -- flight recorder (observability) ----------------------------------------
# Rolling per-lane trace digest: a uint32[2] xor-rotate-multiply fold
# over every popped event tuple plus the step's RNG word block. Not
# cryptographic — built so any single-bit difference in any folded word
# avalanches into both halves within one step, which is all divergence
# detection needs. The IVs are pi's fractional bits (nothing-up-my-
# sleeve); the multipliers are the Weyl/golden-ratio constant and
# murmur3's fmix constant (both odd, so the map is a bijection on u32).
DIGEST_IV0 = 0x243F6A88
DIGEST_IV1 = 0x85A308D3
_DIGEST_M0 = 0x9E3779B1
_DIGEST_M1 = 0x85EBCA6B

# FaultPlan kind names, indexed by K_* — the fault-injection counter
# labels used by run_stream stats / bench / audit output. The table
# lives in madsim_tpu/kinds.py (single source of truth for every host
# mirror; `python -m madsim_tpu lint` cross-checks the consumers).
FAULT_KIND_NAMES = _kinds.FAULT_KIND_NAMES

# -- causal provenance (observability) ---------------------------------------
# One uint32 word per queued event and per node (`EngineConfig.
# provenance`): bit f marks "scheduled fault f is in this value's causal
# past". Provenance is MONOTONE — words only OR, never clear — so a
# violation's word names every scheduled fault whose effects reached the
# violating node through any chain of deliveries (an over-approximation
# of the true cause set, never an under-approximation for fault effects
# that flow through state and messages; what it cannot see is
# absence-causality refinement — a clogged link's bit is planted on both
# endpoints at clog time rather than on each message the clog swallowed).
# Bits 30/31 are reserved for the two non-scheduled chaos channels, so
# attribution can name them even though they own no schedule slot:
# a crash-with-amnesia wipe (strict_restart) and a Bernoulli duplicate
# delivery (allow_dup). Scheduled fault indices clip into the remaining
# 30 bits (plans beyond 30 faults alias — attribution degrades to
# coarser, still-sound-as-OR reporting, never to wrong dataflow).
PROV_FAULT_BITS = 30
PROV_BIT_AMNESIA = 30
PROV_BIT_DUP = 31


def prov_fault_bit(fault_index: int) -> int:
    """The provenance bit a scheduled fault slot sets (python-level;
    the schedule is unrolled statically in init_lane)."""
    return 1 << min(fault_index, PROV_FAULT_BITS - 1)

# Non-scheduled chaos injection counters (flight recorder): Bernoulli
# message duplicates pushed, and strict (crash-with-amnesia) restarts
# applied. They ride fr_metrics after the per-kind totals.
FR_EXTRA_NAMES = _kinds.FR_EXTRA_NAMES

# StreamCarry.fr_metrics layout: per-kind injection totals, the extra
# chaos counters (all summed at harvest), then queue / clogged-link /
# killed-node high-water marks (maxed at harvest).
FR_METRICS_LEN = len(FAULT_KIND_NAMES) + len(FR_EXTRA_NAMES) + 3


def digest_fold(d0, d1, words):
    """One digest round per word: d0 takes an xor-multiply-xorshift, d1
    takes a rotated xor-multiply and absorbs d0 so the halves couple.
    `words` is a python list of traced scalars (static unroll)."""
    for w in words:
        w = jnp.asarray(w).astype(jnp.uint32)
        d0 = (d0 ^ w) * jnp.uint32(_DIGEST_M0)
        d0 = d0 ^ (d0 >> 16)
        d1 = (d1 ^ ((w << 13) | (w >> 19))) * jnp.uint32(_DIGEST_M1)
        d1 = d1 ^ (d1 >> 15) ^ d0
    return d0, d1

# Bit-packed clog rows: node j of row i lives in word j // 30, bit
# j % 30 — the SAME 30-bits-per-int32 encoding the group-partition
# payload masks use (payload args 1+2), so the two-word row covers the
# existing N <= 60 cap and the group fault becomes pure word ops.
CLOG_WORD_BITS = 30
CLOG_WORDS = 2
CLOG_MAX_NODES = CLOG_WORD_BITS * CLOG_WORDS


def _clog_bit_words(j):
    """One-hot (lo, hi) int32 words for a traced node index j."""
    lo = jnp.where(j < CLOG_WORD_BITS,
                   jnp.int32(1) << jnp.clip(j, 0, CLOG_WORD_BITS - 1),
                   jnp.int32(0))
    hi = jnp.where(j >= CLOG_WORD_BITS,
                   jnp.int32(1) << jnp.clip(j - CLOG_WORD_BITS, 0, CLOG_WORD_BITS - 1),
                   jnp.int32(0))
    return lo, hi


def _clog_row_bools(row, n):
    """Expand a packed int32[CLOG_WORDS] row to bool[n] link flags."""
    ii = jnp.arange(n)
    bits = jnp.where(
        ii < CLOG_WORD_BITS,
        row[0] >> jnp.clip(ii, 0, CLOG_WORD_BITS - 1),
        row[1] >> jnp.clip(ii - CLOG_WORD_BITS, 0, CLOG_WORD_BITS - 1),
    )
    return (bits & 1).astype(bool)


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """Per-lane randomized fault schedule (drawn from the lane seed).

    Each fault picks a random kind, start time and duration:
      * partition: clog a random node pair both ways, heal after duration
      * kill: kill a random node, restart after duration
      * dir_clog: clog one direction of a random pair (the host fabric's
        `Direction` semantics, reference sim/net/network.rs:108)
      * group: partition a random non-trivial node subset from the rest
        (covers majority/minority splits; bitmask-encoded)
      * storm: raise the packet-loss rate to `storm_loss_u16`/65536 for
        the duration (timed loss storm on top of the static config rate)
      * delay: a delay-spike window — while active, ~10% of sent
        messages take +1-5 virtual seconds of extra latency (the device
        analogue of the host fabric's buggified rand_delay, reference
        sim/net/mod.rs:287-296; late-but-delivered messages find
        timeout-handling bugs that loss cannot)
      * pause: a pause window — the node is FROZEN, not killed: its
        state survives untouched and every delivery targeting it
        (timers and messages alike) is deferred past the resume time
        instead of dropped (the device analogue of `Handle::pause`,
        reference sim/runtime/mod.rs). Exercises the timeout paths
        kill cannot: peers see silence, then the node comes back with
        stale-but-intact state.
      * skew: a per-node clock-skew window — while active, every timer
        the node arms is stretched/compressed by a q10 factor drawn in
        [0.5x, 2.0x) (payload[2]); leases expire late, heartbeats fire
        early, election timeouts drift.
      * torn: a torn/lost-write storage fault — kill a random node,
        then restart it through the machine's `torn_spec()` atomicity
        contract instead of its restart hook: volatile leaves wipe
        (amnesia), and durable leaves marked non-atomically-written
        (TORN_LOSE / TORN_PREFIX) keep only a seeded prefix or revert
        entirely, per a damage word drawn in the schedule (payload[2])
        and salted by the step's torn RNG word. "The disk lied" — the
        FoundationDB buggify finding class. A machine with only a
        `durable_spec()` survives by construction (default spec: every
        durable write is atomic).
      * heal_asym: an asymmetric partition — clog a random pair both
        ways, then heal the two directions at INDEPENDENTLY drawn
        times (a->b at t+dur, b->a at t+dur2), so every partition tail
        is a one-way-link window: acks flow without requests, requests
        without acks. Each fault takes a third schedule slot for the
        second heal (only materialized when the kind is enabled).

    Plus two non-scheduled chaos gates:
      * `allow_dup`: Bernoulli per-delivery message duplication — each
        successfully pushed message has a DUP_PROB chance of a second
        copy enqueued with an independently drawn latency (idempotency
        chaos; the RNG block grows a tail section, recorded streams
        stay byte-stable with the flag off)
      * `strict_restart`: crash-with-amnesia — restart faults wipe
        every node-state leaf the machine's `durable_spec()` contract
        does not mark durable, instead of trusting the model's
        hand-written restart hook ("node restarts but illegally kept
        volatile state" is the classic DST finding class this makes
        expressible)

    The legacy two-kind derivation (partition/kill only) is byte-stable:
    seeds found by earlier sweeps (e.g. the 66531 LOG_MATCHING
    regression) replay unchanged unless a new kind is enabled, which
    switches the schedule to the v2 derivation. pause/skew ride the v2
    derivation with one extra per-fault draw (the skew factor), taken
    only when either flag is on — dir/group/storm/delay-era schedules
    are untouched.
    """

    n_faults: int = 0
    allow_partition: bool = True
    allow_kill: bool = True
    allow_dir_clog: bool = False
    allow_group: bool = False
    allow_storm: bool = False
    allow_delay: bool = False  # timed delay-spike windows (buggify analogue)
    allow_pause: bool = False  # pause/resume windows (freeze, defer deliveries)
    allow_skew: bool = False   # per-node clock-skew windows (q10 timer scale)
    allow_dup: bool = False    # Bernoulli per-delivery message duplication
    allow_torn: bool = False   # torn/lost-write faults via Machine.torn_spec()
    allow_heal_asym: bool = False  # asymmetric partition healing (one-way decay)
    strict_restart: bool = False  # crash-with-amnesia via Machine.durable_spec()
    storm_loss_u16: int = 52428  # ~80% loss while a storm is active
    t_min_us: int = 0
    t_max_us: int = 1_000_000
    dur_min_us: int = 100_000
    dur_max_us: int = 1_000_000

    def enabled_kinds(self) -> tuple:
        kinds = []
        if self.allow_partition:
            kinds.append(K_PAIR)
        if self.allow_kill:
            kinds.append(K_KILL)
        if self.allow_dir_clog:
            kinds.append(K_DIR)
        if self.allow_group:
            kinds.append(K_GROUP)
        if self.allow_storm:
            kinds.append(K_STORM)
        if self.allow_delay:
            kinds.append(K_DELAY)
        if self.allow_pause:
            kinds.append(K_PAUSE)
        if self.allow_skew:
            kinds.append(K_SKEW)
        if self.allow_torn:
            kinds.append(K_TORN)
        if self.allow_heal_asym:
            kinds.append(K_HEAL_ASYM)
        return tuple(kinds)

    @property
    def uses_v2_kinds(self) -> bool:
        return (
            self.allow_dir_clog or self.allow_group or self.allow_storm
            or self.allow_delay or self.uses_window_kinds
            or self.uses_storage_kinds
        )

    @property
    def uses_window_kinds(self) -> bool:
        """The PR-5 scheduled kinds: they add one draw (the skew q10
        factor) to each fault's v2 derivation — kept behind this flag so
        dir/group/storm/delay-era schedules replay byte-identically."""
        return self.allow_pause or self.allow_skew

    @property
    def uses_storage_kinds(self) -> bool:
        """The PR-6 scheduled kinds (torn / heal_asym): one more
        per-fault draw — the torn damage mask, doubling as the second
        heal duration — taken only when either flag is on, so every
        window-kind-era schedule replays byte-identically."""
        return self.allow_torn or self.allow_heal_asym

    @property
    def slots_per_fault(self) -> int:
        """Event-queue slots each fault occupies. Asymmetric healing
        needs a third slot (the second direction's heal); it is drawn
        for every fault when the kind is enabled and left INVALID for
        non-heal_asym kinds, so it never perturbs them (an invalid slot
        is ordinary free queue space)."""
        return 3 if self.allow_heal_asym else 2


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Static engine parameters (python-level; baked into the jit)."""

    horizon_us: int = 10_000_000  # 10 virtual seconds
    queue_capacity: int = 64
    latency_min_us: int = 1_000  # matches host NetConfig default 1-10ms
    latency_max_us: int = 10_000
    packet_loss_rate: float = 0.0
    handler_rand_words: int = 4
    faults: FaultPlan = dataclasses.field(default_factory=FaultPlan)
    # On-device event ring: keep the last `trace_ring` events per lane in
    # HBM so a failing lane has an immediate post-mortem without a full
    # replay (0 = off; the ring costs [lanes, trace_ring] masked writes
    # per step). Contents match the replay trace exactly (tests assert).
    trace_ring: int = 0
    # Per-step RNG stream version (ops/step_rng.py): 2 = legacy
    # split-chain (the seed-era stream — the default, so every recorded
    # seed and corpus entry replays byte-identically), 3 = counter-based
    # (one threefry per event, block sized to what this config can
    # consume — the fast stream new hunts should opt into). Corpus
    # entries record the version; entries predating the field are v2.
    rng_stream: int = RNG_STREAM_LEGACY
    # Clog-state representation: True packs each node's outbound clog
    # row into two int32 words (30 bits each, the group-mask encoding)
    # instead of an [N, N] bool matrix — fault-branch outer products
    # become word-wise bit ops and per-lane HBM state shrinks. Pure
    # representation swap: results are bit-identical either way (tests
    # assert); False keeps the bool-matrix oracle. Requires N <= 60.
    clog_packed: bool = True
    # Flight recorder (observability): a rolling per-lane trace digest —
    # a uint32[2] fold over each popped (time, kind, node, src, payload)
    # tuple plus the step-RNG word block — checkpointed into a small
    # on-device ring every `fr_digest_every` steps, plus on-device
    # fault-injection / queue / clog occupancy metrics. Rides the
    # existing result harvest (zero extra host syncs); the gate-off path
    # is bit-identical (tests assert). Two digest trails agree exactly
    # as far as the two executions agree, so the first divergent
    # checkpoint localizes a determinism break to one segment —
    # `python -m madsim_tpu audit` (engine/audit.py) is the consumer.
    flight_recorder: bool = False
    fr_digest_every: int = 64  # steps between digest checkpoints
    fr_digest_ring: int = 32  # checkpoints retained per lane (ring)
    # Scenario-coverage telemetry (observability): every popped event
    # hashes (model abstract-state projection, event kind, fault
    # context) into a per-lane AFL-style uint8 saturating-count map
    # (ops/coverage.py; 2^cov_slots_log2 slots, banded
    # [band|phase|mix] layout so the host can decode per-fault-kind and
    # per-phase marginals). The stream harvest OR-reduces lanes into one
    # device vector — zero extra host syncs, same discipline as the
    # flight recorder — and run_stream stats gain "coverage" (slots
    # hit / fraction / curve). The signal behind `--stop-on-plateau`:
    # a hunt that stops adding slots has saturated its scenario space.
    # Gate-off is bit-identical (tests assert); ON is also
    # result-identical — the map is write-only telemetry.
    coverage: bool = False
    cov_slots_log2: int = COV_SLOTS_LOG2_DEFAULT
    # Coverage band-layout floor: 0 = derive from the fault vocabulary
    # as always (3-bit legacy, 4-bit when a PR-5+ capability is on —
    # every recorded map keeps its layout and golden slot constants).
    # A guided hunt (madsim_tpu/search) pins 4 so the slot space stays
    # IDENTICAL across fault-vocabulary escalations: cumulative maps,
    # plateau deltas and parent detection must compare bits from every
    # escalation step in one address space. Write-only telemetry
    # layout, never result-affecting; excluded from corpus configs
    # like the other coverage knobs.
    cov_band_bits_min: int = 0
    # Per-lane coverage slot-buffer depth (flush-on-freeze buffered
    # fold, r12): > 0 buffers each popped event's slot index in a tiny
    # int32[cov_buffer] per-lane ring and folds the packed bit map only
    # on a fixed segment cadence, at segment exit, and therefore at
    # every freeze point — removing the per-event map RMW scatter that
    # BENCH_r11 measured at -7.37% of step throughput. 0 = the
    # unbuffered per-event scatter (the escape hatch / differential
    # oracle; A/B-able via `bench-ab --gate coverage-unbuffered`).
    # Final maps are bit-identical either way — OR is commutative and
    # idempotent, and the executor's segment-exit flush runs
    # unconditionally, so frozen lanes can never strand buffered slots.
    # Host-side perf knob: excluded from corpus serialization with the
    # other coverage knobs.
    cov_buffer: int = COV_BUFFER_DEFAULT
    # Causal provenance (observability): every queued event and every
    # node carries a 32-bit provenance word — one bit per scheduled
    # fault slot (bits 30/31: strict-restart wipes / duplicate
    # deliveries), ORed along deliveries: a delivered message folds its
    # lineage into the receiver, an injected fault plants its slot bit
    # on the nodes it touches, timers and sends inherit their node's
    # word. The violating lane's word is captured at the first invariant
    # failure and rides the existing failure-ring harvest — zero extra
    # host syncs, same discipline as recorder/coverage. Consumers:
    # per-find fault attribution in run_stream/hunt reports,
    # provenance-guided shrink (engine/shrink.py ablates non-implicated
    # faults first), and `python -m madsim_tpu why` (engine/
    # provenance.py decodes the word against the seed's re-derived
    # fault schedule and cuts the replay trace to the violation's past
    # cone). Consumes NO RNG words; gate-off is bit-identical (tests
    # assert under both stream versions).
    provenance: bool = False
    # Whole-event Pallas step megakernel (ops/pallas_pop.py): the
    # model-independent prefix of the step — lexicographic-argmin pop,
    # popped-tuple gather, the counter-based v3 RNG word block
    # (in-kernel Threefry-2x32, bit-exact vs jax's primitive) and,
    # under the flight recorder, the whole digest fold — fused into ONE
    # VMEM pass per lane block. None = auto: ON when the backend is TPU
    # and rng_stream is 3; MADSIM_TPU_PALLAS_MEGAKERNEL=0/1 forces
    # either way. Requires rng_stream=3 (the word block IS the v3
    # counter derivation). Pure fusion: results are bit-identical to
    # the XLA path, which stays the oracle (tests assert end-to-end
    # and per-kernel in interpreter mode). Host-side perf knob —
    # excluded from corpus serialization like compile_cache_dir.
    pallas_megakernel: Optional[bool] = None
    # Opt-in JAX persistent compilation cache directory (also
    # $MADSIM_TPU_COMPILE_CACHE): hunts and sweeps pay each multi-second
    # compile once per machine instead of once per process. Host-side
    # knob — never affects traces/results and is excluded from corpus
    # serialization.
    compile_cache_dir: Optional[str] = None


@struct.dataclass
class LaneState:
    now_us: jax.Array
    next_seq: jax.Array
    step: jax.Array
    rng_key: jax.Array  # uint32[2]
    done: jax.Array
    failed: jax.Array
    fail_code: jax.Array
    horizon_hit: jax.Array
    msg_count: jax.Array
    storm_loss: jax.Array  # int32: active storm loss rate in 1/65536 (0 = none)
    delay_spike: jax.Array  # int32: 1 while a delay-spike window is active
    eq_time: jax.Array  # int32[Q]
    eq_seq: jax.Array  # int32[Q]
    eq_kind: jax.Array  # int32[Q]
    eq_node: jax.Array  # int32[Q]
    eq_src: jax.Array  # int32[Q]
    eq_payload: jax.Array  # int32[Q, P]
    eq_valid: jax.Array  # bool[Q]
    clogged: jax.Array  # int32[N, CLOG_WORDS] packed rows (clog_packed) | bool[N, N]
    killed: jax.Array  # bool[N]
    # pause/skew windows: int32[N] when the kind is enabled, int32[0]
    # otherwise (the leaf exists so the pytree structure is uniform, but
    # a disabled kind carries — and computes — nothing)
    paused_until: jax.Array  # virtual us the node resumes at (0 = running)
    skew_q10: jax.Array  # active q10 timer-delay multiplier (0 = none)
    # causal provenance (EngineConfig.provenance): uint32 lineage words —
    # uint32[N] per node / uint32[Q] per queued event / uint32 scalar
    # captured at the first invariant failure; uint32[0] when the gate
    # is off (the leaves exist so the pytree structure is uniform, but a
    # disabled gate carries — and computes — nothing)
    node_prov: jax.Array
    eq_prov: jax.Array
    fail_prov: jax.Array
    nodes: Any
    ring: Any  # {} when trace_ring == 0, else dict of [R]/[R,P] arrays
    fr: Any  # {} unless flight_recorder: digest + checkpoint ring + metrics
    # {} unless coverage: {"map": int32[2^cov_slots_log2 / 32] bit words};
    # the buffered regime (cov_buffer > 0) adds {"buf": int32[cov_buffer]
    # pending slot indices, "buf_n": int32 live-entry count} — flushed
    # into "map" by run_segment's cadence/exit folds
    cov: Any


@struct.dataclass
class StreamCarry:
    """Device-resident streaming state: lanes + seed counter + result
    rings. Everything run_stream needs per segment lives on-device; the
    host fetches only `counters` (one small uint32[6] transfer) and
    drains the rings when they near capacity."""

    state: LaneState
    seeds: jax.Array  # uint32[L] — seed currently owned by each lane
    done: jax.Array  # bool[L] — harvest mask; refilled at next segment start
    next_seed: jax.Array  # uint32 scalar
    completed: jax.Array  # int32 scalar
    segments: jax.Array  # int32 scalar — segments executed on device
    fail_seeds: jax.Array  # uint32[C]
    fail_codes: jax.Array  # int32[C]
    fail_provs: jax.Array  # uint32[C] violation provenance words ([0] when off)
    fail_count: jax.Array  # int32 scalar
    ab_seeds: jax.Array  # uint32[C]
    ab_count: jax.Array  # int32 scalar
    counters: jax.Array  # uint32[7]: completed, fail_count, ab_count, next_seed, flags, segments, cov_slots_hit
    fr_metrics: jax.Array  # int32[FR_METRICS_LEN] flight-recorder totals ([0] when off)
    cov_map: jax.Array  # int32[2^cov_slots_log2 / 32] global OR of lane bit maps ([0] when off)


@struct.dataclass
class BatchResult:
    seeds: jax.Array
    done: jax.Array
    failed: jax.Array
    fail_code: jax.Array
    fail_prov: jax.Array  # uint32[L] violation provenance words ([L, 0] when off)
    now_us: jax.Array
    steps: jax.Array
    msg_count: jax.Array
    summary: Any
    ring: Any  # per-lane event rings ({} unless config.trace_ring > 0)
    fr: Any  # per-lane flight-recorder state ({} unless flight_recorder)
    cov: Any  # per-lane coverage maps ({} unless config.coverage)


class Engine:
    """Bind a Machine + EngineConfig into jittable batch/replay runners."""

    def __init__(
        self,
        machine: Machine,
        config: EngineConfig = EngineConfig(),
        use_pallas_pop: Optional[bool] = None,
    ):
        self.machine = machine
        self.config = config
        enable_compile_cache(config.compile_cache_dir)
        # Batched event-pop backend: the fused Pallas pop+gather kernel
        # (ops/pallas_pop.py) vs the vmapped XLA reductions. Default ON
        # when the backend is TPU (the kernel's home turf); the XLA path
        # stays the default elsewhere and the bit-identity oracle
        # everywhere. MADSIM_TPU_PALLAS_POP=0/1 (or the constructor arg)
        # forces either way — meshed pod runs should force 0, because
        # pallas_call blocks sharding propagation. Resolved once at
        # construction so jit caches stay consistent; on non-TPU
        # backends a forced-on kernel runs in interpreter mode (slow —
        # for equivalence tests, not production).
        if use_pallas_pop is None:
            env = os.environ.get("MADSIM_TPU_PALLAS_POP", "")
            if env == "":
                import jax as _jax

                use_pallas_pop = _jax.default_backend() == "tpu"
            else:
                use_pallas_pop = env != "0"
        self.use_pallas_pop = bool(use_pallas_pop) and HAVE_PALLAS
        # Whole-event step megakernel (EngineConfig.pallas_megakernel /
        # MADSIM_TPU_PALLAS_MEGAKERNEL): resolved like the pop kernel —
        # auto means ON only on TPU — plus the static requirement that
        # the stream is v3 (the kernel computes the counter-based word
        # block; v2's split-chain key evolution is inherently
        # sequential host..er, XLA-side). A forced-on megakernel off-TPU
        # runs in interpreter mode (equivalence tests, not production).
        mk = config.pallas_megakernel
        if mk is None:
            env_mk = os.environ.get("MADSIM_TPU_PALLAS_MEGAKERNEL", "")
            if env_mk == "":
                import jax as _jax

                mk = _jax.default_backend() == "tpu"
            else:
                mk = env_mk != "0"
            # auto/env resolution degrades gracefully on a v2 engine
            # (legacy replays, shrink of recorded seeds): the kernel
            # simply cannot serve that stream, so it stays off
            mk = mk and config.rng_stream == RNG_STREAM_COUNTER
        elif mk and config.rng_stream != RNG_STREAM_COUNTER:
            # explicitly requested on a v2 engine is a config error
            raise ValueError(
                "pallas_megakernel requires rng_stream=3 (the kernel "
                "computes the counter-based v3 word block in the same "
                "VMEM pass as the pop; v2's per-step key split-chain "
                "cannot be expressed as a counter)"
            )
        self.use_megakernel = bool(mk) and HAVE_PALLAS
        if self.use_pallas_pop or self.use_megakernel:
            import jax as _jax

            self._pallas_interpret = _jax.default_backend() != "tpu"
        else:
            self._pallas_interpret = False
        n, q = machine.NUM_NODES, config.queue_capacity
        min_slots = n + config.faults.slots_per_fault * config.faults.n_faults
        if q < min_slots + machine.MAX_MSGS + machine.MAX_TIMERS:
            raise ValueError(
                f"queue_capacity={q} too small for {n} nodes + "
                f"{config.faults.n_faults} faults + outbox headroom"
            )
        fp = config.faults
        if fp.n_faults > 0 and not fp.enabled_kinds():
            raise ValueError("FaultPlan has n_faults > 0 but every kind disabled")
        if fp.allow_group and (n < 2 or n > 60):
            raise ValueError(
                "group partitions need 2 <= NUM_NODES <= 60 (two-word "
                "int32 bitmask: payload args 1+2 carry 30 bits each)"
            )
        if not 0 <= fp.storm_loss_u16 <= 65535:
            raise ValueError("storm_loss_u16 must be in [0, 65535]")
        if config.clog_packed and n > CLOG_MAX_NODES:
            raise ValueError(
                f"clog_packed needs NUM_NODES <= {CLOG_MAX_NODES} (two-word "
                f"int32 rows); pass EngineConfig(clog_packed=False) for "
                f"{n} nodes"
            )
        if config.rng_stream not in RNG_STREAM_VERSIONS:
            raise ValueError(
                f"rng_stream={config.rng_stream!r} unknown; supported "
                f"versions: {RNG_STREAM_VERSIONS}"
            )
        if config.flight_recorder and (
            config.fr_digest_every < 1 or config.fr_digest_ring < 1
        ):
            raise ValueError(
                "flight_recorder needs fr_digest_every >= 1 and "
                "fr_digest_ring >= 1"
            )
        if fp.strict_restart and fp.allow_kill and machine.durable_spec() is None:
            raise ValueError(
                f"strict_restart (crash-with-amnesia) needs "
                f"{type(machine).__name__}.durable_spec() to declare the "
                f"durable-state contract (which leaves survive restart)"
            )
        if fp.allow_torn:
            spec = machine.durable_spec()
            if spec is None:
                raise ValueError(
                    f"allow_torn (torn/lost-write storage faults) needs "
                    f"{type(machine).__name__}.durable_spec() to declare "
                    f"the durable-state contract the torn restart damages"
                )
            tspec = machine.torn_spec()
            if tspec is not None:
                from .machine import TORN_ATOMIC, TORN_LOSE, TORN_PREFIX

                bad = [
                    c for c in jax.tree.leaves(tspec)
                    if c not in (TORN_ATOMIC, TORN_LOSE, TORN_PREFIX)
                ]
                if bad or jax.tree.structure(tspec) != jax.tree.structure(spec):
                    raise ValueError(
                        f"{type(machine).__name__}.torn_spec() must be "
                        f"congruent to durable_spec() with every leaf in "
                        f"{{TORN_ATOMIC, TORN_LOSE, TORN_PREFIX}}"
                    )
        # Coverage banded-slot layout version: the band field grows to 4
        # bits whenever any PR-5 chaos capability can occur (those are
        # new configs by definition, so every historical map keeps its
        # 3-bit layout and its golden slot constants).
        if config.cov_band_bits_min not in (0, 3, 4):
            raise ValueError(
                f"cov_band_bits_min={config.cov_band_bits_min!r} — "
                f"0 (derive), 3 or 4 are the known banded layouts"
            )
        self.cov_band_bits = max(
            config.cov_band_bits_min,
            4
            if (fp.allow_pause or fp.allow_skew or fp.allow_dup
                or fp.strict_restart or fp.allow_torn or fp.allow_heal_asym)
            else 3,
        )
        min_log2 = self.cov_band_bits + 3 + 1
        if config.coverage and not min_log2 <= config.cov_slots_log2 <= 20:
            raise ValueError(
                f"coverage needs {min_log2} <= cov_slots_log2 <= 20 "
                f"({self.cov_band_bits} band bits + 3 phase bits + at "
                f"least 1 mix bit; 2^20 slots = 1 MiB per lane is "
                f"already past any sane map size)"
            )
        # Static step-RNG block layout + compute-elision flags: which
        # chaos draws this (config, machine) pair can ever consume.
        # Deliberately independent of n_faults (kind FLAGS only): shrink
        # bisects n_faults per candidate, and the layout staying fixed
        # keeps (a) the v3 stream identical across candidates and (b)
        # the compiled-replay cache shared (one lane_step compile serves
        # every candidate — the r5 hunt-throughput fix relies on it).
        self._rng_layout = layout_for(
            config.rng_stream,
            config.handler_rand_words,
            machine.MAX_MSGS,
            loss_possible=config.packet_loss_rate > 0 or fp.allow_storm,
            spike_possible=fp.allow_delay,
            delay_enabled=fp.allow_delay,
            # torn restarts re-init through the machine like kill
            # restarts do, so they need the restart key too
            restart_possible=fp.allow_kill or fp.allow_torn,
            dup_possible=fp.allow_dup,
            torn_possible=fp.allow_torn,
        )
        # Buffered-coverage flush cadence: a step appends at most
        # `slots_per_step` slots (the popped event, plus the synthetic
        # dup-band slot when Bernoulli duplicates can occur), so
        # flushing every cov_buffer // slots_per_step segment
        # iterations makes buffer overflow impossible by construction —
        # no per-event overflow branch exists, because a masked
        # fallback fold would put the map RMW right back into every
        # step's program (the cost the buffer removes). Validated
        # here, after _rng_layout, because slots_per_step needs
        # layout.dup_active.
        self._cov_slots_per_step = 2 if self._rng_layout.dup_active else 1
        if config.cov_buffer < 0 or config.cov_buffer > 1024:
            raise ValueError(
                f"cov_buffer={config.cov_buffer!r} — 0 (unbuffered "
                f"per-event fold) or a depth in "
                f"[{self._cov_slots_per_step}, 1024]"
            )
        if config.coverage and 0 < config.cov_buffer < self._cov_slots_per_step:
            raise ValueError(
                f"cov_buffer={config.cov_buffer} is shallower than the "
                f"{self._cov_slots_per_step} slots one step can append "
                f"under this config (dup events add a synthetic band "
                f"slot); use 0 for the unbuffered fold or >= "
                f"{self._cov_slots_per_step}"
            )
        self._cov_buffered = bool(config.coverage and config.cov_buffer > 0)
        self._cov_flush_every = (
            config.cov_buffer // self._cov_slots_per_step
            if self._cov_buffered
            else 0
        )

    # -- lane init -----------------------------------------------------------

    def init_lane(self, seed) -> LaneState:
        m, cfg = self.machine, self.config
        n, q, p = m.NUM_NODES, cfg.queue_capacity, m.PAYLOAD_WIDTH
        key = jax.random.PRNGKey(seed)
        key, k_init, k_faults = jax.random.split(key, 3)
        nodes = m.init(k_init)

        # BOOT timers for every node at t=0 in slots [0, n) (analogue of
        # node init closures); all arrays built by static masks, no scatters.
        slots = jnp.arange(q, dtype=jnp.int32)
        is_boot_slot = slots < n
        eq_time = jnp.zeros((q,), jnp.int32)
        eq_seq = jnp.where(is_boot_slot, slots, 0)
        eq_kind = jnp.zeros((q,), jnp.int32)  # EV_TIMER == 0
        eq_node = jnp.where(is_boot_slot, slots, 0)
        eq_src = jnp.full((q,), -1, jnp.int32)
        eq_payload = jnp.zeros((q, p), jnp.int32)  # timer id BOOT == 0
        eq_valid = is_boot_slot
        next_seq = n
        # provenance: boot timers are causal roots (word 0); each fault
        # slot carries its fault's bit so processing the event plants it
        eq_prov = jnp.zeros((q if cfg.provenance else 0,), jnp.uint32)

        # Fault schedule: apply + undo event per fault, slots [n, n+2F).
        fp = cfg.faults
        for f in range(fp.n_faults):
            if not fp.uses_v2_kinds:
                # v1 derivation (partition/kill) — byte-stable for replay
                # of historically found seeds
                k_faults, k1, k2, k3, k4, k5 = jax.random.split(k_faults, 6)
                t = jnp.int32(fp.t_min_us) + (
                    jax.random.bits(k1, (), jnp.uint32) % jnp.uint32(fp.t_max_us - fp.t_min_us)
                ).astype(jnp.int32)
                dur = jnp.int32(fp.dur_min_us) + (
                    jax.random.bits(k2, (), jnp.uint32) % jnp.uint32(fp.dur_max_us - fp.dur_min_us)
                ).astype(jnp.int32)
                a = (jax.random.bits(k3, (), jnp.uint32) % jnp.uint32(n)).astype(jnp.int32)
                b_off = 1 + (jax.random.bits(k4, (), jnp.uint32) % jnp.uint32(n - 1)).astype(
                    jnp.int32
                )
                b = (a + b_off) % n
                if fp.allow_partition and fp.allow_kill:
                    is_part = (jax.random.bits(k5, (), jnp.uint32) % 2) == 0
                elif fp.allow_partition:
                    is_part = jnp.bool_(True)
                else:
                    is_part = jnp.bool_(False)
                op_apply = jnp.where(is_part, F_CLOG_PAIR, F_KILL).astype(jnp.int32)
                op_undo = jnp.where(is_part, F_UNCLOG_PAIR, F_RESTART).astype(jnp.int32)
                arg1, arg2 = a, b
            else:
                # v2 derivation: uniform over the enabled kind set; every
                # argument is drawn unconditionally (constant draw count
                # keeps the schedule stable under config flag flips that
                # don't change the kind list)
                k_faults, k1, k2, k3, k4, k5, k6 = jax.random.split(k_faults, 7)
                t = jnp.int32(fp.t_min_us) + (
                    jax.random.bits(k1, (), jnp.uint32) % jnp.uint32(fp.t_max_us - fp.t_min_us)
                ).astype(jnp.int32)
                dur = jnp.int32(fp.dur_min_us) + (
                    jax.random.bits(k2, (), jnp.uint32) % jnp.uint32(fp.dur_max_us - fp.dur_min_us)
                ).astype(jnp.int32)
                a = (jax.random.bits(k3, (), jnp.uint32) % jnp.uint32(n)).astype(jnp.int32)
                b_off = 1 + (jax.random.bits(k4, (), jnp.uint32) % jnp.uint32(n - 1)).astype(
                    jnp.int32
                )
                b = (a + b_off) % n
                kinds = jnp.asarray(fp.enabled_kinds(), jnp.int32)
                kind = kinds[jax.random.bits(k5, (), jnp.uint32) % jnp.uint32(len(kinds))]
                # Group masks: payload arg1 carries bits [0, 30), arg2
                # bits [30, 60) — two int32 words, so group partitions
                # scale past the old 30-node cap (lifted round 5; the
                # constructor rejects n > 60). The low draw keeps the
                # historical ≤30-node derivation byte-stable; the high
                # word is drawn ONLY for n > 30 machines (new since the
                # lift), so recorded seeds replay unchanged.
                lo_bits = min(n, 30)
                mask_lo = 1 + (
                    jax.random.bits(k6, (), jnp.uint32) % jnp.uint32(2 ** lo_bits - 2)
                ).astype(jnp.int32)
                if n > 30:
                    k_faults, k7 = jax.random.split(k_faults)
                    hi_bits = n - 30
                    mask_hi = (
                        jax.random.bits(k7, (), jnp.uint32) % jnp.uint32(2 ** hi_bits)
                    ).astype(jnp.int32)
                else:
                    mask_hi = jnp.int32(0)
                op_apply = (2 * kind).astype(jnp.int32)
                op_undo = (2 * kind + 1).astype(jnp.int32)
                arg1 = jnp.where(
                    kind == K_GROUP,
                    mask_lo,
                    jnp.where(kind == K_STORM, jnp.int32(fp.storm_loss_u16), a),
                )
                arg2 = jnp.where(kind == K_GROUP, mask_hi, b)
                if fp.uses_window_kinds:
                    # one extra draw — the skew q10 factor — taken only
                    # when pause/skew are in the vocabulary, so every
                    # dir/group/storm/delay-era schedule stays
                    # byte-stable. Drawn unconditionally (constant draw
                    # count) like every other v2 argument.
                    k_faults, k8 = jax.random.split(k_faults)
                    skew_q10 = jnp.int32(SKEW_Q10_MIN) + (
                        jax.random.bits(k8, (), jnp.uint32)
                        % jnp.uint32(SKEW_Q10_SPAN)
                    ).astype(jnp.int32)
                    # pause: arg2 carries the resume time (the undo
                    # event's own timestamp), so the defer target needs
                    # no extra state; skew: arg2 carries the factor
                    arg2 = jnp.where(
                        kind == K_PAUSE,
                        t + dur,
                        jnp.where(kind == K_SKEW, skew_q10, arg2),
                    )
                if fp.uses_storage_kinds:
                    # one more draw — the torn damage mask, doubling as
                    # the heal_asym second-direction duration — taken
                    # only when torn/heal_asym are in the vocabulary, so
                    # every window-kind-era schedule stays byte-stable.
                    # Drawn unconditionally (constant draw count); a
                    # fault is exactly one kind, so the word serves
                    # whichever use that kind has.
                    k_faults, k9 = jax.random.split(k_faults)
                    storage_word = jax.random.bits(k9, (), jnp.uint32)
                    # torn: arg2 carries the damage mask (int31 — the
                    # payload is int32 and signs would survive replay,
                    # but non-negative reads cleaner in traces)
                    arg2 = jnp.where(
                        kind == K_TORN,
                        (storage_word & jnp.uint32(0x7FFFFFFF)).astype(jnp.int32),
                        arg2,
                    )
                    dur2 = jnp.int32(fp.dur_min_us) + (
                        storage_word % jnp.uint32(fp.dur_max_us - fp.dur_min_us)
                    ).astype(jnp.int32)
            # slot layout per fault: [apply at t, undo at t+dur] plus,
            # when heal_asym is enabled, a third slot for the second
            # direction's heal at t+dur2 (valid only for heal_asym
            # faults — other kinds leave it invalid, i.e. free space)
            slot_events = [
                (t, op_apply, arg1, arg2, None),
                (t + dur, op_undo, arg1, arg2, None),
            ]
            if fp.allow_heal_asym:
                slot_events.append(
                    (t + dur2, jnp.int32(F_HASYM_HEAL), b, a, kind == K_HEAL_ASYM)
                )
            for slot_off, (tt, op, p1, p2, valid) in enumerate(slot_events):
                i = n + fp.slots_per_fault * f + slot_off
                msk = slots == i
                eq_time = jnp.where(msk, tt, eq_time)
                eq_seq = jnp.where(msk, next_seq + slot_off, eq_seq)
                eq_kind = jnp.where(msk, EV_FAULT, eq_kind)
                eq_node = jnp.where(msk, a, eq_node)
                pay = jnp.stack([op, p1, p2] + [jnp.int32(0)] * (p - 3))
                eq_payload = jnp.where(msk[:, None], pay[None, :], eq_payload)
                eq_valid = eq_valid | (msk if valid is None else (msk & valid))
                if cfg.provenance:
                    eq_prov = jnp.where(
                        msk, jnp.uint32(prov_fault_bit(f)), eq_prov
                    )
            next_seq += fp.slots_per_fault

        return LaneState(
            now_us=jnp.int32(0),
            next_seq=jnp.int32(next_seq),
            step=jnp.int32(0),
            rng_key=key,
            done=jnp.bool_(False),
            failed=jnp.bool_(False),
            fail_code=jnp.int32(OK),
            horizon_hit=jnp.bool_(False),
            msg_count=jnp.int32(0),
            storm_loss=jnp.int32(0),
            delay_spike=jnp.int32(0),
            eq_time=eq_time,
            eq_seq=eq_seq,
            eq_kind=eq_kind,
            eq_node=eq_node,
            eq_src=eq_src,
            eq_payload=eq_payload,
            eq_valid=eq_valid,
            clogged=(
                jnp.zeros((n, CLOG_WORDS), jnp.int32)
                if cfg.clog_packed
                else jnp.zeros((n, n), bool)
            ),
            killed=jnp.zeros((n,), bool),
            paused_until=jnp.zeros((n if fp.allow_pause else 0,), jnp.int32),
            skew_q10=jnp.zeros((n if fp.allow_skew else 0,), jnp.int32),
            node_prov=jnp.zeros((n if cfg.provenance else 0,), jnp.uint32),
            eq_prov=eq_prov,
            fail_prov=(
                jnp.uint32(0) if cfg.provenance else jnp.zeros((0,), jnp.uint32)
            ),
            nodes=nodes,
            ring=self._empty_ring(),
            fr=self._empty_fr(eq_valid),
            cov=self._empty_cov(),
        )

    def _empty_cov(self):
        """Fresh coverage state: a zeroed per-lane hit map, plus — in
        the buffered regime (cov_buffer > 0) — the per-lane slot buffer
        and its live-entry count. Unbuffered keeps the map-only pytree,
        so cov_buffer=0 states are leaf-for-leaf identical to the
        pre-buffer layout."""
        if not self.config.coverage:
            return {}
        cov = {"map": empty_cov_map(self.config.cov_slots_log2)}
        if self._cov_buffered:
            cov["buf"] = jnp.zeros((self.config.cov_buffer,), jnp.int32)
            cov["buf_n"] = jnp.int32(0)
        return cov

    def _empty_fr(self, eq_valid=None):
        """Fresh flight-recorder state: digest at its IV, empty
        checkpoint ring (step -1 = unused slot), zeroed metrics.
        `eq_valid` (the lane's initial queue-valid plane) seeds the
        incremental occupancy counter `eq_n` — the step tracks queue
        occupancy as (-1 per pop, +1 per push) instead of re-summing
        the [Q] valid plane every event, so the q_hwm metric costs
        O(1) per step. Value-identical to the old per-step sum by
        construction (every pop clears exactly one valid slot, every
        push fills exactly one free slot); the host-oracle metrics
        differential asserts it."""
        cfg = self.config
        if not cfg.flight_recorder:
            return {}
        r = cfg.fr_digest_ring
        return {
            "d0": jnp.uint32(DIGEST_IV0),
            "d1": jnp.uint32(DIGEST_IV1),
            "eq_n": (
                jnp.int32(0) if eq_valid is None
                else eq_valid.sum(dtype=jnp.int32)
            ),
            "ck_step": jnp.full((r,), -1, jnp.int32),
            "ck_d0": jnp.zeros((r,), jnp.uint32),
            "ck_d1": jnp.zeros((r,), jnp.uint32),
            "inj": jnp.zeros((len(FAULT_KIND_NAMES),), jnp.int32),
            "dup": jnp.int32(0),
            "amnesia": jnp.int32(0),
            "q_hwm": jnp.int32(0),
            "clog_hwm": jnp.int32(0),
            "kill_hwm": jnp.int32(0),
        }

    def _empty_ring(self):
        r = self.config.trace_ring
        if not r:
            return {}
        return {
            "step": jnp.full((r,), -1, jnp.int32),
            "time": jnp.zeros((r,), jnp.int32),
            "kind": jnp.zeros((r,), jnp.int32),
            "node": jnp.zeros((r,), jnp.int32),
            "src": jnp.zeros((r,), jnp.int32),
            "payload": jnp.zeros((r, self.machine.PAYLOAD_WIDTH), jnp.int32),
        }

    # -- one event per lane --------------------------------------------------

    def lane_step(self, s: LaneState, horizon_us=None) -> LaneState:
        idx, any_valid = pop_earliest(s.eq_time, s.eq_seq, s.eq_valid)
        return self._lane_step_popped(s, idx, any_valid, horizon_us=horizon_us)

    def _lane_step_popped(
        self, s: LaneState, idx, any_valid, popped=None, horizon_us=None,
        active=None, step_block=None,
    ) -> LaneState:
        """lane_step with the event-queue pop hoisted out, so step_batch
        can swap in the batched Pallas kernel for the whole [L, Q] block
        while the rest of the step stays vmapped. `popped`, when given,
        is the pre-gathered (time, kind, node, src, payload) event tuple
        from the fused pop+gather kernel — the 5 per-lane slot gathers
        below disappear; values are bit-identical by construction.

        `active` (traced bool), when given, folds the executor's
        per-lane freeze (a done/failed lane must pass through untouched)
        into the step's OWN write masks: every state write below is
        already a masked select, so gating the masks costs a handful of
        scalar ANDs — where the old `tree_where(active, new, state)`
        wrapper in step_batch re-selected every [L, Q] queue leaf and
        the whole nodes tree each step. `None` keeps the ungated step
        (replay paths freeze externally). Results are bit-identical:
        an inactive lane's every field provably writes back its old
        value.

        `horizon_us` optionally overrides the config horizon with a
        TRACED value — identical arithmetic, but one compiled replay
        serves every horizon candidate (shrink bisects the horizon
        per-seed; baking it would recompile per candidate).

        `step_block`, when given, is the megakernel's precomputed
        `(words,)` or `(words, nd0, nd1)` tuple — the v3 RNG word block
        (and, under the flight recorder, the already-folded digest)
        from the fused Pallas pass. The step then draws nothing and
        folds nothing itself; values are bit-identical by the kernel
        contract. Only meaningful on a v3 engine (the lane key is
        immutable; the restart key is a block slice)."""
        m, cfg = self.machine, self.config

        if popped is None:
            ev_time = s.eq_time[idx]
            ev_kind = s.eq_kind[idx]
            ev_node = s.eq_node[idx]
            ev_src = s.eq_src[idx]
            ev_payload = s.eq_payload[idx]
        else:
            ev_time, ev_kind, ev_node, ev_src, ev_payload = popped

        if cfg.provenance:
            # the popped event's lineage word (fault slots carry their
            # bit from init; messages/timers carry their sender's word)
            ev_prov = s.eq_prov[idx]

        new_now = jnp.maximum(s.now_us, ev_time)
        hz = cfg.horizon_us if horizon_us is None else horizon_us
        # `live` = this lane pops an event this step (frozen lanes never
        # do; their popped tuple is junk-but-deterministic and every use
        # below is gated on live/process/effective)
        live = any_valid if active is None else any_valid & active
        horizon_hit = live & (new_now >= hz)
        process = live & ~horizon_hit
        node_alive = ~s.killed[ev_node]
        # pause windows: a handler event targeting a paused (alive) node
        # is DEFERRED — the popped slot stays valid and only its time
        # moves to the node's resume point (the state survives, nothing
        # is processed, nothing is dropped). Kill still dominates: a
        # dead node's events are consumed as before. The deferred pop
        # itself is a popped event (trace ring / digest / coverage see
        # it) — host replay pops it identically, so the contract holds.
        if cfg.faults.allow_pause:
            node_resume_us = s.paused_until[ev_node]
            defer = (
                process
                & (ev_kind != EV_FAULT)
                & node_alive
                & (node_resume_us > new_now)
            )
        else:
            defer = None
        pop_mask = (jnp.arange(s.eq_valid.shape[0]) == idx) & live
        if defer is not None:
            pop_mask = pop_mask & ~defer
        eq_valid = s.eq_valid & ~pop_mask

        # on-device trace ring: record every popped event (same condition
        # as the replay trace: popped, processed or not)
        ring = s.ring
        if cfg.trace_ring:
            slot = (jnp.arange(cfg.trace_ring) == s.step % cfg.trace_ring) & live
            ring = {
                "step": jnp.where(slot, s.step, ring["step"]),
                "time": jnp.where(slot, ev_time, ring["time"]),
                "kind": jnp.where(slot, ev_kind, ring["kind"]),
                "node": jnp.where(slot, ev_node, ring["node"]),
                "src": jnp.where(slot, ev_src, ring["src"]),
                "payload": jnp.where(slot[:, None], ev_payload[None, :], ring["payload"]),
            }

        # One batched draw covers the step's randomness (handler words,
        # per-message latency draws, and whatever chaos draws this
        # config can consume). The block layout and draw count are the
        # versioned stream contract (ops/step_rng.py): v2 is the legacy
        # split-chain (two threefry invocations, fixed block), v3 is
        # counter-based off the immutable lane key and the step index
        # (ONE threefry invocation, block sized to the enabled config).
        layout = self._rng_layout
        if step_block is None:
            key, step_words, k_restart = draw_step_words(s.rng_key, s.step, layout)
        else:
            # megakernel path: the word block arrived from the fused
            # Pallas pass. v3 semantics exactly — the lane key is
            # immutable and the restart key is the block's restart
            # slice (step_words_v3's contract).
            step_words = step_block[0]
            key = s.rng_key
            if layout.restart_off is not None:
                k_restart = step_words[layout.restart_off : layout.restart_off + 2]
            else:
                k_restart = jnp.zeros((2,), jnp.uint32)
        rand_u32 = step_words[: layout.handler_words]
        if active is not None and layout.version == RNG_STREAM_LEGACY:
            # v2's key evolves per step — freeze it with the lane
            # (v3's lane key is immutable, nothing to gate)
            key = jnp.where(active, key, s.rng_key)

        def timer_branch(_):
            nodes, outbox = m.on_timer(s.nodes, ev_node, ev_payload[0], new_now, rand_u32)
            return (nodes, outbox, s.clogged, s.killed, s.storm_loss,
                    s.delay_spike, s.paused_until, s.skew_q10, jnp.int32(-1))

        def msg_branch(_):
            nodes, outbox = m.on_message(s.nodes, ev_node, ev_src, ev_payload, new_now, rand_u32)
            return (nodes, outbox, s.clogged, s.killed, s.storm_loss,
                    s.delay_spike, s.paused_until, s.skew_q10, jnp.int32(-1))

        def fault_branch(_):
            op, a, b = ev_payload[0], ev_payload[1], ev_payload[2]
            nn = s.killed.shape[0]
            pair_val = op == F_CLOG_PAIR
            touch_pair = (op == F_CLOG_PAIR) | (op == F_UNCLOG_PAIR)
            dir_val = op == F_CLOG_DIR
            touch_dir = (op == F_CLOG_DIR) | (op == F_UNCLOG_DIR)
            if cfg.faults.allow_heal_asym:
                # asymmetric partition: the apply op clogs the pair both
                # ways (pair word ops); each F_HASYM_HEAL op unclogs the
                # single direction arg1->arg2 (the dir word ops with
                # dir_val False), so the two heals land independently
                pair_val = pair_val | (op == F_HASYM)
                touch_pair = touch_pair | (op == F_HASYM)
                touch_dir = touch_dir | (op == F_HASYM_HEAL)
            touch_group = (op == F_CLOG_GROUP) | (op == F_UNCLOG_GROUP)
            idxs = jnp.arange(nn)
            # group membership: `a` carries mask bits [0, 30), `b` bits
            # [30, 60) — nodes inside the group partition from the rest
            in_g = jnp.where(
                idxs < 30,
                (a >> jnp.clip(idxs, 0, 29)) & 1,
                (b >> jnp.clip(idxs - 30, 0, 29)) & 1,
            ).astype(bool)
            if cfg.clog_packed:
                # word-wise bit ops on the two-int32 rows: each fault
                # event touches O(N) words, not an [N, N] outer product
                w0, w1 = s.clogged[:, 0], s.clogged[:, 1]

                def apply_bit(w0, w1, row_mask, bit_lo, bit_hi, val, touch):
                    msk = touch & row_mask
                    nw0 = jnp.where(val, w0 | bit_lo, w0 & ~bit_lo)
                    nw1 = jnp.where(val, w1 | bit_hi, w1 & ~bit_hi)
                    return jnp.where(msk, nw0, w0), jnp.where(msk, nw1, w1)

                a_lo, a_hi = _clog_bit_words(a)
                b_lo, b_hi = _clog_bit_words(b)
                # pair partition: both directions
                w0, w1 = apply_bit(w0, w1, idxs == a, b_lo, b_hi, pair_val, touch_pair)
                w0, w1 = apply_bit(w0, w1, idxs == b, a_lo, a_hi, pair_val, touch_pair)
                # directional clog: a->b only (Direction parity,
                # network.rs:108)
                w0, w1 = apply_bit(w0, w1, idxs == a, b_lo, b_hi, dir_val, touch_dir)
                # group partition: row i's cross-boundary links are the
                # group complement for members, the group for outsiders
                # (bit i lands on neither side, so self-links are clean)
                full_lo = jnp.int32((1 << min(nn, CLOG_WORD_BITS)) - 1)
                full_hi = jnp.int32((1 << max(nn - CLOG_WORD_BITS, 0)) - 1)
                cross_lo = jnp.where(in_g, ~a & full_lo, a & full_lo)
                cross_hi = jnp.where(in_g, ~b & full_hi, b & full_hi)
                g_on = op == F_CLOG_GROUP
                nw0 = jnp.where(g_on, w0 | cross_lo, w0 & ~cross_lo)
                nw1 = jnp.where(g_on, w1 | cross_hi, w1 & ~cross_hi)
                w0 = jnp.where(touch_group, nw0, w0)
                w1 = jnp.where(touch_group, nw1, w1)
                clogged = jnp.stack([w0, w1], axis=1)
            else:
                # bool-matrix oracle: outer-equality masked writes
                clogged = jnp.where(
                    touch_pair,
                    set2d(set2d(s.clogged, a, b, pair_val), b, a, pair_val),
                    s.clogged,
                )
                clogged = jnp.where(touch_dir, set2d(clogged, a, b, dir_val), clogged)
                cross = in_g[:, None] != in_g[None, :]
                clogged = jnp.where(touch_group & cross, op == F_CLOG_GROUP, clogged)
            a_mask = jnp.arange(nn) == a
            kill_op = op == F_KILL
            restart_op = op == F_RESTART
            if cfg.faults.allow_torn:
                # a torn fault is a kill whose restart goes through the
                # torn_spec() storage contract instead of the model hook
                kill_op = kill_op | (op == F_TORN)
                restart_op = restart_op | (op == F_TORN_RESTART)
            killed = jnp.where(
                kill_op,
                s.killed | a_mask,
                jnp.where(restart_op, s.killed & ~a_mask, s.killed),
            )
            # loss storm: `a` is the storm rate in 1/65536 units
            storm = jnp.where(
                op == F_LOSS_STORM,
                a,
                jnp.where(op == F_LOSS_END, jnp.int32(0), s.storm_loss),
            ).astype(jnp.int32)
            # delay-spike window toggle (buggify analogue)
            delay = jnp.where(
                op == F_DELAY_SPIKE,
                jnp.int32(1),
                jnp.where(op == F_DELAY_END, jnp.int32(0), s.delay_spike),
            ).astype(jnp.int32)
            # pause window: arg2 (`b`) carries the resume time the
            # schedule derivation baked in — deferral needs no clock
            # state beyond this per-node word
            paused = s.paused_until
            if cfg.faults.allow_pause:
                paused = jnp.where(
                    (op == F_PAUSE) & a_mask,
                    b,
                    jnp.where((op == F_RESUME) & a_mask, jnp.int32(0), paused),
                ).astype(jnp.int32)
            # clock-skew window: arg2 (`b`) is the drawn q10 factor
            skew = s.skew_q10
            if cfg.faults.allow_skew:
                skew = jnp.where(
                    (op == F_SKEW) & a_mask,
                    b,
                    jnp.where((op == F_SKEW_END) & a_mask, jnp.int32(0), skew),
                ).astype(jnp.int32)
            # cond folded into the machine's own row masks — no full-tree
            # select here (XLA CSEs it inside the fused loop, but eager
            # step_batch paid ~30% for it, and masked writes are strictly
            # less work for any backend)
            nodes = m.restart_node_if(
                s.nodes, a, op == F_RESTART, k_restart,
                strict=cfg.faults.strict_restart,
            )
            if cfg.faults.allow_torn:
                # torn/lost-write restart: the damage seed is the fault
                # payload's schedule-drawn mask (b) salted by this
                # step's torn RNG word — bit-deterministic on replay
                torn_seed = b.astype(jnp.uint32) ^ step_words[layout.torn_off]
                nodes = m.torn_restart_if(
                    nodes, a, op == F_TORN_RESTART, k_restart, torn_seed
                )
            boot_node = jnp.where(restart_op, a, jnp.int32(-1))
            return (nodes, m.empty_outbox(), clogged, killed, storm, delay,
                    paused, skew, boot_node)

        (nodes, outbox, clogged, killed, storm_loss, delay_spike,
         paused_until, skew_q10, boot_node) = lax.switch(
            ev_kind, [timer_branch, msg_branch, fault_branch], None
        )

        # Killed nodes process nothing (reference: killed node's tasks are
        # dropped); fault events always apply. Deferred events (pause
        # windows) are not processed either — they re-deliver at resume.
        is_handler = ev_kind != EV_FAULT
        effective = process & (node_alive | ~is_handler)
        if defer is not None:
            effective = effective & ~defer
        nodes = tree_where(effective, nodes, s.nodes)
        clogged = jnp.where(effective, clogged, s.clogged)
        killed = jnp.where(effective, killed, s.killed)
        storm_loss = jnp.where(effective, storm_loss, s.storm_loss)
        delay_spike = jnp.where(effective, delay_spike, s.delay_spike)
        if cfg.faults.allow_pause:
            paused_until = jnp.where(effective, paused_until, s.paused_until)
        else:
            paused_until = s.paused_until
        if cfg.faults.allow_skew:
            skew_q10 = jnp.where(effective, skew_q10, s.skew_q10)
        else:
            skew_q10 = s.skew_q10
        outbox_valid_msgs = outbox.msg_valid & effective
        outbox_valid_timers = outbox.timer_valid & effective

        # -- causal provenance fold (gate-off adds NO ops) ------------------
        # A processed handler event folds its lineage into the handling
        # node; a processed fault event plants its word on the nodes it
        # touches — both endpoints for pair/dir/heal ops, node `a` for
        # node ops (kill/restart/pause/skew/torn), every node for the
        # global window/group ops (a loss storm touches every link; the
        # over-approximation is the documented contract). Everything the
        # node emits afterwards (messages, timers, the restart boot)
        # inherits the node's updated word.
        if cfg.provenance:
            nn_p = s.killed.shape[0]
            idxs_p = jnp.arange(nn_p)
            p_op = ev_payload[0]
            is_fault_ev = ev_kind == EV_FAULT
            prov_pair_ops = (
                (p_op == F_CLOG_PAIR) | (p_op == F_UNCLOG_PAIR)
                | (p_op == F_CLOG_DIR) | (p_op == F_UNCLOG_DIR)
            )
            if cfg.faults.allow_heal_asym:
                prov_pair_ops = prov_pair_ops | (p_op == F_HASYM) | (p_op == F_HASYM_HEAL)
            prov_global_ops = (
                (p_op == F_CLOG_GROUP) | (p_op == F_UNCLOG_GROUP)
                | (p_op == F_LOSS_STORM) | (p_op == F_LOSS_END)
                | (p_op == F_DELAY_SPIKE) | (p_op == F_DELAY_END)
            )
            touched = jnp.where(
                is_fault_ev,
                prov_global_ops
                | (prov_pair_ops & ((idxs_p == ev_payload[1]) | (idxs_p == ev_payload[2])))
                | (~prov_global_ops & ~prov_pair_ops & (idxs_p == ev_payload[1])),
                idxs_p == ev_node,
            )
            add_word = ev_prov
            if cfg.faults.strict_restart:
                # a crash-with-amnesia wipe is its own attribution
                # channel (bit 30): it has no schedule slot of its own
                add_word = jnp.where(
                    is_fault_ev & (p_op == F_RESTART),
                    ev_prov | jnp.uint32(1 << PROV_BIT_AMNESIA),
                    ev_prov,
                )
            node_prov = jnp.where(
                touched & effective, s.node_prov | add_word, s.node_prov
            )
            # the word every push below inherits (fault events push only
            # the restart boot timer, whose node is ev_node == a)
            sender_prov = node_prov[ev_node]
        else:
            node_prov = s.node_prov
            sender_prov = None

        # -- push outbox messages with chaos (latency / loss / clog) --------
        eq = {
            "time": s.eq_time,
            "seq": s.eq_seq,
            "kind": s.eq_kind,
            "node": s.eq_node,
            "src": s.eq_src,
            "payload": s.eq_payload,
            "valid": eq_valid,
        }
        if cfg.provenance:
            eq["prov"] = s.eq_prov
        if defer is not None:
            # deferred delivery: rewrite the (still-valid) popped slot's
            # time to the node's resume point. Seq is untouched — at the
            # resume instant `paused_until > now` is already false, so
            # the event delivers regardless of its order relative to the
            # F_RESUME event, and same-time deferred events keep their
            # original relative order. No free slot is consumed, so
            # deferral can never overflow the queue.
            defer_slot = (jnp.arange(s.eq_valid.shape[0]) == idx) & defer
            eq["time"] = jnp.where(defer_slot, node_resume_us, eq["time"])
            if cfg.provenance:
                # the deferral is caused by the pause window: the target
                # node's word (which carries the pause fault's bit since
                # the F_PAUSE apply touched it) folds into the deferred
                # event's lineage
                eq["prov"] = jnp.where(
                    defer_slot, eq["prov"] | s.node_prov[ev_node], eq["prov"]
                )
        next_seq = s.next_seq
        failed = s.failed
        fail_code = s.fail_code
        msg_count = s.msg_count

        lat_span = max(1, cfg.latency_max_us - cfg.latency_min_us)
        lat_bits = step_words[layout.lat_off : layout.lat_off + m.MAX_MSGS]
        # Sections that are statically inert for this (config, machine)
        # pair cost nothing: v3 doesn't even draw them; v2 draws them
        # (the legacy block is part of the stream contract) but the
        # consuming compute is elided — with loss_rate == 0 and storms
        # unreachable the drop compare is constant-False, so eliding it
        # is result-preserving in both versions.
        if layout.loss_active:
            drop_bits = step_words[layout.drop_off : layout.drop_off + m.MAX_MSGS]
            # static config loss + active storm (storm rate 65535 ~= drop
            # all), saturating at u32 max
            base_threshold = jnp.uint32(int(cfg.packet_loss_rate * 0xFFFFFFFF))
            storm_threshold = storm_loss.astype(jnp.uint32) * jnp.uint32(65537)
            summed = base_threshold + storm_threshold
            loss_threshold = jnp.where(
                summed < storm_threshold, jnp.uint32(0xFFFFFFFF), summed
            )
        if layout.spike_active:
            # spike gate + magnitude are INDEPENDENT words: conditioning
            # the magnitude on the gate's sub-threshold bits would cap the
            # extra latency at ~2.7 s instead of the documented 1-5 s
            spike_bits = step_words[layout.spike_off : layout.spike_off + m.MAX_MSGS]
            spike_mag_bits = step_words[
                layout.spike_off + m.MAX_MSGS : layout.spike_off + 2 * m.MAX_MSGS
            ]
        if layout.dup_active:
            # duplication gate + fresh-latency words (tail section of
            # the block — recorded streams are untouched with dup off)
            dup_bits = step_words[layout.dup_off : layout.dup_off + m.MAX_MSGS]
            dup_lat_bits = step_words[
                layout.dup_off + m.MAX_MSGS : layout.dup_off + 2 * m.MAX_MSGS
            ]
            n_dups = jnp.int32(0)
        # the handling node's outbound clog row, read ONCE (pre-fault
        # state, matching the unpacked path's s.clogged[ev_node, dst])
        # and expanded to bool[N] so each message pays the same tiny
        # gather as the bool-matrix path, not a shift/mask per slot
        if cfg.clog_packed:
            clog_row_bool = _clog_row_bools(s.clogged[ev_node], s.killed.shape[0])

        for mi in range(m.MAX_MSGS):
            want = outbox_valid_msgs[mi]
            dst = outbox.msg_dst[mi]
            if cfg.clog_packed:
                blocked = clog_row_bool[dst]
            else:
                blocked = s.clogged[ev_node, dst]
            if layout.loss_active:
                blocked = blocked | (drop_bits[mi] < loss_threshold)
            do_push = want & ~blocked
            latency = jnp.int32(cfg.latency_min_us) + (
                lat_bits[mi] % jnp.uint32(lat_span)
            ).astype(jnp.int32)
            if layout.spike_active:
                # delay-spike window: ~10% of sends take +1-5 virtual s
                # (the host buggify's numbers); the draws are consumed
                # every step so windows don't perturb the stream shape
                spiked = (delay_spike > 0) & (spike_bits[mi] < jnp.uint32(DELAY_PROB_U32))
                extra = jnp.int32(DELAY_EXTRA_MIN_US) + (
                    spike_mag_bits[mi] % jnp.uint32(DELAY_EXTRA_SPAN_US)
                ).astype(jnp.int32)
                latency = latency + jnp.where(spiked, extra, 0)
            slot, has_free = find_free_slot(eq["valid"])
            overflow = do_push & ~has_free
            failed = failed | overflow
            fail_code = jnp.where(overflow, jnp.int32(OVERFLOW), fail_code)
            do_push = do_push & has_free
            eq = _push(
                eq, slot, do_push, new_now + latency, next_seq, EV_MSG, dst,
                ev_node, outbox.msg_payload[mi], prov=sender_prov,
            )
            next_seq = next_seq + jnp.where(do_push, 1, 0)
            msg_count = msg_count + jnp.where(do_push, 1, 0)
            if layout.dup_active:
                # Bernoulli duplicate of a successfully pushed message,
                # re-enqueued with an independently drawn latency (the
                # idempotency chaos loss-only vocabularies can't
                # express). Same overflow accounting as any push.
                want_dup = do_push & (dup_bits[mi] < jnp.uint32(DUP_PROB_U32))
                dslot, dfree = find_free_slot(eq["valid"])
                doverflow = want_dup & ~dfree
                failed = failed | doverflow
                fail_code = jnp.where(doverflow, jnp.int32(OVERFLOW), fail_code)
                want_dup = want_dup & dfree
                dup_latency = jnp.int32(cfg.latency_min_us) + (
                    dup_lat_bits[mi] % jnp.uint32(lat_span)
                ).astype(jnp.int32)
                eq = _push(
                    eq, dslot, want_dup, new_now + dup_latency, next_seq,
                    EV_MSG, dst, ev_node, outbox.msg_payload[mi],
                    # the duplicate copy carries the dup attribution bit:
                    # a violation whose lineage includes it names `dup`
                    prov=(
                        sender_prov | jnp.uint32(1 << PROV_BIT_DUP)
                        if sender_prov is not None else None
                    ),
                )
                next_seq = next_seq + jnp.where(want_dup, 1, 0)
                msg_count = msg_count + jnp.where(want_dup, 1, 0)
                n_dups = n_dups + want_dup.astype(jnp.int32)

        # -- push timers (for the handling node) ----------------------------
        slot0 = jnp.arange(m.PAYLOAD_WIDTH) == 0
        if cfg.faults.allow_skew:
            # clock-skew window: the handling node's armed timers are
            # stretched/compressed by its active q10 factor (read from
            # the pre-step state — handler events never change skew, and
            # fault events arm no timers, so pre == post here)
            node_skew_q10 = s.skew_q10[ev_node]
        for ti in range(m.MAX_TIMERS):
            want = outbox_valid_timers[ti]
            slot, has_free = find_free_slot(eq["valid"])
            overflow = want & ~has_free
            failed = failed | overflow
            fail_code = jnp.where(overflow, jnp.int32(OVERFLOW), fail_code)
            want = want & has_free
            tpay = jnp.where(slot0, outbox.timer_id[ti], 0).astype(jnp.int32)
            t_delay = outbox.timer_delay_us[ti]
            if cfg.faults.allow_skew:
                t_delay = jnp.where(
                    node_skew_q10 > 0,
                    skew_scale_us(t_delay, node_skew_q10),
                    t_delay,
                )
            eq = _push(
                eq, slot, want, new_now + t_delay, next_seq,
                EV_TIMER, ev_node, jnp.int32(-1), tpay, prov=sender_prov,
            )
            next_seq = next_seq + jnp.where(want, 1, 0)

        # -- restart boot timer ---------------------------------------------
        want_boot = effective & (boot_node >= 0)
        slot, has_free = find_free_slot(eq["valid"])
        boot_overflow = want_boot & ~has_free
        failed = failed | boot_overflow
        fail_code = jnp.where(boot_overflow, jnp.int32(OVERFLOW), fail_code)
        want_boot = want_boot & has_free
        boot_pay = jnp.zeros((m.PAYLOAD_WIDTH,), jnp.int32)  # BOOT == 0
        eq = _push(
            eq, slot, want_boot, new_now, next_seq, EV_TIMER, boot_node,
            jnp.int32(-1), boot_pay, prov=sender_prov,
        )
        next_seq = next_seq + jnp.where(want_boot, 1, 0)

        # -- flight recorder (observability; gate-off adds NO ops) ----------
        fr = s.fr
        if cfg.flight_recorder:
            stepped = jnp.bool_(True) if active is None else active
            new_step = s.step + stepped.astype(jnp.int32)
            # digest: fold the popped tuple + the step's whole RNG word
            # block — exactly the inputs that determine this step — on
            # every step that pops an event (same condition as the trace
            # ring / replay trace). The megakernel hands the fold in
            # pre-computed (same words, same order, same math — the
            # fused pass runs the identical chain in VMEM).
            if step_block is not None and len(step_block) == 3:
                nd0, nd1 = step_block[1], step_block[2]
            else:
                nd0, nd1 = digest_fold(
                    fr["d0"],
                    fr["d1"],
                    [ev_time, ev_kind, ev_node, ev_src]
                    + [ev_payload[i] for i in range(m.PAYLOAD_WIDTH)]
                    + [step_words[i] for i in range(layout.total_words)],
                )
            d0 = jnp.where(live, nd0, fr["d0"])
            d1 = jnp.where(live, nd1, fr["d1"])
            # checkpoint ring: every `fr_digest_every`-th step the lane
            # actually executes lands (step, d0, d1) in slot
            # (step/every - 1) % ring — the host decodes by sorting on
            # step. Condition is "the step counter crossed a multiple",
            # not "popped": the audit's host-side trail reads the digest
            # at exact step multiples and must see the same checkpoints.
            every, rr = cfg.fr_digest_every, cfg.fr_digest_ring
            want_ck = stepped & (new_step % every == 0)
            ck_slot = ((new_step // every - 1) % rr == jnp.arange(rr)) & want_ck
            # fault-injection counters: one per FaultPlan kind, counted
            # when an APPLY op (even payload[0]) is processed
            is_inj = process & (ev_kind == EV_FAULT) & (ev_payload[0] % 2 == 0)
            kind_idx = ev_payload[0] // 2
            inj = fr["inj"] + (
                (jnp.arange(len(FAULT_KIND_NAMES)) == kind_idx) & is_inj
            ).astype(jnp.int32)
            # non-scheduled chaos counters: duplicates pushed this step,
            # crash-with-amnesia wipes applied (strict restarts)
            fr_dup = fr["dup"]
            if layout.dup_active:
                fr_dup = fr_dup + n_dups
            fr_amnesia = fr["amnesia"]
            if cfg.faults.strict_restart:
                fr_amnesia = fr_amnesia + (
                    process & (ev_kind == EV_FAULT) & (ev_payload[0] == F_RESTART)
                ).astype(jnp.int32)
            # occupancy high-water marks on the post-step state (frozen
            # lanes' state is unchanged, so their marks are stable).
            # Queue occupancy is tracked INCREMENTALLY: the pop clears
            # exactly one valid slot (when live and not deferred) and
            # every successful push — messages, duplicates, timers, the
            # restart boot — fills exactly one free slot and bumped
            # next_seq, so the delta is (next_seq' - next_seq) minus the
            # pop. Replaces a [Q]-wide re-sum of eq["valid"] per event
            # with three scalar ops; equal to the old sum by
            # construction (host-oracle differential asserts it).
            popped_one = live if defer is None else (live & ~defer)
            eq_n = (
                fr["eq_n"]
                - popped_one.astype(jnp.int32)
                + (next_seq - s.next_seq)
            )
            n_clog = (
                lax.population_count(clogged).sum()
                if cfg.clog_packed
                else clogged.sum()
            ).astype(jnp.int32)
            fr = {
                "d0": d0,
                "d1": d1,
                "eq_n": eq_n,
                "ck_step": jnp.where(ck_slot, new_step, fr["ck_step"]),
                "ck_d0": jnp.where(ck_slot, d0, fr["ck_d0"]),
                "ck_d1": jnp.where(ck_slot, d1, fr["ck_d1"]),
                "inj": inj,
                "dup": fr_dup,
                "amnesia": fr_amnesia,
                "q_hwm": jnp.maximum(fr["q_hwm"], eq_n),
                "clog_hwm": jnp.maximum(fr["clog_hwm"], n_clog),
                "kill_hwm": jnp.maximum(
                    fr["kill_hwm"], killed.sum().astype(jnp.int32)
                ),
            }

        # -- scenario coverage (observability; gate-off adds NO ops) --------
        cov = s.cov
        if cfg.coverage:
            # abstract-state projection of the POST-step state: the
            # scenario this event's processing REACHED (the model
            # contract: Machine.coverage_projection, low 3 bits = its
            # coarsest "phase" notion)
            abs_word = m.coverage_projection(nodes, new_now)
            # fault-environment context: killed count + active chaos
            # windows — the same abstract state under partition vs storm
            # is a different scenario
            n_killed = jnp.clip(killed.sum().astype(jnp.int32), 0, 7)
            clog_any = jnp.any(clogged != 0)
            ctx = (
                n_killed
                | (clog_any.astype(jnp.int32) << 3)
                | ((storm_loss > 0).astype(jnp.int32) << 4)
                | ((delay_spike > 0).astype(jnp.int32) << 5)
            )
            # new chaos windows extend the context word only when their
            # kind is enabled — legacy configs hash identical inputs
            if cfg.faults.allow_pause:
                ctx = ctx | (jnp.any(paused_until > 0).astype(jnp.int32) << 6)
            if cfg.faults.allow_skew:
                ctx = ctx | (jnp.any(skew_q10 > 0).astype(jnp.int32) << 7)
            # event discriminant: payload[0] for msg (message type) and
            # fault (op) events; timers fold 0 — timer ids are
            # epoch-encoded, and counting every restart epoch as a new
            # scenario would inflate the map
            op_word = jnp.where(ev_kind == EV_TIMER, jnp.int32(0), ev_payload[0])
            band = cov_band(ev_kind, op_word, self.cov_band_bits)
            if cfg.faults.strict_restart:
                # a strict restart is a different scenario class than a
                # plain kill/restart: route it to the amnesia band
                band = jnp.where(
                    (ev_kind == EV_FAULT) & (ev_payload[0] == F_RESTART),
                    jnp.int32(COV_BAND_AMNESIA),
                    band,
                )
            slot = cov_slot(
                abs_word, ev_kind, ev_node, op_word, ctx, cfg.cov_slots_log2,
                band_bits=self.cov_band_bits, band=band,
            )
            # same condition as the trace ring / digest: popped events.
            # Buffered regime (cov_buffer > 0): append the slot index to
            # the tiny per-lane ring instead of scattering into the
            # 2 KiB map — the map never appears in the step program;
            # run_segment folds the buffer at the flush cadence, at
            # segment exit, and therefore at every freeze point. OR is
            # commutative + idempotent, so the final map is
            # bit-identical to the per-event fold (the cov_buffer=0
            # oracle; tests/test_coverage.py differentials).
            if self._cov_buffered:
                buf, buf_n = cov_push(cov["buf"], cov["buf_n"], slot, live)
                cov = dict(cov, buf=buf, buf_n=buf_n)
            else:
                cov = {"map": cov_fold(cov["map"], slot, live)}
            if layout.dup_active:
                # synthetic dup band: a step that enqueued >= 1 duplicate
                # is its own scenario class (one extra word fold, only
                # when the gate is on)
                dup_slot = cov_slot(
                    abs_word, ev_kind, ev_node, n_dups, ctx,
                    cfg.cov_slots_log2, band_bits=self.cov_band_bits,
                    band=jnp.int32(COV_BAND_DUP),
                )
                dup_hit = live & (n_dups > 0)
                if self._cov_buffered:
                    buf, buf_n = cov_push(
                        cov["buf"], cov["buf_n"], dup_slot, dup_hit
                    )
                    cov = dict(cov, buf=buf, buf_n=buf_n)
                else:
                    cov = {"map": cov_fold(cov["map"], dup_slot, dup_hit)}

        # -- invariants / termination ---------------------------------------
        ok, code = m.invariant(nodes, new_now)
        inv_fail = process & ~ok
        if cfg.provenance:
            # the violation's provenance: the handling node's lineage
            # cone at the step whose transition broke the invariant
            # (its word already folds the popped event's). Captured at
            # the FIRST failure only — that is the violation the fail
            # code names.
            fail_prov = jnp.where(
                inv_fail & ~s.failed, sender_prov | ev_prov, s.fail_prov
            )
        else:
            fail_prov = s.fail_prov
        failed = failed | inv_fail
        fail_code = jnp.where(inv_fail, code, fail_code)
        if active is None:
            done = s.done | ~any_valid | horizon_hit | m.is_done(nodes, new_now)
        else:
            done = (
                s.done
                | (active & ~any_valid)
                | horizon_hit
                | (active & m.is_done(nodes, new_now))
            )

        return LaneState(
            now_us=new_now if active is None else jnp.where(active, new_now, s.now_us),
            next_seq=next_seq,
            step=s.step + (1 if active is None else active.astype(jnp.int32)),
            rng_key=key,
            done=done,
            failed=failed,
            fail_code=fail_code,
            horizon_hit=s.horizon_hit | horizon_hit,
            msg_count=msg_count,
            storm_loss=storm_loss,
            delay_spike=delay_spike,
            eq_time=eq["time"],
            eq_seq=eq["seq"],
            eq_kind=eq["kind"],
            eq_node=eq["node"],
            eq_src=eq["src"],
            eq_payload=eq["payload"],
            eq_valid=eq["valid"],
            clogged=clogged,
            killed=killed,
            paused_until=paused_until,
            skew_q10=skew_q10,
            node_prov=node_prov,
            eq_prov=eq.get("prov", s.eq_prov),
            fail_prov=fail_prov,
            nodes=nodes,
            ring=ring,
            fr=fr,
            cov=cov,
        )

    # -- batch runners -------------------------------------------------------

    def init_batch(self, seeds: jax.Array) -> LaneState:
        return jax.vmap(self.init_lane)(seeds)

    def step_batch(self, state: LaneState) -> LaneState:
        # the per-lane freeze rides inside the step's write masks
        # (`active=`) instead of a post-hoc tree_where that re-selected
        # every [L, Q] queue leaf and the whole nodes tree each step
        active = ~(state.done | state.failed)
        if self.use_megakernel:
            # whole-event megakernel: pop + gather + the v3 RNG block
            # (+ the digest fold under the recorder) leave one fused
            # VMEM pass; the rest of the step consumes them via
            # step_block and draws/folds nothing itself
            fr_on = self.config.flight_recorder
            idx, any_valid, popped, words, digest = step_megakernel(
                state.eq_time, state.eq_seq, state.eq_valid,
                state.eq_kind, state.eq_node, state.eq_src, state.eq_payload,
                state.rng_key, state.step, self._rng_layout.total_words,
                d0=state.fr["d0"] if fr_on else None,
                d1=state.fr["d1"] if fr_on else None,
                digest_fold=digest_fold if fr_on else None,
                interpret=self._pallas_interpret,
            )
            block = (words,) + digest
            return jax.vmap(
                lambda st, i, a, act, p, blk: self._lane_step_popped(
                    st, i, a, popped=p, active=act, step_block=blk
                )
            )(state, idx, any_valid, active, popped, block)
        if self.use_pallas_pop:
            # fused pop+gather: the popped event tuple leaves the kernel
            # in the same VMEM pass as the argmin
            idx, any_valid, popped = pop_gather_batch(
                state.eq_time, state.eq_seq, state.eq_valid,
                state.eq_kind, state.eq_node, state.eq_src, state.eq_payload,
                use_pallas=True, interpret=self._pallas_interpret,
            )
            return jax.vmap(
                lambda st, i, a, act, p: self._lane_step_popped(
                    st, i, a, popped=p, active=act
                )
            )(state, idx, any_valid, active, popped)
        idx, any_valid = pop_earliest_batch(
            state.eq_time, state.eq_seq, state.eq_valid, use_pallas=False
        )
        return jax.vmap(
            lambda st, i, a, act: self._lane_step_popped(st, i, a, active=act)
        )(state, idx, any_valid, active)

    def run_batch(self, seeds: jax.Array, max_steps: int = 10_000) -> BatchResult:
        """Run every seed lane to completion (or max_steps events/lane).

        jit-compile with `jax.jit(engine.run_batch, static_argnums=1)` or
        use `make_runner`.
        """
        state = self.init_batch(seeds)
        final = self.run_segment(state, max_steps)
        return BatchResult(
            seeds=seeds,
            done=final.done,
            failed=final.failed,
            fail_code=final.fail_code,
            fail_prov=final.fail_prov,
            now_us=final.now_us,
            steps=final.step,
            msg_count=final.msg_count,
            summary=jax.vmap(self.machine.summary)(final.nodes),
            ring=final.ring,
            fr=final.fr,
            cov=final.cov,
        )

    def _cov_flush_batch(self, state: LaneState) -> LaneState:
        """Fold every lane's buffered coverage slots into its packed
        bit map and reset the buffer counts. Bit-identical to having
        folded each slot at its original event (OR commutes and is
        idempotent); the buffer contents are left in place — only the
        live count resets, and cov_push masks dead entries to 0 anyway,
        so stale tails stay deterministic for check_determinism."""
        with _xprof.scope("cov_flush"):
            cov = state.cov
            new_map = cov_flush_batch(
                cov["map"], cov["buf"], cov["buf_n"],
                use_pallas=self.use_pallas_pop,
                interpret=self._pallas_interpret,
            )
            zeros = jnp.zeros_like(cov["buf_n"])
            return state.replace(cov=dict(cov, map=new_map, buf_n=zeros))

    def run_segment(self, state: LaneState, segment_steps: int) -> LaneState:
        """Advance the batch at most `segment_steps` events per lane (stops
        early if every lane finishes). Building block for streaming.

        In the buffered-coverage regime the body folds the slot buffers
        into the bit maps every `_cov_flush_every` iterations (a SCALAR
        cadence predicate — the untaken branch costs nothing), and an
        unconditional exit flush runs after the loop. The exit flush is
        what makes flush-on-freeze safe with no per-lane bookkeeping: a
        lane frozen mid-segment (done/failed; step_batch's `active`
        mask) simply stops appending, and whatever its buffer holds is
        folded here before any consumer — run_batch's harvest, the
        stream's cov-map OR — can observe the map."""

        def cond(carry):
            s, it = carry
            with _xprof.collective_scope("segment-done-any"):
                # madsim: collective(segment-done-any, reduce=any) — the
                # while-cond early-exit mask: under the mesh this is the one
                # designed per-event-step collective (a 1-bit or-all-reduce)
                return (it < segment_steps) & jnp.any(~(s.done | s.failed))

        def body(carry):
            s, it = carry
            s, it = self.step_batch(s), it + 1
            if self._cov_buffered:
                # cadence flush: overflow is impossible by construction
                # (cov_buffer // slots_per_step iterations fill at most
                # cov_buffer entries), so no per-event overflow branch
                # ever touches the map. The predicate is a scalar, so
                # only the taken branch executes.
                s = lax.cond(
                    it % self._cov_flush_every == 0,
                    self._cov_flush_batch,
                    lambda x: x,
                    s,
                )
            return s, it

        with _xprof.scope("step"):
            final, _ = lax.while_loop(cond, body, (state, jnp.int32(0)))
        if self._cov_buffered:
            # segment-exit flush — skipped only when NO lane holds a
            # buffered slot (e.g. segment_steps is a multiple of the
            # cadence, so the last body flush already drained; or every
            # lane froze before appending), which the any-reduce below
            # detects. cov-buffer-fold in srules.COLLECTIVES.
            with _xprof.scope("cov_flush"):
                with _xprof.collective_scope("cov-buffer-fold"):
                    # madsim: collective(cov-buffer-fold, reduce=or)
                    pending = jnp.any(final.cov["buf_n"] > 0)
                final = lax.cond(
                    pending, self._cov_flush_batch, lambda x: x, final
                )
        return final

    def _stream_fns(
        self,
        segment_steps: int,
        max_steps: int,
        ring_capacity: int,
        batch: int,
        donate: bool = True,
        segments_per_dispatch: int = 8,
        aot: bool = False,
        mesh=None,
    ):
        """Jitted building blocks for run_stream, cached per shape-affecting
        params (fresh jit wrappers would recompile on every call).

        Returns (init_carry, segment, supersegment, reset_rings).

        With `mesh` (a 1-D "batch" mesh, parallel.make_mesh), the four
        fns are jitted with EXPLICIT in/out_shardings derived from the
        declared carry-axis table (`parallel.carry_shardings` over
        `analysis.srules.CARRY_AXES`): every lane leaf pinned
        `NamedSharding(mesh, P("batch"))`, every global leaf replicated
        `P()` — one hunt spans all devices as a single jitted SPMD
        program, donation preserved. The pinned layout is what places
        the 17 registered collectives (srules.COLLECTIVES) at segment
        boundaries: per-lane state never crosses devices inside the
        per-event loop, because only the segment-level folds (refill
        count/ranks, harvest-completed, ring appends, fr folds,
        cov-map OR) read lane values into replicated leaves. `mesh` is
        part of the fns cache key; `aot` and `mesh` are mutually
        exclusive (exported modules are traced unsharded).

        `segment` / `supersegment` / `reset_rings` donate their
        StreamCarry argument when `donate` (the multi-MB lane state is
        aliased in place instead of copied in HBM every call; toggle
        kept for one release so bit-identity vs the undonated path stays
        assertable). A donated carry is CONSUMED: never touch a carry
        after passing it back in — read counters/rings first.

        `supersegment` is the pipelined executor's device half: an inner
        `lax.while_loop` advances up to `segments_per_dispatch` whole
        segments (refill + advance + harvest each) per host dispatch,
        with the termination check (`completed < need`) and the
        ring-pressure check ON DEVICE — the exact conditions the r5 host
        loop evaluated between segments, so the executed segment
        sequence is bit-identical to the per-segment driver. When a ring
        crosses its drain mark (count > cap - batch) the loop parks
        until the host drains, which bounds appends at `cap` regardless
        of how many dispatches are in flight."""
        cache = getattr(self, "_stream_cache", None)
        if cache is None:
            cache = self._stream_cache = {}
        # scan-over-segments (r12): the supersegment's fixed-count
        # dispatch loop as lax.scan of a predicated segment body
        # instead of lax.while_loop. MADSIM_TPU_STREAM_SCAN=0 keeps the
        # while form A/B-able for one release; both execute the
        # bit-identical segment sequence (see supersegment below).
        use_scan = os.environ.get("MADSIM_TPU_STREAM_SCAN", "1") != "0"
        if aot and mesh is not None:
            raise ValueError(
                "AOT stream fns cannot serve a meshed run: jax.export "
                "modules are traced with unsharded avals (run_stream "
                "gates aot to mesh=None)"
            )
        # jax.sharding.Mesh hashes by (devices, axis names), so two
        # calls with equal meshes share one quartet. The xprof gate is
        # part of the key: phase scopes are inserted at TRACE time, so
        # flipping MADSIM_TPU_XPROF between runs must re-trace rather
        # than serve an un(der)-annotated cached quartet.
        key = (segment_steps, max_steps, ring_capacity, batch, donate,
               segments_per_dispatch, use_scan, aot, mesh,
               _xprof.enabled())
        if key in cache:
            return cache[key]

        cap = ring_capacity
        drain_mark = cap - batch

        def _append_ring(buf, count, mask, values):
            """Scatter-free ordered append: masked lane of rank r (in lane
            order) lands at ring slot count+r. Inverted as a gather — slot
            j's source lane is the first lane whose inclusive cumsum equals
            j-count+1 (searchsorted: O(cap log L), vs O(cap*L) for a
            one-hot matrix) — so it stays cheap at pod-scale batches.
            Entries past capacity are dropped; the host's drain policy
            makes that unreachable."""
            with _xprof.scope("ring_append"):
                with _xprof.collective_scope("ring-append-ranks"):
                    # madsim: collective(ring-append-ranks, reduce=scan)
                    csum = jnp.cumsum(mask.astype(jnp.int32))  # [L], rank+1 at masked lanes
                n_new = csum[-1]
                want_rank = jnp.arange(cap, dtype=jnp.int32) - count + 1  # 1-based
                src = jnp.searchsorted(csum, want_rank, side="left").astype(jnp.int32)
                fills = (want_rank >= 1) & (want_rank <= n_new)
                with _xprof.collective_scope("ring-append-gather"):
                    # madsim: collective(ring-append-gather, reduce=gather)
                    vals = values[jnp.clip(src, 0, mask.shape[0] - 1)]
                buf = jnp.where(fills, vals, buf)
                return buf, count + n_new

        def _counters(c: StreamCarry) -> jax.Array:
            with _xprof.scope("counters"):
                return _counters_impl(c)

        def _counters_impl(c: StreamCarry) -> jax.Array:
            over = (c.fail_count > cap) | (c.ab_count > cap)
            return jnp.stack(
                [
                    c.completed.astype(jnp.uint32),
                    c.fail_count.astype(jnp.uint32),
                    c.ab_count.astype(jnp.uint32),
                    c.next_seed,
                    over.astype(jnp.uint32),
                    c.segments.astype(jnp.uint32),
                    # global coverage slots hit: rides the one small
                    # counters transfer the host polls anyway, so the
                    # live coverage curve costs zero extra syncs. Gate
                    # off = a literal zero — the popcount op itself is
                    # specialized out of the lowered segment (the
                    # gate-off HLO pin in tests/test_step_gates.py
                    # string-matches its absence).
                    (
                        lax.population_count(c.cov_map).sum(dtype=jnp.uint32)
                        if self.config.coverage
                        else jnp.uint32(0)
                    ),
                ]
            )

        def init_carry(seeds) -> StreamCarry:
            with _xprof.collective_scope("seed-counter-init"):
                # madsim: collective(seed-counter-init, reduce=gather)
                next_seed0 = seeds[-1] + jnp.uint32(1)
            c = StreamCarry(
                state=self.init_batch(seeds),
                seeds=seeds,
                done=jnp.zeros((seeds.shape[0],), bool),
                next_seed=next_seed0,
                completed=jnp.int32(0),
                segments=jnp.int32(0),
                fail_seeds=jnp.zeros((cap,), jnp.uint32),
                fail_codes=jnp.zeros((cap,), jnp.int32),
                fail_provs=jnp.zeros(
                    (cap if self.config.provenance else 0,), jnp.uint32
                ),
                fail_count=jnp.int32(0),
                ab_seeds=jnp.zeros((cap,), jnp.uint32),
                ab_count=jnp.int32(0),
                counters=jnp.zeros((7,), jnp.uint32),
                # recorder off: a ZERO-LENGTH leaf, not a vector of
                # zeros — the dead operand would otherwise ride the
                # whole supersegment while_loop carry (the host-visible
                # schema is unaffected: the stats dict synthesizes
                # nothing unless the gate is on)
                fr_metrics=jnp.zeros(
                    (FR_METRICS_LEN if self.config.flight_recorder else 0,),
                    jnp.int32,
                ),
                cov_map=(
                    empty_cov_map(self.config.cov_slots_log2)
                    if self.config.coverage
                    else jnp.zeros((0,), jnp.int32)
                ),
            )
            return c.replace(counters=_counters(c))

        def _segment_impl(c: StreamCarry) -> StreamCarry:
            # 1. refill lanes harvested at the end of the previous segment
            #    (device-side ranks + seed counter: gapless, in lane order)
            with _xprof.scope("refill"):
                with _xprof.collective_scope("refill-count"):
                    n_refill = c.done.sum(dtype=jnp.int32)  # madsim: collective(refill-count, reduce=sum)

                def do_refill(_):
                    with _xprof.collective_scope("refill-ranks"):
                        # madsim: collective(refill-ranks, reduce=scan)
                        ranks = jnp.cumsum(c.done.astype(jnp.int32)) - 1
                    fresh_seeds = c.next_seed + ranks.astype(jnp.uint32)
                    fresh = self.init_batch(fresh_seeds)
                    return (
                        tree_where(c.done, fresh, c.state),
                        jnp.where(c.done, fresh_seeds, c.seeds),
                        c.next_seed + n_refill.astype(jnp.uint32),
                    )

                state, seeds, next_seed = lax.cond(
                    n_refill > 0,
                    do_refill,
                    lambda _: (c.state, c.seeds, c.next_seed),
                    None,
                )

            # 2. advance the batch one segment
            state = self.run_segment(state, segment_steps)

            # 3. harvest on-device: count completions, ring-append failing
            #    seeds/codes and abandoned (over-cap) seeds
            with _xprof.scope("harvest"):
                over_cap = state.step >= max_steps
                done = state.done | state.failed | over_cap
                with _xprof.collective_scope("harvest-completed"):
                    completed = c.completed + done.sum(dtype=jnp.int32)  # madsim: collective(harvest-completed, reduce=sum)
                fail_mask = done & state.failed
                fail_seeds, fail_count = _append_ring(
                    c.fail_seeds, c.fail_count, fail_mask, seeds
                )
                fail_codes, _ = _append_ring(
                    c.fail_codes, c.fail_count, fail_mask, state.fail_code
                )
                # violation provenance words ride the same failure ring —
                # harvested with the seeds/codes at the existing drain,
                # zero extra steady-state syncs
                fail_provs = c.fail_provs
                if self.config.provenance:
                    fail_provs, _ = _append_ring(
                        c.fail_provs, c.fail_count, fail_mask, state.fail_prov
                    )
                ab_mask = done & ~state.failed & over_cap
                ab_seeds, ab_count = _append_ring(
                    c.ab_seeds, c.ab_count, ab_mask, seeds
                )

            # flight-recorder totals ride the harvest: injection counts
            # of lanes finishing THIS segment sum in, high-water marks
            # max in — one small device-resident vector, read by the
            # host only at the final drain (zero extra steady-state
            # syncs)
            fr_metrics = c.fr_metrics
            if self.config.flight_recorder:
                with _xprof.scope("fr_fold"):
                    frs = state.fr
                    nk = len(FAULT_KIND_NAMES)
                    ne = len(FR_EXTRA_NAMES)
                    with _xprof.collective_scope("fr-fold"):
                        # madsim: collective(fr-fold, reduce=sum)
                        inj_tot = fr_metrics[:nk] + (
                            frs["inj"] * done[:, None].astype(jnp.int32)
                        ).sum(axis=0)
                        extra_tot = jnp.stack(
                            [
                                # madsim: collective(fr-fold, reduce=sum)
                                fr_metrics[nk + i] + jnp.where(done, frs[k], 0).sum()
                                for i, k in enumerate(FR_EXTRA_NAMES)
                            ]
                        )
                    with _xprof.collective_scope("fr-hwm"):
                        hwm = jnp.stack(
                            [
                                jnp.maximum(
                                    fr_metrics[nk + ne + i],
                                    # madsim: collective(fr-hwm, reduce=max)
                                    jnp.where(done, frs[k], 0).max(),
                                )
                                for i, k in enumerate(
                                    ("q_hwm", "clog_hwm", "kill_hwm")
                                )
                            ]
                        )
                    fr_metrics = jnp.concatenate([inj_tot, extra_tot, hwm])

            # coverage rides the harvest too: OR every lane's bit map
            # into the global vector. ALL lanes, not just done ones —
            # lane maps are monotone (bits only set), so the fold is
            # idempotent and in-flight lanes contribute their partial
            # coverage to the live curve the host polls.
            cov_map = c.cov_map
            if self.config.coverage:
                # the cov-map-or collective lives in cov_fold_words
                with _xprof.scope("cov_fold"), _xprof.collective_scope(
                    "cov-map-or"
                ):
                    cov_map = cov_map | cov_fold_words(
                        state.cov["map"],
                        shards=mesh.size if mesh is not None else 1,
                    )

            new = StreamCarry(
                state=state,
                seeds=seeds,
                done=done,
                next_seed=next_seed,
                completed=completed,
                segments=c.segments + 1,
                fail_seeds=fail_seeds,
                fail_codes=fail_codes,
                fail_provs=fail_provs,
                fail_count=fail_count,
                ab_seeds=ab_seeds,
                ab_count=ab_count,
                counters=c.counters,
                fr_metrics=fr_metrics,
                cov_map=cov_map,
            )
            return new.replace(counters=_counters(new))

        def _dispatch_go(cc: StreamCarry, need):
            # The host loop's between-segment checks, moved on-device:
            # stop at the completion target (same crossing as the r5
            # per-segment driver — bit-identical executed-segment
            # sequence for any dispatch depth), park on ring pressure
            # (host must drain), else advance another whole segment.
            pressure = (cc.fail_count > drain_mark) | (cc.ab_count > drain_mark)
            return (cc.completed < need) & ~pressure

        def supersegment(c: StreamCarry, need) -> StreamCarry:
            if use_scan:
                # scan-over-segments: a fixed segments_per_dispatch trip
                # count with the go-predicate as a per-iteration
                # lax.cond (scalar, so the parked branch executes
                # nothing). Bit-identical to the while form: completed
                # only grows and the rings only fill WITHIN a dispatch
                # (drains happen on the host between dispatches), so
                # the go-predicate is monotone — once it flips false it
                # stays false, and the executed segment prefix is
                # exactly the while_loop's.
                def body(cc, _):
                    cc = lax.cond(
                        _dispatch_go(cc, need),
                        _segment_impl,
                        lambda x: x,
                        cc,
                    )
                    return cc, None

                final, _ = lax.scan(
                    body, c, None, length=segments_per_dispatch
                )
                return final

            def cond(carry):
                cc, it = carry
                return (it < segments_per_dispatch) & _dispatch_go(cc, need)

            def body(carry):
                cc, it = carry
                return _segment_impl(cc), it + 1

            final, _ = lax.while_loop(cond, body, (c, jnp.int32(0)))
            return final

        def reset_rings(c: StreamCarry) -> StreamCarry:
            new = c.replace(fail_count=jnp.int32(0), ab_count=jnp.int32(0))
            return new.replace(counters=_counters(new))

        donate_kw = {"donate_argnums": (0,)} if donate else {}
        if mesh is not None:
            # The mesh path: pin every leaf's placement at the jit
            # boundary per the declared CARRY_AXES axis. Donation
            # composes because in_shardings == out_shardings per leaf —
            # XLA aliases each shard of the donated carry in place, the
            # same zero-copy contract as the single-device path (T003
            # guards the rebuild site). `need` is a replicated scalar.
            from ..parallel import carry_shardings, seed_sharding
            from jax.sharding import NamedSharding, PartitionSpec

            seeds_aval = jax.ShapeDtypeStruct((batch,), jnp.uint32)
            cshard = carry_shardings(
                mesh, jax.eval_shape(init_carry, seeds_aval)
            )
            repl = NamedSharding(mesh, PartitionSpec())
            fns = (
                jax.jit(
                    init_carry,
                    in_shardings=(seed_sharding(mesh),),
                    out_shardings=cshard,
                ),
                jax.jit(
                    _segment_impl,
                    in_shardings=(cshard,),
                    out_shardings=cshard,
                    **donate_kw,
                ),
                jax.jit(
                    supersegment,
                    in_shardings=(cshard, repl),
                    out_shardings=cshard,
                    **donate_kw,
                ),
                jax.jit(
                    reset_rings,
                    in_shardings=(cshard,),
                    out_shardings=cshard,
                    **donate_kw,
                ),
            )
            cache[key] = fns
            return fns
        fns = (
            jax.jit(init_carry),
            jax.jit(_segment_impl, **donate_kw),
            jax.jit(supersegment, **donate_kw),
            jax.jit(reset_rings, **donate_kw),
        )
        if aot:
            fns = self._aot_stream_fns(
                fns,
                (init_carry, _segment_impl, supersegment, reset_rings),
                donate_kw=donate_kw,
                batch=batch,
                fns_key=key,
            )
        cache[key] = fns
        return fns

    def _aot_stream_fns(self, jitted, raw, *, donate_kw, batch, fns_key):
        """AOT-serialize the streaming fns via `jax.export`, keyed so a
        warm fleet worker deserializes the traced+lowered StableHLO
        instead of re-tracing Python (the r11 flagship warm start was
        18.2 s, TRACE-dominated — the persistent XLA cache already
        covers the compile half).

        Key = `compile_cache.cache_subkey` (jax version / stream / lane
        shape / device topology) + a sha1 over the package source fingerprint, the full
        EngineConfig, the machine identity and scalar params, the
        stream-fns shape tuple, the kernel-backend flags and the jax
        backend — everything that can change the traced program. A key
        that misses (or a corrupt/stale artifact) degrades to a live
        trace which is then exported and saved for the next worker.
        Every path EXECUTES through `jax.jit(exported.call)` — never
        mixing "exported on warm, plain jit on cold" — so both paths
        compile the same exported-call HLO and share one persistent
        XLA cache entry.

        Only called with `mesh is None` (run_stream gates it): an
        exported module is traced with unsharded avals, and replaying
        it under explicit shardings would silently drop the layout
        contract."""
        import hashlib
        import time

        from jax import export as jexport

        from .. import compile_cache as _cc

        m = self.machine
        scalars = {
            k: v
            for k, v in sorted(vars(m).items())
            if isinstance(v, (int, float, str, bool))
        }
        ident = "|".join(
            [
                _cc.source_fingerprint(),
                repr(self.config),
                f"{type(m).__module__}.{type(m).__qualname__}",
                repr(scalars),
                repr(fns_key),
                repr(
                    (
                        self.use_pallas_pop,
                        self.use_megakernel,
                        self._pallas_interpret,
                    )
                ),
                jax.default_backend(),
            ]
        )
        # devices=1: an exported module is a SINGLE-device program by
        # construction (this path is gated to mesh=None). The explicit
        # topology in the key is the refusal contract — if meshed
        # exports ever land, their d{mesh.size} artifacts can never be
        # deserialized into an unsharded run or vice versa.
        subkey = (
            _cc.cache_subkey(
                rng_stream=self.config.rng_stream, lanes=batch, devices=1
            )
            + "-"
            + hashlib.sha1(ident.encode()).hexdigest()[:16]
        )
        names = ("init_carry", "segment", "supersegment", "reset_rings")
        seeds_aval = jax.ShapeDtypeStruct((batch,), jnp.uint32)
        carry_aval = jax.eval_shape(jitted[0], seeds_aval)
        need_aval = jax.ShapeDtypeStruct((), jnp.int32)
        avals = {
            "init_carry": (seeds_aval,),
            "segment": (carry_aval,),
            "supersegment": (carry_aval, need_aval),
            "reset_rings": (carry_aval,),
        }
        # jax.export cannot serialize custom pytree nodes (the flax
        # struct dataclasses and model states riding the carry), so
        # each fn is exported over FLAT LEAF LISTS and the pytree
        # structure is rebuilt at the call boundary. The treedefs come
        # from a local eval_shape — abstract tracing, milliseconds —
        # never from the artifact, so structure drift between writer
        # and reader surfaces as a leaf-count/shape mismatch (a loud
        # error), not a misdecoded tree.
        out_tree = jax.tree.structure(carry_aval)

        def _make_flat(rfn, in_tree):
            def flat_fn(*leaves):
                args = jax.tree.unflatten(in_tree, list(leaves))
                return tuple(jax.tree.leaves(rfn(*args)))

            return flat_fn

        def _make_wrapped(exp):
            def from_export(*args):
                flat = exp.call(*jax.tree.leaves(args))
                return jax.tree.unflatten(out_tree, list(flat))

            return from_export

        timings = self.compile_timings = {
            "trace_s": 0.0,
            "aot_hits": [],
            "aot_misses": [],
            "aot_key": subkey,
        }
        out = []
        for name, jfn, rfn in zip(names, jitted, raw):
            kw = {} if name == "init_carry" else donate_kw
            in_leaves, in_tree = jax.tree.flatten(avals[name])
            exp = None
            blob = _cc.load_aot(subkey, name)
            if blob is not None:
                try:
                    exp = jexport.deserialize(bytearray(blob))
                    timings["aot_hits"].append(name)
                except Exception as e:
                    _stream_log.warning(
                        "corrupt AOT artifact %s/%s (%s); re-tracing",
                        subkey, name, e,
                    )
                    exp = None
            if exp is None:
                t0 = time.perf_counter()  # madsim: allow(D001) — host-side timing
                try:
                    exp = jexport.export(jax.jit(_make_flat(rfn, in_tree)))(
                        *in_leaves
                    )
                    blob = bytes(exp.serialize())
                except Exception as e:
                    _stream_log.warning(
                        "jax.export failed for %s (%s); falling back to "
                        "plain jit for this process", name, e,
                    )
                    out.append(jfn)
                    continue
                timings["trace_s"] += time.perf_counter() - t0  # madsim: allow(D001)
                timings["aot_misses"].append(name)
                _cc.save_aot(subkey, name, blob)
            out.append(jax.jit(_make_wrapped(exp), **kw))
        return tuple(out)

    def measure_stream_trace(
        self,
        batch: int,
        segment_steps: int = 256,
        max_steps: int = 10_000,
        segments_per_dispatch: int = 8,
        donate: Optional[bool] = None,
    ) -> float:
        """Time the TRACE+LOWER phase of the streaming supersegment at
        this shape — the component of a cold compile that `jax.jit`
        re-pays every process even when the persistent XLA cache
        serves the executable. bench.py reports it as `trace_s` next
        to compile_s_cold/warm so TRACE- vs XLA-dominance is a
        recorded number. `jitted.lower()` always re-traces, so calling
        this AFTER the timed cold run leaves that measurement
        untouched."""
        import time

        if donate is None:
            donate = os.environ.get("MADSIM_TPU_STREAM_DONATE", "1") not in ("", "0")
        init_carry, _segment, supersegment, _reset = self._stream_fns(
            segment_steps, max_steps, 2 * batch, batch,
            donate=donate, segments_per_dispatch=segments_per_dispatch,
        )
        seeds_aval = jax.ShapeDtypeStruct((batch,), jnp.uint32)
        carry_aval = jax.eval_shape(init_carry, seeds_aval)
        t0 = time.perf_counter()  # madsim: allow(D001) — host-side timing
        supersegment.lower(carry_aval, jax.ShapeDtypeStruct((), jnp.int32))
        return time.perf_counter() - t0  # madsim: allow(D001)

    def compile_stream(
        self,
        batch: int,
        segment_steps: int = 256,
        max_steps: int = 10_000,
        segments_per_dispatch: int = 8,
        donate: Optional[bool] = None,
    ) -> None:
        """Force-compile the streaming quartet at this shape WITHOUT
        executing a stream: build (or fetch) the jitted fns exactly as
        the unsharded `run_stream` would — same `_stream_fns` cache
        key, same AOT gating — then `.lower().compile()` each at its
        declared avals. This is a worker's start cost in isolation:
        trace (or AOT deserialize) + XLA compile (or persistent-cache
        hit), with zero device execution mixed in. bench.py times this
        as compile_s_cold / compile_s_warm; the old run(1)-based timing
        conflated the start cost with the FIRST DISPATCH's execution,
        which at the 8192-lane flagship shape on the 1-core CPU
        reference box is ~17 s of fixed-shape compute — drowning the
        ~1 s the warm start actually pays."""
        from ..compile_cache import aot_enabled

        if donate is None:
            donate = os.environ.get("MADSIM_TPU_STREAM_DONATE", "1") not in ("", "0")
        init_carry, segment, supersegment, reset_rings = self._stream_fns(
            segment_steps, max_steps, 2 * batch, batch,
            donate=donate, segments_per_dispatch=segments_per_dispatch,
            aot=aot_enabled(),
        )
        seeds_aval = jax.ShapeDtypeStruct((batch,), jnp.uint32)
        carry_aval = jax.eval_shape(init_carry, seeds_aval)
        need_aval = jax.ShapeDtypeStruct((), jnp.int32)
        for fn, avals in (
            (init_carry, (seeds_aval,)),
            (segment, (carry_aval,)),
            (supersegment, (carry_aval, need_aval)),
            (reset_rings, (carry_aval,)),
        ):
            fn.lower(*avals).compile()

    def stream_compile_autopsy(
        self,
        batch: int,
        segment_steps: int = 256,
        max_steps: int = 10_000,
        segments_per_dispatch: int = 8,
        donate: Optional[bool] = None,
        mesh=None,
    ) -> list:
        """Per-fn compile autopsy of the streaming quartet at this
        shape: trace_s / lower_s / backend_s plus cost_analysis flops /
        bytes and memory_analysis peak bytes for each of init_carry,
        segment, supersegment, reset_rings — the `compile_s` opaque
        total split into the three stages the [perf] open item needs
        apart (perf/xprof.compile_autopsy; `prof compile`, bench.py).
        Re-traces by construction, so run it on a throwaway engine or
        accept the duplicate trace cost."""
        from ..perf import xprof

        if donate is None:
            donate = os.environ.get("MADSIM_TPU_STREAM_DONATE", "1") not in ("", "0")
        init_carry, segment, supersegment, reset_rings = self._stream_fns(
            segment_steps, max_steps, 2 * batch, batch,
            donate=donate, segments_per_dispatch=segments_per_dispatch,
            mesh=mesh,
        )
        seeds_aval = jax.ShapeDtypeStruct((batch,), jnp.uint32)
        carry_aval = jax.eval_shape(init_carry, seeds_aval)
        need_aval = jax.ShapeDtypeStruct((), jnp.int32)
        return [
            xprof.compile_autopsy(fn, avals, label=label)
            for label, fn, avals in (
                ("init_carry", init_carry, (seeds_aval,)),
                ("segment", segment, (carry_aval,)),
                ("supersegment", supersegment, (carry_aval, need_aval)),
                ("reset_rings", reset_rings, (carry_aval,)),
            )
        ]

    def run_stream(self, n_seeds: int, **kwargs):
        """See `_run_stream_impl` (the real docstring). This wrapper
        puts the WHOLE streaming call on the host timeline as one
        outer `run_stream` span when a PerfRecorder is active: on a
        host that shares cores with the XLA compute threads (the
        1-core CPU reference box), device execution shows up as the
        host thread being starved at arbitrary points BETWEEN the
        inner spans — the outer span captures it, and the recorder
        reports it as `device_wait` (outer-span time not covered by
        any inner span) instead of losing it to unattributed gaps."""
        from ..perf.recorder import current_recorder

        perf = current_recorder()
        if perf is None:
            return self._run_stream_impl(n_seeds, **kwargs)
        with perf.span(
            "run_stream", n_seeds=n_seeds, batch=kwargs.get("batch", 1024)
        ):
            return self._run_stream_impl(n_seeds, **kwargs)

    def _run_stream_impl(
        self,
        n_seeds: int,
        batch: int = 1024,
        segment_steps: int = 256,
        seed_start: int = 0,
        max_steps: int = 10_000,
        mesh=None,
        pipelined: bool = True,
        segments_per_dispatch: int = 8,
        dispatch_depth: int = 4,
        donate: Optional[bool] = None,
    ):
        """Continuous seed streaming: run at least n_seeds simulations
        keeping every lane busy. Each segment — refill previously-finished
        lanes with fresh seeds (device-side cumsum ranks + a
        device-resident next-seed counter), advance `segment_steps`
        events, then harvest completions into on-device result rings —
        is fused device work; the host only ever reads the small
        `counters` array and drains the failing/abandoned rings when
        they near capacity.

        The default PIPELINED executor dispatches `segments_per_dispatch`
        segments per jitted call (an inner device `lax.while_loop` with
        the termination and ring-pressure checks on-device) and keeps
        `dispatch_depth` such calls in flight before one blocking
        counters read — the steady state runs with ZERO blocking host
        syncs between segments, vs one per segment for the r5 driver
        (`pipelined=False`, kept for one release; both executors run the
        bit-identical segment sequence, so results are equal by
        construction). All streaming ops donate the multi-MB StreamCarry
        (`donate=False` or MADSIM_TPU_STREAM_DONATE=0 opts out), so XLA
        aliases the lane state in HBM instead of copying it every call.

        Seed coverage is gapless: exactly the range
        [seed_start, seed_start + seeds_consumed) enters lanes, in order.
        Lanes exceeding `max_steps` events are abandoned and reported.

        With `mesh` (a 1-D "batch" mesh, parallel.make_mesh), one hunt
        spans all mesh devices as a single jitted SPMD program: every
        StreamCarry leaf is PINNED at the jit boundary per its declared
        `analysis.srules.CARRY_AXES` axis (lane leaves
        `NamedSharding(mesh, P("batch"))`, global leaves replicated
        P()), donation preserved. The 17 registered collectives
        (srules.COLLECTIVES) become their declared all-reduce /
        all-gather at segment boundaries — per-lane state never crosses
        devices inside the per-event loop; the counters poll and the
        coverage-OR are tiny cross-device reductions read at poll
        cadence, and the ring drain gathers only failing lanes (the
        rings are replicated leaves, so host reads stay O(polls +
        drains), never O(devices)). Results are byte-identical to the
        unsharded run at ANY device count: lane keys derive from the
        seed alone (init_lane's per-seed PRNGKey), and every cross-lane
        op computes over the full logical [L] axis under GSPMD — the
        shard-count invariance tests/test_mesh.py pins. `batch` must be
        a multiple of the mesh size.

        Returns {"completed", "failing": [(seed, code)...], "infra":
        [(seed, code)...] (infrastructure artifacts: OVERFLOW lanes —
        queue-capacity aborts, not protocol findings), "abandoned":
        [seed...], "seeds_consumed", "stats": {host_syncs, drains,
        dispatches, device_segments, dispatch_depth,
        segments_per_dispatch, donation, pipelined}}. With
        `config.coverage`, stats additionally carry "coverage"
        (slots_hit / slots_total / fraction / by_band / curve — the
        (completed, slots_hit) pair at every poll) and the result dict a
        "coverage_map" bool array (the global OR of lane maps, the
        artifact `hunt --coverage-out` persists). With
        `config.provenance`, the result dict gains "provenance"
        {seed: violation provenance word} for every drained failing
        lane (engine/provenance.py decodes the words to implicated
        faults).
        """
        import numpy as np

        if donate is None:
            donate = os.environ.get("MADSIM_TPU_STREAM_DONATE", "1") not in ("", "0")
        if segments_per_dispatch < 1 or dispatch_depth < 1:
            raise ValueError("segments_per_dispatch and dispatch_depth must be >= 1")

        # Ring capacity: the device parks at the drain mark (cap - batch),
        # and one segment can complete at most `batch` lanes, so the
        # rings can never overflow no matter how many dispatches are in
        # flight.
        ring_capacity = 2 * batch
        # AOT deserialization of the streaming fns ($MADSIM_TPU_AOT_
        # CACHE, compile_cache.aot_enabled): gated to the unsharded
        # path — an exported module is traced without shardings, and
        # replaying it under a mesh would drop the layout contract.
        from ..compile_cache import aot_enabled

        if mesh is not None and mesh.size > 1 and (
            self.use_pallas_pop or self.use_megakernel
        ):
            raise ValueError(
                "meshed runs need the Pallas kernels off "
                "(MADSIM_TPU_PALLAS_POP=0 / MADSIM_TPU_PALLAS_MEGAKERNEL=0, "
                "or Engine(use_pallas_pop=False)): pallas_call blocks "
                "GSPMD sharding propagation, so the lane-pinned layout "
                "cannot cross it"
            )
        init_carry, segment, supersegment, reset_rings = self._stream_fns(
            segment_steps, max_steps, ring_capacity, batch,
            donate=donate, segments_per_dispatch=segments_per_dispatch,
            aot=mesh is None and aot_enabled(),
            mesh=mesh,
        )

        seeds = jnp.arange(seed_start, seed_start + batch, dtype=jnp.uint32)
        if mesh is not None:
            from ..parallel import shard_seeds

            seeds = shard_seeds(seeds, mesh)  # validates mesh axis + batch

        failing: list = []
        infra: list = []
        abandoned: list = []
        # seed -> violation provenance word (EngineConfig.provenance):
        # filled at the same ring drains that surface the seeds
        prov_by_seed: dict = {}
        stats = {"host_syncs": 0, "drains": 0, "dispatches": 0,
                 "dispatch_retries": 0}
        # (completed, slots_hit) at every blocking poll: the live
        # coverage curve — its deltas are the "new slots this poll
        # cycle" signal the plateau detector and StatsEmitter consume
        cov_curve: list = []

        # Transient-backend retry: device dispatches and the blocking
        # counter/ring reads ride a small retry-with-backoff so a
        # plugin/tunnel hiccup doesn't abort an hour-long hunt; a
        # non-transient error (including "donated buffer deleted" — a
        # dispatch that died AFTER consuming its carry cannot be safely
        # replayed) propagates immediately, and exhausted retries fail
        # loud with the attempt count. Counted in stats.
        from .._backend_watchdog import retry_transient

        # Host-timeline tracing (madsim_tpu/perf): when a PerfRecorder
        # is active in this context (--perf-timeline / `perf`), every
        # dispatch/poll/drain below lands on the host timeline as a
        # span. Pure host-side wall-clock accounting — no RNG words, no
        # device-visible values, so streams are untouched by
        # construction. `perf_warmed` tracks which jitted streaming fns
        # this engine has already invoked: the FIRST call of a jitted
        # fn traces + compiles synchronously before the async dispatch,
        # so it is labelled "compile" (near-zero wall on a warm
        # persistent cache), later calls "dispatch"/"init".
        from ..perf.recorder import current_recorder

        perf = current_recorder()
        perf_warmed = self.__dict__.setdefault("_perf_warmed", set())

        def _span_name(fn, hot_name):
            # membership by object identity — the jitted fns are cached
            # on the engine, so the set holds no extra lifetime
            return "compile" if fn not in perf_warmed else hot_name

        def _dispatch(what, fn, *fn_args, span=None):
            def on_retry(attempt, exc, delay_s):
                stats["dispatch_retries"] += 1
                import logging

                logging.getLogger("madsim_tpu.stream").warning(
                    "transient backend error on %s (attempt %d, retrying "
                    "in %.2fs): %s", what, attempt, delay_s, exc,
                )

            # Device-profile attribution (perf/xprof, MADSIM_TPU_XPROF):
            # every executor operation lands in a jax.profiler capture
            # as a named "madsim.<phase>" slice; the dispatch/poll loops
            # stamp the clock-sync markers the merged plane aligns on.
            # Gate off => the shared nullcontext: nothing inserted,
            # bit-identity preserved by construction.
            name = span or what
            if perf is None:
                with _xprof.annotation(name):
                    return retry_transient(
                        lambda: fn(*fn_args), what=what, on_retry=on_retry
                    )
            with perf.span(name), _xprof.annotation(name):
                return retry_transient(
                    lambda: fn(*fn_args), what=what, on_retry=on_retry
                )

        carry = _dispatch(
            "carry init", init_carry, seeds,
            span=_span_name(init_carry, "init"),
        )
        perf_warmed.add(init_carry)

        def drain(c: StreamCarry) -> StreamCarry:
            # madsim: allow(T002) — this IS a designed sync point: the
            # ring drain runs only when a ring crosses its drain mark
            # (or once at stream end), and its cost is budgeted in
            # stats["drains"]; the T002 contract bans *hidden* fetches
            f_seeds, f_codes, f_provs, f_n, a_seeds, a_n = _dispatch(
                "ring drain",
                jax.device_get,
                (c.fail_seeds, c.fail_codes, c.fail_provs, c.fail_count,
                 c.ab_seeds, c.ab_count),
                span="ring_drain",
            )
            stats["drains"] += 1
            stats["host_syncs"] += 1
            for i, (s, code) in enumerate(
                zip(f_seeds[: int(f_n)], f_codes[: int(f_n)])
            ):
                # infra artifacts (fixed-shape overflow aborts) are kept
                # out of the findings bucket: an OVERFLOW lane means
                # "rerun with a bigger queue", not "protocol bug"
                (infra if int(code) == OVERFLOW else failing).append(
                    (int(s), int(code))
                )
                if self.config.provenance:
                    prov_by_seed[int(s)] = int(f_provs[i])
            abandoned.extend(int(s) for s in a_seeds[: int(a_n)])
            reset = _dispatch(
                "ring reset", reset_rings, c,
                span=_span_name(reset_rings, "dispatch"),
            )
            perf_warmed.add(reset_rings)
            return reset

        def poll(c: StreamCarry):
            """The blocking device->host sync: one small counters read."""
            _xprof.sync_marker("counters_poll")
            counters = np.asarray(
                # madsim: allow(T002) — THE designed blocking poll: one
                # small counters read per dispatch_depth dispatches,
                # counted in stats["host_syncs"]; everything else in
                # the dispatch region must stay async
                _dispatch(
                    "counters poll", jax.device_get, c.counters,
                    span="counters_poll",
                )
            )
            stats["host_syncs"] += 1
            if counters[4]:
                raise RuntimeError(
                    "run_stream result ring overflowed (drain policy bug)"
                )
            if self.config.coverage:
                cov_curve.append((int(counters[0]), int(counters[6])))
            return counters

        drain_mark = ring_capacity - batch
        completed = 0
        # hard ceiling well above the expected segment count (progress is
        # guaranteed because over-cap lanes are abandoned at harvest);
        # pipelining adds at most dispatch_depth no-op dispatches per
        # poll cycle, which the per-dispatch ceiling below absorbs
        max_segments = (max_steps // segment_steps + 2) * (n_seeds // batch + 2)

        if pipelined:
            need = jnp.int32(min(n_seeds, 2**31 - 1))
            max_dispatch = max_segments + dispatch_depth * (n_seeds // batch + 4)
            in_flight = 0
            while completed < n_seeds and stats["dispatches"] < max_dispatch:
                # async dispatch: returns immediately, device work queues
                # behind the donated carry chain
                _xprof.sync_marker("dispatch")
                carry = _dispatch(
                    "supersegment dispatch", supersegment, carry, need,
                    span=_span_name(supersegment, "dispatch"),
                )
                perf_warmed.add(supersegment)
                stats["dispatches"] += 1
                in_flight += 1
                if in_flight >= dispatch_depth:
                    in_flight = 0
                    counters = poll(carry)
                    completed = int(counters[0])
                    if (
                        int(counters[1]) > drain_mark
                        or int(counters[2]) > drain_mark
                    ):
                        carry = drain(carry)
        else:
            # r5 executor: one blocking counters read per segment
            while completed < n_seeds and stats["dispatches"] < max_segments:
                _xprof.sync_marker("dispatch")
                carry = _dispatch(
                    "segment dispatch", segment, carry,
                    span=_span_name(segment, "dispatch"),
                )
                perf_warmed.add(segment)
                stats["dispatches"] += 1
                counters = poll(carry)
                completed = int(counters[0])
                if (
                    int(counters[1]) > drain_mark
                    or int(counters[2]) > drain_mark
                ):
                    carry = drain(carry)

        counters = poll(carry)
        carry = drain(carry)
        fr_stats = {}
        if self.config.flight_recorder:
            # one extra small transfer, after streaming is over
            from ..runtime.metrics import fr_metrics_dict

            with (
                perf.span("harvest") if perf else contextlib.nullcontext()
            ), _xprof.annotation("harvest"):
                fr_vec = jax.device_get(carry.fr_metrics)
            fr_stats = {"flight_recorder": fr_metrics_dict(fr_vec)}
        cov_stats = {}
        cov_map_np = None
        if self.config.coverage:
            # one extra small transfer (2^14/32 words), after streaming
            # is over: the global map itself, unpacked to the bool[S]
            # form every host-side consumer reads
            from ..runtime.coverage import coverage_dict, unpack_map

            with (
                perf.span("harvest") if perf else contextlib.nullcontext()
            ), _xprof.annotation("harvest"):
                cov_words = jax.device_get(carry.cov_map)
            cov_map_np = unpack_map(
                np.asarray(cov_words),
                self.config.cov_slots_log2,
            )
            cov_stats = {
                "coverage": {
                    **coverage_dict(
                        cov_map_np, self.config.cov_slots_log2,
                        band_bits=self.cov_band_bits,
                    ),
                    "curve": cov_curve,
                }
            }
        # Device-memory high-water accounting: backends that implement
        # memory_stats (TPU, some GPU builds; CPU returns None) report
        # peak/live HBM for the device the stream ran on. Read only
        # under an active PerfRecorder — one host call, zero device
        # work — and surfaced in stats so the timeline's "is this run
        # memory-pressured" question has an answer next to it.
        mem_stats = {}
        if perf is not None:
            try:
                m = jax.local_devices()[0].memory_stats()
            except Exception:  # backend without the API
                m = None
            if m:
                mem_stats = {
                    "device_memory": {
                        k: int(m[k])
                        for k in (
                            "peak_bytes_in_use", "bytes_in_use", "bytes_limit"
                        )
                        if k in m
                    }
                }
                perf.count("device_peak_bytes",
                           int(m.get("peak_bytes_in_use", 0)))
        out = {
            "completed": int(counters[0]),
            "failing": failing,
            "infra": infra,
            "abandoned": abandoned,
            "seeds_consumed": int(counters[3]) - seed_start,
            "stats": {
                **stats,
                "device_segments": int(counters[5]),
                "dispatch_depth": dispatch_depth if pipelined else 1,
                "segments_per_dispatch": segments_per_dispatch if pipelined else 1,
                "donation": bool(donate),
                "pipelined": bool(pipelined),
                **mem_stats,
                **fr_stats,
                **cov_stats,
            },
        }
        if cov_map_np is not None:
            out["coverage_map"] = cov_map_np
        if self.config.provenance:
            out["provenance"] = prov_by_seed
        return out

    def make_runner(self, max_steps: int = 10_000, mesh=None):
        """A jitted `seeds -> BatchResult`, optionally sharded over a mesh
        axis "seeds" (lanes are embarrassingly parallel; XLA propagates
        the sharding through the whole while_loop)."""
        fn = jax.jit(partial(self.run_batch, max_steps=max_steps))
        if mesh is None:
            return fn

        from ..parallel import shard_seeds

        def sharded(seeds):
            return fn(shard_seeds(seeds, mesh))

        return sharded

    def make_stream_runner(
        self,
        batch: int = 1024,
        segment_steps: int = 256,
        max_steps: int = 10_000,
        mesh=None,
        **stream_kwargs,
    ):
        """A configured `(n_seeds, seed_start=0) -> run_stream dict`:
        one place to bind the pipelined-executor knobs (pipelined /
        segments_per_dispatch / dispatch_depth / donate) so the CLI, the
        bench harness, and the sharded + multihost paths all inherit the
        same executor. Pre-warms nothing: the first call compiles."""

        def run(n_seeds: int, seed_start: int = 0):
            return self.run_stream(
                n_seeds,
                batch=batch,
                segment_steps=segment_steps,
                seed_start=seed_start,
                max_steps=max_steps,
                mesh=mesh,
                **stream_kwargs,
            )

        return run

    def run_seed_batch(self, seeds, max_steps: int = 10_000) -> dict:
        """Run an EXPLICIT seed vector — one lane per seed, every lane
        to completion, no streaming refill — and decode the result to
        the `run_stream` dict shape. The guided-search batch runner
        (madsim_tpu/search/guided.py): a guided batch is a *chosen* set
        of seeds (corpus mutants + fresh exploration), which the
        streaming executor's contiguous device-side seed counter cannot
        express; `run_batch` takes any vector, so guidance rides the
        fixed path and the streaming hot path stays byte-for-byte
        untouched when guidance is off.

        Returns {"completed", "failing": [(seed, code)...], "infra",
        "abandoned": [seed...], "seeds_consumed", "stats": {}} plus,
        under the coverage gate, "coverage_map" (bool[S] — the OR of
        all lanes) and "cov_lane_words" (the per-lane packed int32 bit
        maps, which is what parent detection diffs), and under the
        provenance gate "provenance" {seed: violation word}."""
        import numpy as np

        seeds = jnp.asarray(np.asarray(list(seeds), dtype=np.uint32))
        cache = self.__dict__.setdefault("_seed_batch_runners", {})
        fn = cache.get(max_steps)
        if fn is None:
            fn = cache[max_steps] = self.make_runner(max_steps=max_steps)
        res = fn(seeds)
        seeds_np = np.asarray(res.seeds)
        done = np.asarray(res.done)
        failed = np.asarray(res.failed)
        codes = np.asarray(res.fail_code)
        failing, infra = [], []
        # madsim: collective(final-fail-gather, reduce=gather)
        for s, c in zip(seeds_np[failed].tolist(), codes[failed].tolist()):
            (infra if int(c) == OVERFLOW else failing).append(
                (int(s), int(c))
            )
        out = {
            "completed": int(seeds_np.shape[0]),
            "failing": failing,
            "infra": infra,
            # over the step budget without finishing: the fixed path's
            # abandonment criterion, mirroring the streaming harvest
            # madsim: collective(final-abandoned-gather, reduce=gather)
            "abandoned": [int(s) for s in seeds_np[~done & ~failed]],
            "seeds_consumed": int(seeds_np.shape[0]),
            "stats": {},
        }
        if self.config.coverage:
            from ..runtime.coverage import unpack_map

            lane_words = np.asarray(res.cov["map"])
            out["cov_lane_words"] = lane_words
            out["coverage_map"] = unpack_map(
                # madsim: collective(final-cov-or, reduce=or)
                np.bitwise_or.reduce(lane_words, axis=0),
                self.config.cov_slots_log2,
            )
        if self.config.provenance:
            out["provenance"] = {
                int(s): int(p)
                for s, p in zip(
                    # madsim: collective(final-prov-gather, reduce=gather)
                    seeds_np[failed].tolist(),
                    # madsim: collective(final-prov-gather, reduce=gather)
                    np.asarray(res.fail_prov)[failed].tolist(),
                )
            }
        return out

    def failing_seeds(self, result: BatchResult) -> jax.Array:
        """Gather the failing lane seeds back to the host
        (the only device->host traffic besides summaries)."""
        # madsim: collective(final-fail-gather, reduce=gather)
        return result.seeds[result.failed]

    def ring_trace(self, result, lane: int):
        """Decode lane `lane`'s on-device event ring into TraceEvents
        (the last `config.trace_ring` events, oldest first) — immediate
        post-mortem without a replay. Requires `trace_ring > 0`."""
        from .replay import decode_ring

        if not self.config.trace_ring:
            raise ValueError("engine built with trace_ring=0 — no ring recorded")
        ring = result.ring
        lane_ring = jax.tree.map(lambda a: a[lane], ring)
        return decode_ring(lane_ring)

    def digest_checkpoints(self, result, lane: int):
        """Decode lane `lane`'s digest checkpoint ring into a list of
        (step, d0, d1) tuples, oldest first (the last
        `config.fr_digest_ring` checkpoints). Requires
        `flight_recorder=True`."""
        from .audit import decode_checkpoint_ring

        if not self.config.flight_recorder:
            raise ValueError(
                "engine built with flight_recorder=False — no digests recorded"
            )
        lane_fr = jax.tree.map(lambda a: a[lane], result.fr)
        return decode_checkpoint_ring(lane_fr)

    def check_determinism(self, seeds: jax.Array, max_steps: int = 10_000) -> BatchResult:
        """Run the batch twice and require exactly equal results — the
        engine-side analogue of `Runtime.check_determinism`
        (reference: sim/runtime/mod.rs:178-203). Catches machines that
        smuggle nondeterminism past the tracer (e.g. host callbacks or
        trace-time Python state)."""
        from ..errors import NonDeterminism

        # Two independent jit wrappers => two traces, so trace-time Python
        # nondeterminism (mutable counters, random.choice in handlers) is
        # caught, not just per-execution effects.
        r1 = jax.jit(partial(self.run_batch, max_steps=max_steps))(seeds)
        r2 = jax.jit(partial(self.run_batch, max_steps=max_steps))(seeds)
        flat1 = jax.tree_util.tree_flatten_with_path(r1)[0]
        flat2 = jax.tree.leaves(r2)
        mismatches = [
            jax.tree_util.keystr(path)
            for (path, a), b in zip(flat1, flat2)
            if not bool((a == b).all())
        ]
        if mismatches:
            raise NonDeterminism(
                f"TPU engine produced different results for identical seed "
                f"batches; diverging leaves: {mismatches}"
            )
        return r1


def _push(eq, idx, do_push, time, seq, kind, node, src, payload, prov=None):
    """Masked-select write of one event into slot `idx` (no scatters).
    `prov`, when the provenance gate materializes the eq["prov"] plane,
    is the pushed event's lineage word (the sender's word, plus the dup
    bit for duplicate copies)."""
    m = (jnp.arange(eq["valid"].shape[0]) == idx) & do_push

    def upd(arr, value):
        return jnp.where(m, jnp.int32(value), arr)

    out = {
        "time": upd(eq["time"], time),
        "seq": upd(eq["seq"], seq),
        "kind": upd(eq["kind"], kind),
        "node": upd(eq["node"], node),
        "src": upd(eq["src"], src),
        "payload": jnp.where(m[:, None], payload[None, :], eq["payload"]),
        "valid": eq["valid"] | m,
    }
    if "prov" in eq:
        out["prov"] = (
            jnp.where(m, prov, eq["prov"]) if prov is not None else eq["prov"]
        )
    return out
