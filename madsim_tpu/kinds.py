"""The fault-kind vocabulary — single source of truth.

Every host-side mirror of the chaos palette (flight-recorder counter
labels, coverage band names, shrink's ablation table, CLI
`--fault-kinds` parsing) historically kept its own literal copy of this
table, and nothing checked them against each other — the G-rules of
`python -m madsim_tpu lint` grew out of exactly that drift hazard. The
copies now live here once; consumers import (`engine/core.py`,
`runtime/metrics.py`, `ops/coverage.py`, `runtime/coverage.py`,
`engine/shrink.py`, `__main__.py`) and the lint G-pass statically
cross-checks both this file's internal consistency and that every
consumer still binds from it.

Contract notes:

* This module imports NOTHING (the host-side decoders that use it —
  `runtime/metrics.py`, `runtime/coverage.py` — are jax-free by
  contract, and the lint G-pass parses it statically).
* Every table below is a PURE LITERAL: the lint G-pass resolves tuple
  literals and `+`-concatenations only, on purpose — a computed table
  could silently encode the very drift this file exists to prevent.
* `FAULT_KIND_NAMES` order IS the `K_*` index space in
  `engine/core.py` (lint rule G007 asserts `K_<NAME> ==
  FAULT_KIND_NAMES.index(name)`). Append new kinds at the TAIL — the
  indices are baked into recorded fault schedules and golden pins.
"""

from __future__ import annotations

# Scheduled fault kinds, indexed by engine/core.py's K_* constants.
FAULT_KIND_NAMES = (
    "pair", "kill", "dir", "group", "storm", "delay", "pause", "skew",
    "torn", "heal-asym",
)

# Non-scheduled chaos channels (flight-recorder extra counters): the
# Bernoulli duplicate-delivery gate and crash-with-amnesia restarts.
FR_EXTRA_NAMES = ("dup", "amnesia")

# kind name -> FaultPlan field, in K_* index order.
KIND_TO_FLAG = (
    ("pair", "allow_partition"),
    ("kill", "allow_kill"),
    ("dir", "allow_dir_clog"),
    ("group", "allow_group"),
    ("storm", "allow_storm"),
    ("delay", "allow_delay"),
    ("pause", "allow_pause"),
    ("skew", "allow_skew"),
    ("torn", "allow_torn"),
    ("heal-asym", "allow_heal_asym"),
)

# The two chaos gates that are not scheduled kinds but still FaultPlan
# flags (shrink ablates them; strict-restart has its own CLI flag).
EXTRA_FLAGS = (
    ("dup", "allow_dup"),
    ("strict-restart", "strict_restart"),
)

# The `--fault-kinds` CLI vocabulary with its historical print order
# (dup rides between the window kinds and the PR-6 storage kinds —
# shrink repro lines have printed this order since PR-5; keep it).
CLI_KIND_TO_FLAG = (
    ("pair", "allow_partition"),
    ("kill", "allow_kill"),
    ("dir", "allow_dir_clog"),
    ("group", "allow_group"),
    ("storm", "allow_storm"),
    ("delay", "allow_delay"),
    ("pause", "allow_pause"),
    ("skew", "allow_skew"),
    ("dup", "allow_dup"),
    ("torn", "allow_torn"),
    ("heal-asym", "allow_heal_asym"),
)

# Coverage band names (ops/coverage.py slot layout): bands 0/1 are the
# event classes, bands 2..7 the first six scheduled kinds; the 4-bit v2
# layout appends the window kinds, the two synthetic chaos bands, and
# the storage kinds (band 4+k for scheduled kind k >= 8). Band names
# use "_" where kind names use "-" (band names feed prometheus labels).
COV_BAND_NAMES = ("timer", "msg", "pair", "kill", "dir", "group", "storm", "delay")
COV_BAND_NAMES_V2 = COV_BAND_NAMES + (
    "pause", "skew", "dup", "amnesia",
    "torn", "heal_asym", "reserved14", "reserved15",
)

# Runtime conveniences (derived — the lint G-pass ignores these and
# checks the literals above instead).
FLAG_BY_KIND = dict(KIND_TO_FLAG + EXTRA_FLAGS)
KIND_BY_FLAG = {field: name for name, field in KIND_TO_FLAG + EXTRA_FLAGS}


def band_name(kind_name: str) -> str:
    """Coverage-band label for a fault-kind name."""
    return kind_name.replace("-", "_")
