"""Poll-based futures — the bridge between Python coroutines and the executor.

The reference builds on Rust's poll/waker model (`async-task` crate). A
Python coroutine cannot be polled without running it, so this module
defines a small `Pollable` protocol that *primitives* (timers, channels,
join handles, network sockets) implement; arbitrary user coroutines are
driven as tasks and composed via `JoinHandle`, mirroring how Rust user
futures compose over leaf futures.

A suspended `await` point re-polls its pollable on every wake, so
spurious wakeups are harmless (same contract as Rust futures).
Cancellation (node kill / task abort -> `coro.close()`) raises
`GeneratorExit` at the await point; `_Await.__await__` then calls
`pollable.drop()` so registered wakers are deregistered — the Python
equivalent of Rust's `Drop` on a pending future
(reference kill path: madsim/src/sim/task/mod.rs:133-140).
"""

from __future__ import annotations

from typing import Any, Callable, Generator, List, Optional

from . import _context
from .errors import RecvError

__all__ = ["PENDING", "Pollable", "Ready", "await_", "OneShotCell", "yield_now"]

# Native __await__ iterator — resolved lazily on first await so that a
# bare `import madsim_tpu` never triggers the g++ build of hostcore.
_AwaitIter = None
_await_iter_resolved = False


def _resolve_await_iter():
    global _AwaitIter, _await_iter_resolved
    _await_iter_resolved = True
    from . import _native

    mod = _native.get_mod()
    if mod is not None:
        _AwaitIter = mod.AwaitIter
    return _AwaitIter


class _Pending:
    __slots__ = ()

    def __repr__(self) -> str:  # pragma: no cover
        return "PENDING"


PENDING = _Pending()


class Ready:
    __slots__ = ("value",)

    def __init__(self, value: Any = None):
        self.value = value


class Pollable:
    """Protocol: poll(waker) -> Ready(v) | PENDING; drop() deregisters."""

    def poll(self, waker: Callable[[], None]):  # pragma: no cover - interface
        raise NotImplementedError

    def drop(self) -> None:
        pass


class _Await:
    __slots__ = ("pollable",)

    def __init__(self, pollable: Pollable):
        self.pollable = pollable

    def __await__(self) -> Generator[None, None, Any]:
        it = _AwaitIter
        if it is None and not _await_iter_resolved:
            it = _resolve_await_iter()
        if it is not None:
            return it(self.pollable)  # native iterator, same protocol
        return self._await_py()

    def _await_py(self) -> Generator[None, None, Any]:
        p = self.pollable
        try:
            while True:
                task = _context.current_task()
                r = p.poll(task.waker)
                if r is not PENDING:
                    return r.value
                task.pending_on = p
                try:
                    yield
                finally:
                    task.pending_on = None
        finally:
            p.drop()


def await_(pollable: Pollable):
    """Turn a Pollable into an awaitable: ``value = await await_(p)``.

    With the native core, the AwaitIter IS the awaitable (its type has
    am_await = self), skipping the _Await wrapper object per await."""
    it = _AwaitIter
    if it is None and not _await_iter_resolved:
        it = _resolve_await_iter()
    if it is not None:
        return it(pollable)
    return _Await(pollable)


class OneShotCell(Pollable):
    """A set-once cell that wakes registered waiters; building block for
    timers, oneshot channels and join handles."""

    __slots__ = ("_value", "_set", "_closed", "_wakers")

    def __init__(self) -> None:
        self._value: Any = None
        self._set = False
        self._closed = False
        self._wakers: List[Callable[[], None]] = []

    def set(self, value: Any = None) -> bool:
        if self._set or self._closed:
            return False
        self._value = value
        self._set = True
        self._wake_all()
        return True

    def close(self) -> None:
        """Close without a value: waiters see RecvError."""
        if not self._set and not self._closed:
            self._closed = True
            self._wake_all()

    def _wake_all(self) -> None:
        wakers, self._wakers = self._wakers, []
        for w in wakers:
            w()

    def is_set(self) -> bool:
        return self._set

    def peek(self) -> Any:
        return self._value

    def poll(self, waker: Callable[[], None]):
        if self._set:
            return Ready(self._value)
        if self._closed:
            raise RecvError("oneshot closed")
        if waker not in self._wakers:
            self._wakers.append(waker)
        return PENDING

    # Note: no waker cleanup on drop — a stale waker is harmless (the task
    # re-polls and re-parks), whereas removing could drop another waiter's
    # registration. Same policy as naive-timer in the reference.


class _YieldNow(Pollable):
    __slots__ = ("_polled",)

    def __init__(self) -> None:
        self._polled = False

    def poll(self, waker: Callable[[], None]):
        if self._polled:
            return Ready(None)
        self._polled = True
        waker()
        return PENDING


async def yield_now() -> None:
    """Re-enqueue the current task once (reference: tokio `yield_now`)."""
    await await_(_YieldNow())
