"""Consumer-group chaos example: a group survives member crashes.

The rdkafka consumer-group story end to end on the host engine (the
batched twin is models/kafka_group.py): one broker, one producer
publishing N records, and a group of consumers that the supervisor
randomly kills and restarts. Rebalancing hands dead members' partitions
to survivors, committed offsets make every hand-off lossless, and the
run asserts at-least-once delivery of every record. Same seed, same
output, every time.

Run:  python examples/group_consumers.py [seed]
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import madsim_tpu
from madsim_tpu import time as sim_time
from madsim_tpu.runtime import Handle, Runtime
from madsim_tpu.services import kafka

TOPIC = "events"
PARTITIONS = 3
RECORDS = 30
GROUP = "workers"


async def consumer_proc(name: str, seen: set) -> None:
    cfg = kafka.ClientConfig(
        {
            "bootstrap.servers": "10.8.0.1:9092",
            "group.id": GROUP,
            "session.timeout.ms": "300",
            "enable.auto.commit": "false",
        }
    )
    c = await cfg.create_base_consumer()
    await c.subscribe([TOPIC])
    while True:
        msg = await c.poll(timeout=0.5)
        if msg is None:
            continue
        seen.add(int(msg.payload.decode()))
        try:
            await c.commit()
        except kafka.KafkaError:
            # fenced commit: a rebalance bumped the generation while this
            # record was in flight (we were partitioned/slow). The record
            # stays uncommitted — the new owner redelivers it, which is
            # exactly the at-least-once contract. Next poll rejoins.
            continue


async def main_async() -> tuple:
    handle = Handle.current()
    rng = madsim_tpu.rand.thread_rng()

    async def serve():
        await kafka.SimBroker().serve("0.0.0.0:9092")

    handle.create_node().name("broker").ip("10.8.0.1").init(serve).build()
    await sim_time.sleep(0.2)

    # producer: publish RECORDS numbered records round-robin
    prod_node = handle.create_node().name("producer").ip("10.8.0.2").build()

    async def produce():
        cfg = kafka.ClientConfig({"bootstrap.servers": "10.8.0.1:9092"})
        admin = await cfg.create_admin()
        await admin.create_topics([kafka.NewTopic(TOPIC, PARTITIONS)])
        p = await cfg.create_future_producer()
        for i in range(RECORDS):
            await p.send_and_wait(
                kafka.FutureRecord(TOPIC, payload=str(i).encode(), partition=i % PARTITIONS)
            )
            await sim_time.sleep(0.05)

    prod_node.spawn(produce())

    # the group: 3 members, restarted with fresh state on every kill
    seen: set = set()
    members = []
    for i in range(3):
        node = (
            handle.create_node()
            .name(f"worker-{i}")
            .ip(f"10.8.0.{10 + i}")
            .init(lambda i=i: consumer_proc(f"worker-{i}", seen))
            .build()
        )
        members.append(node)

    # chaos: random member kill/restart while the stream flows
    for _ in range(4):
        await sim_time.sleep(0.3 + rng.random() * 0.4)
        victim = rng.choice(members)
        handle.kill(victim.id)
        await sim_time.sleep(0.2 + rng.random() * 0.3)
        handle.restart(victim.id)

    # drain: wait until the group has consumed everything
    deadline = sim_time.now() + 20.0
    while len(seen) < RECORDS and sim_time.now() < deadline:
        await sim_time.sleep(0.25)
    return tuple(sorted(seen))


def main() -> int:
    seed = int(sys.argv[1]) if len(sys.argv) > 1 else 7
    out = Runtime(seed=seed).block_on(main_async())
    ok = out == tuple(range(RECORDS))
    print(
        f"seed {seed}: group consumed {len(out)}/{RECORDS} records "
        f"under member crashes -> {'at-least-once holds' if ok else 'LOST RECORDS: ' + str(out)}"
    )
    # determinism: the same seed reproduces the same consumption set
    again = Runtime(seed=seed).block_on(main_async())
    assert again == out, "nondeterministic run!"
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
