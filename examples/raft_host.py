"""MadRaft-style Raft on the HOST engine — free-form async authoring.

The reference's flagship use case is MadRaft: students implement Raft
against madsim's tokio-like API and the harness explores seeds
(reference: BASELINE.json workloads; tonic-example shows the API shape).
This example is that workload on madsim_tpu's host engine: leader
election + log replication written as ordinary async tasks over the
simulated fabric, with elections surviving partitions, and every seed
bit-reproducible.

Run:  python examples/raft_host.py [num_seeds]
Also imported by tests/test_examples.py.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import madsim_tpu
from madsim_tpu import time as sim_time
from madsim_tpu.net import Endpoint, NetSim, Request
from madsim_tpu.plugin import simulator
from madsim_tpu.runtime import Handle, Runtime
from madsim_tpu.task import spawn

FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"


class RequestVote(Request):
    def __init__(self, term, candidate, last_idx, last_term):
        self.term = term
        self.candidate = candidate
        self.last_idx = last_idx
        self.last_term = last_term


class AppendEntries(Request):
    def __init__(self, term, leader, prev_idx, prev_term, entries, commit):
        self.term = term
        self.leader = leader
        self.prev_idx = prev_idx
        self.prev_term = prev_term
        self.entries = entries  # list of (term, value)
        self.commit = commit


class RaftNode:
    """One Raft peer as ordinary async code."""

    def __init__(self, me: int, peers: list, state: dict):
        self.me = me
        self.peers = peers  # ip:port of every node (incl. self)
        self.state = state  # shared dict: harness observations + stable storage
        # stable storage survives kill/restart (Raft §5.1); the node re-reads
        # it on every (re)boot, like the reference's fs-backed persistence
        stable = state.setdefault("stable", {}).setdefault(
            me, {"term": 0, "voted_for": None, "log": [(0, None)]}
        )
        self.term = stable["term"]
        self.voted_for = stable["voted_for"]
        self.log = list(stable["log"])
        self.commit = 0
        self.role = FOLLOWER
        self.election_deadline = 0.0
        self.next_idx = {p: len(self.log) for p in range(len(peers))}

    def persist(self):
        self.state["stable"][self.me] = {
            "term": self.term,
            "voted_for": self.voted_for,
            "log": list(self.log),
        }

    def rng(self):
        return madsim_tpu.rand.thread_rng()

    def reset_election_timer(self):
        self.election_deadline = sim_time.now() + 0.15 + self.rng().random() * 0.15

    def become_follower(self, term):
        if term > self.term:
            self.term = term
            self.voted_for = None
            self.persist()
        self.role = FOLLOWER

    async def run(self):
        ep = await Endpoint.bind(f"0.0.0.0:{5000 + self.me}")
        ep.add_rpc_handler(RequestVote, self.on_request_vote)
        ep.add_rpc_handler(AppendEntries, self.on_append_entries)
        self.reset_election_timer()
        spawn(self.ticker(ep))
        await sim_time.sleep(1e9)

    async def ticker(self, ep):
        """Event-driven: a leader beats on a fixed cadence; everyone else
        sleeps exactly until the election deadline (handlers move the
        deadline; waking at a stale one just re-sleeps) — no 20 ms
        polling, ~7x fewer timer events per simulated second."""
        while True:
            if self.role == LEADER:
                await self.heartbeat(ep)
                await sim_time.sleep(0.05)
                continue
            delta = self.election_deadline - sim_time.now()
            if delta > 1e-6:  # float dust would arm a zero-delay timer spin
                await sim_time.sleep(delta)
                continue
            await self.campaign(ep)

    async def campaign(self, ep):
        self.term += 1
        self.role = CANDIDATE
        self.voted_for = self.me
        self.persist()
        self.reset_election_timer()
        votes = 1
        term = self.term
        last_idx = len(self.log) - 1
        req = RequestVote(term, self.me, last_idx, self.log[last_idx][0])
        for peer_id, addr in enumerate(self.peers):
            if peer_id == self.me:
                continue
            try:
                rsp = await ep.call_timeout(addr, req, 0.05)
            except TimeoutError:
                continue
            if rsp["term"] > self.term:
                self.become_follower(rsp["term"])
                return
            if rsp["granted"]:
                votes += 1
        if self.role == CANDIDATE and self.term == term and votes > len(self.peers) // 2:
            self.role = LEADER
            self.next_idx = {p: len(self.log) for p in range(len(self.peers))}
            self.state.setdefault("leaders_by_term", {}).setdefault(term, set()).add(self.me)
            # client load model: the leader appends an entry per term
            self.log.append((self.term, f"op-t{self.term}"))
            self.persist()

    async def heartbeat(self, ep):
        acks = 1
        term = self.term
        for peer_id, addr in enumerate(self.peers):
            if peer_id == self.me:
                continue
            # per-peer nextIndex with backoff, so lagging/restarted
            # followers catch up from wherever their log diverged
            prev = min(self.next_idx.get(peer_id, len(self.log)), len(self.log)) - 1
            prev = max(prev, 0)
            req = AppendEntries(
                self.term, self.me, prev, self.log[prev][0], self.log[prev + 1 :], self.commit
            )
            try:
                rsp = await ep.call_timeout(addr, req, 0.05)
            except TimeoutError:
                continue
            if rsp["term"] > self.term:
                self.become_follower(rsp["term"])
                return
            if rsp["ok"]:
                acks += 1
                self.next_idx[peer_id] = len(self.log)
            else:
                self.next_idx[peer_id] = max(1, self.next_idx.get(peer_id, 1) - 1)
        # an on_append_entries during the awaited ack loop can depose us;
        # a deposed/newer-term node must not record these acks as a commit
        if self.role != LEADER or self.term != term:
            return
        if acks > len(self.peers) // 2:
            self.commit = len(self.log) - 1
            self.state["max_commit"] = max(self.state.get("max_commit", 0), self.commit)
            self.state.setdefault("commits", {})[self.me] = self.commit

    async def on_request_vote(self, req: RequestVote, data):
        if req.term > self.term:
            self.become_follower(req.term)
        my_last = len(self.log) - 1
        log_ok = (req.last_term, req.last_idx) >= (self.log[my_last][0], my_last)
        granted = (
            req.term == self.term
            and self.voted_for in (None, req.candidate)
            and log_ok
        )
        if granted:
            self.voted_for = req.candidate
            self.persist()
            self.reset_election_timer()
        return {"term": self.term, "granted": granted}

    async def on_append_entries(self, req: AppendEntries, data):
        if req.term < self.term:
            return {"term": self.term, "ok": False}
        self.become_follower(req.term)
        self.reset_election_timer()
        if req.prev_idx >= len(self.log) or self.log[req.prev_idx][0] != req.prev_term:
            return {"term": self.term, "ok": False}
        if req.entries:
            self.log = self.log[: req.prev_idx + 1] + list(req.entries)
            self.persist()
        self.commit = min(req.commit, len(self.log) - 1)
        self.state.setdefault("commits", {})[self.me] = max(
            self.state.setdefault("commits", {}).get(self.me, 0), self.commit
        )
        return {"term": self.term, "ok": True}


async def scenario(n=5, horizon=3.0):
    handle = Handle.current()
    net = simulator(NetSim)
    rng = madsim_tpu.rand.thread_rng()
    state: dict = {}
    peers = [f"10.2.0.{i+1}:{5000+i}" for i in range(n)]
    nodes = []
    for i in range(n):
        node = (
            handle.create_node()
            .name(f"raft-{i}")
            .ip(f"10.2.0.{i+1}")
            .init(lambda i=i: RaftNode(i, peers, state).run())
            .build()
        )
        nodes.append(node)

    # chaos: a random partition + a random kill/restart inside the horizon
    async def chaos():
        await sim_time.sleep(rng.random() * horizon / 2)
        a = rng.gen_range(0, n)
        b = (a + 1 + rng.gen_range(0, n - 1)) % n
        net.partition([nodes[a].id], [nodes[b].id])
        await sim_time.sleep(rng.random() * horizon / 4)
        net.heal([nodes[a].id], [nodes[b].id])
        victim = rng.gen_range(0, n)
        handle.kill(nodes[victim].id)
        await sim_time.sleep(0.2)
        handle.restart(nodes[victim].id)

    spawn(chaos())
    await sim_time.sleep(horizon)

    # safety: at most one leader per term
    for term, leaders in state.get("leaders_by_term", {}).items():
        assert len(leaders) == 1, f"election safety violated in term {term}: {leaders}"
    return {
        "terms_with_leader": len(state.get("leaders_by_term", {})),
        "max_commit": state.get("max_commit", 0),
    }


def main():
    num_seeds = int(sys.argv[1]) if len(sys.argv) > 1 else 20
    t0 = time.perf_counter()
    elected = 0
    for seed in range(num_seeds):
        result = Runtime(seed=seed).block_on(scenario())
        elected += 1 if result["terms_with_leader"] > 0 else 0
    dt = time.perf_counter() - t0
    print(
        f"{num_seeds} seeds in {dt:.2f}s -> {num_seeds / dt:.1f} seeds/sec (host engine); "
        f"{elected}/{num_seeds} seeds elected a leader"
    )


if __name__ == "__main__":
    main()
