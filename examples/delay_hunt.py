"""Timeout-mishandling hunt with the delay fault kind — the round-5
chaos vocabulary in action.

Most fault vocabularies (loss, partitions, kills) make messages VANISH.
The `delay` kind makes them LATE: during a timed window, ~10% of sends
take +1-5 virtual seconds (the host fabric's buggify numbers,
reference sim/net/mod.rs:287-296). Late-but-delivered is the only way
to reach a whole class of real bugs: code that treats a timeout as
failure while the request is still in flight.

The demo machine is a deadline-RPC client against a token-dedup server
(models/etcd_mvcc.py PREMATURE_GIVEUP): each op is sent once with a
300 ms deadline; on expiry the client reports failure to the
application and moves on. The bug: the abandoned request can still
land — a write the application compensated for becomes visible
(ABANDONED_WRITE, code 206). Loss destroys the in-flight copy and
clogs/kills block it at the link, so every other vocabulary finds
NOTHING; only delay reaches it (measured: 21.6% vs 0.0% at 384 seeds
per vocabulary).

Run:  python examples/delay_hunt.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from madsim_tpu._backend_watchdog import ensure_live_backend

ensure_live_backend()  # falls back to CPU if the accelerator is wedged

import jax.numpy as jnp

from madsim_tpu.engine import Engine, EngineConfig, FaultPlan, replay, shrink
from madsim_tpu.models.etcd_mvcc import ABANDONED_WRITE, EtcdMvccMachine


class PrematureGiveup(EtcdMvccMachine):
    PREMATURE_GIVEUP = True  # the CLI ships this as demo-giveup-mvcc


def main() -> None:
    def engine(**fault_kinds):
        kinds = dict(allow_partition=False, allow_kill=False)
        kinds.update(fault_kinds)
        return Engine(
            PrematureGiveup(num_nodes=4),
            EngineConfig(
                horizon_us=8_000_000,
                queue_capacity=48,
                faults=FaultPlan(
                    n_faults=3, t_max_us=3_000_000,
                    dur_min_us=200_000, dur_max_us=800_000, **kinds,
                ),
            ),
        )

    seeds = jnp.arange(256, dtype=jnp.uint32)

    # 1. the vanishing vocabularies find nothing…
    for name, kinds in [
        ("loss storms", dict(allow_storm=True)),
        ("partitions + kills", dict(allow_partition=True, allow_kill=True)),
    ]:
        res = engine(**kinds).make_runner(max_steps=3000)(seeds)
        n = int(res.failed.sum())
        print(f"{name:>20}: {n}/256 seeds flagged")

    # 2. …the delay vocabulary finds the bug
    eng = engine(allow_delay=True)
    res = eng.make_runner(max_steps=3000)(seeds)
    failing = [int(s) for s in eng.failing_seeds(res).tolist()]
    codes = {int(c) for c in res.fail_code.tolist() if c}
    print(f"{'delay spikes':>20}: {len(failing)}/256 seeds flagged, codes {codes}")
    assert codes == {ABANDONED_WRITE}

    # 3. bit-identical replay of one find, then shrink it to a minimal repro
    seed = failing[0]
    rp = replay(eng, seed, max_steps=3000, trace=False)
    assert rp.failed and rp.fail_code == ABANDONED_WRITE
    sr = shrink(eng, seed, max_steps=3000)
    print(f"{'replay + shrink':>20}: {sr.summary()}")
    # the minimal config still carries delay windows — the late delivery
    # IS the bug's trigger, so shrink cannot remove every fault
    assert sr.shrunk.faults.n_faults >= 1


if __name__ == "__main__":
    main()
