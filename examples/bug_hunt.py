"""End-to-end bug hunt: inject a Raft voting bug, find it at scale on the
engine, then debug it with bit-identical replay and trace diffing.

This is the framework's signature workflow — the reason DST exists:

  1. run thousands of seeds with chaos (partitions, kills, latency)
  2. the on-device ElectionSafety invariant flags failing seeds
  3. replay one failing seed on CPU, bit-identically, with a full trace
  4. diff it against a passing neighbor to find where schedules fork

Run:  python examples/bug_hunt.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from madsim_tpu._backend_watchdog import ensure_live_backend

ensure_live_backend()  # falls back to CPU if the accelerator is wedged

import jax.numpy as jnp

from madsim_tpu.engine import Engine, EngineConfig, FaultPlan, replay, replay_diff
from madsim_tpu.engine.machine import send_if
from madsim_tpu.models import raft as R
from madsim_tpu.models.raft import RaftMachine


class DoubleVoteRaft(RaftMachine):
    """Raft with a classic bug: granting votes without checking whether we
    already voted this term (drop the §5.2 single-vote rule). With normal
    randomized election timeouts the bug only fires when two candidacies
    happen to race — a needle-in-the-haystack for the explorer to find."""

    def on_message(self, nodes, node, src, payload, now_us, rand_u32):
        nodes2, outbox = super().on_message(nodes, node, src, payload, now_us, rand_u32)
        grant_anyway = payload[0] == R.M_RV  # BUG: unconditional grant
        vote = self._pay(R.M_VOTE, jnp.maximum(payload[1], nodes.term[node]), 1)
        return nodes2, send_if(outbox, 0, grant_anyway, src, vote)


def main() -> None:
    eng = Engine(
        DoubleVoteRaft(num_nodes=5, log_capacity=8),
        EngineConfig(
            horizon_us=3_000_000,
            queue_capacity=96,
            faults=FaultPlan(n_faults=1, t_max_us=2_000_000),
        ),
    )

    print("=== 1. explore: stream seeds through the engine ===")
    out = eng.run_stream(2048, batch=512, segment_steps=192)
    by_code: dict = {}
    for _s, c in out["failing"]:
        by_code[c] = by_code.get(c, 0) + 1
    codes = {R.ELECTION_SAFETY: "ElectionSafety", R.LOG_MATCHING: "LogMatching"}
    summary = ", ".join(f"{n} x {codes.get(c, c)}" for c, n in sorted(by_code.items()))
    print(f"ran {out['completed']} simulations; "
          f"{len(out['failing'])} invariant violations ({summary or 'none'})")
    if not out["failing"]:
        print("no violations found — increase seeds")
        return

    seed, code = out["failing"][0]
    print(f"\n=== 2. replay failing seed {seed} (code {code}) bit-identically ===")
    rp = replay(eng, seed, max_steps=3000)
    print(f"replay: failed={rp.failed} code={rp.fail_code}, "
          f"{len(rp.trace)} events; last 3 before the violation:")
    for ev in rp.trace[-3:]:
        print("   ", ev)

    # a verified-passing neighbor: completed, not failing, not abandoned,
    # not an infra artifact (queue overflow), and confirmed by replay
    # (in-flight-at-exit seeds don't count)
    excluded = (
        {s for s, _ in out["failing"]}
        | {s for s, _ in out["infra"]}
        | set(out["abandoned"])
    )
    passing = None
    for cand in range(out["seeds_consumed"]):
        if cand in excluded:
            continue
        if not replay(eng, cand, max_steps=3000, trace=False).failed:
            passing = cand
            break
    if passing is None:
        print("\n(no passing seed in the explored range — every seed trips "
              "the bug; nothing to diff)")
        return
    print(f"\n=== 3. diff failing seed {seed} vs passing seed {passing} ===")
    replay_diff(eng, seed, passing, max_steps=3000, context=1)


if __name__ == "__main__":
    main()
