"""A service pipeline under chaos: gRPC + etcd + kafka + S3, one seed.

Shows the host engine's ecosystem surface in one place (the reference's
tonic-example + etcd/rdkafka integration tests rolled together). Every
run with the same seed prints the same thing, byte for byte.

Run:  python examples/chaos_pipeline.py [seed]
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from madsim_tpu import grpc, time as sim_time
from madsim_tpu.runtime import Handle, Runtime
from madsim_tpu.services import etcd, kafka, s3


@grpc.service("pipeline.Ingest")
class Ingest:
    def __init__(self, producer):
        self.producer = producer

    @grpc.unary
    async def push(self, request):
        part, off = await self.producer.send_and_wait(
            kafka.FutureRecord("events", payload=request.into_inner().encode())
        )
        return grpc.Response(f"events[{part}]@{off}")


async def scenario():
    handle = Handle.current()
    handle.create_node().name("etcd").ip("10.0.8.1").init(
        lambda: etcd.SimServer().serve("0.0.0.0:2379")
    ).build()
    handle.create_node().name("kafka").ip("10.0.8.2").init(
        lambda: kafka.SimBroker().serve("0.0.0.0:9092")
    ).build()
    handle.create_node().name("s3").ip("10.0.8.3").init(
        lambda: s3.SimServer().serve("0.0.0.0:9000")
    ).build()
    await sim_time.sleep(0.3)

    async def ingest_app():
        cfg = kafka.ClientConfig({"bootstrap.servers": "10.0.8.2:9092"})
        await (await cfg.create_admin()).create_topics([kafka.NewTopic("events", 1)])
        producer = await cfg.create_future_producer()
        await grpc.Server.builder().add_service(Ingest(producer)).serve("0.0.0.0:50051")

    app = handle.create_node().name("ingest").ip("10.0.8.10").init(ingest_app).build()
    await sim_time.sleep(0.3)

    async def client():
        # coordination: become the pipeline leader via etcd election
        ecli = await etcd.Client.connect("10.0.8.1:2379")
        lease = await ecli.lease_grant(30)
        await ecli.campaign("pipeline", "worker-1", lease["id"])

        ch = await grpc.connect("http://10.0.8.10:50051")
        placed = [await ch.unary("/pipeline.Ingest/Push", f"evt-{i}") for i in range(3)]

        # chaos: the ingest service crashes and recovers
        handle.kill(app.id)
        await sim_time.sleep(0.2)
        handle.restart(app.id)
        await sim_time.sleep(0.4)
        ch2 = await grpc.connect("http://10.0.8.10:50051")
        placed.append(await ch2.unary("/pipeline.Ingest/Push", "evt-after-crash"))

        # drain the log and snapshot it to S3
        consumer = await kafka.ClientConfig(
            {"bootstrap.servers": "10.0.8.2:9092"}
        ).create_stream_consumer()
        await consumer.subscribe(["events"])
        events = [(await consumer.recv()).payload.decode() for _ in range(4)]
        scli = s3.Client.from_conf(s3.Config(endpoint_url="http://10.0.8.3:9000"))
        await scli.create_bucket().bucket("snapshots").send()
        await scli.put_object().bucket("snapshots").key("events").body(
            ",".join(events).encode()
        ).send()
        snap = await scli.get_object().bucket("snapshots").key("events").send()
        return placed, snap["body"].decode()

    worker = handle.create_node().name("worker").ip("10.0.8.20").build()
    return await worker.spawn(client())


def main():
    seed = int(sys.argv[1]) if len(sys.argv) > 1 else 42
    placed, snapshot = Runtime(seed=seed).block_on(scenario())
    print(f"seed {seed}:")
    print(f"  placed:   {placed}")
    print(f"  snapshot: {snapshot}")


if __name__ == "__main__":
    main()
