"""One etcd app, two worlds — the dual-build story end to end.

`workload(client)` below is ordinary application code against the
`services.etcd.Client` surface. It runs UNMODIFIED in both modes
(reference: madsim-etcd-client/src/lib.rs:1-8 re-exports the real client
under `cfg(not(madsim))` so app code is identical in test and prod):

  sim (default):  python examples/etcd_dual.py
      -> deterministic simulation; the server is a sim node, seeds
         reproduce, chaos applies

  real:           MADSIM_TPU_MODE=real python -m madsim_tpu serve --service etcd --addr 127.0.0.1:23790 &
                  MADSIM_TPU_MODE=real python examples/etcd_dual.py 127.0.0.1:23790
      -> the same client code over real asyncio TCP to a real server

  real + genuine etcd:
                  MADSIM_TPU_MODE=real python examples/etcd_dual.py <etcd-host>:2379
      -> Client.connect probes the endpoint with an etcd v3 Status rpc;
         a genuine etcd (or `madsim_tpu serve --service etcd --grpc`)
         answers, and every call goes over the real etcd wire protocol
         (services/etcd/real_client.py — the analogue of the reference
         re-exporting etcd_client in non-sim builds, lib.rs:5-6).
         Unreachable/non-etcd endpoints fall back to the pickle
         sim-protocol server above.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from madsim_tpu import dual
from madsim_tpu.services.etcd import Client, Compare, SimServer, Txn, TxnOp


async def workload(client: Client) -> dict:
    """App logic — identical bytes in sim and production."""
    await client.put("config/region", "us-east")
    await client.put("config/replicas", "3")
    got = await client.get("config/region")
    assert got["kvs"][0].value == b"us-east", got

    # prefix scan
    pfx = await client.get("config/", prefix=True)
    keys = sorted(kv.key.decode() for kv in pfx["kvs"])

    # lease + attached key + keepalive
    lease = await client.lease_grant(60)
    await client.put("live/worker-1", "up", lease=lease["id"])
    await client.lease_keep_alive(lease["id"])

    # CAS via txn
    txn = (
        Txn()
        .when([Compare.value("config/replicas", "=", "3")])
        .and_then([TxnOp.put("config/replicas", "5")])
        .or_else([TxnOp.put("config/conflict", "1")])
    )
    txn_rsp = await client.txn(txn)
    after = await client.get("config/replicas")

    return {
        "keys": keys,
        "txn_succeeded": txn_rsp["succeeded"],
        "replicas": after["kvs"][0].value.decode(),
        "lease": lease["id"] > 0,
    }


def main() -> None:
    if dual.IS_SIM:
        from madsim_tpu.runtime import Handle, Runtime

        async def scenario():
            handle = Handle.current()

            async def server():
                await SimServer().serve("0.0.0.0:2379")

            handle.create_node().name("etcd").ip("10.5.0.1").init(server).build()
            client_node = handle.create_node().name("app").ip("10.5.0.2").build()

            async def app():
                client = await Client.connect("10.5.0.1:2379")
                return await workload(client)

            return await client_node.spawn(app())

        result = Runtime(seed=1).block_on(scenario())
        print(f"[sim] {result}")
    else:
        import asyncio

        addr = sys.argv[1] if len(sys.argv) > 1 else "127.0.0.1:23790"

        async def app():
            client = await Client.connect(addr)
            return await workload(client)

        result = asyncio.run(app())
        print(f"[real] {result}")


if __name__ == "__main__":
    main()
